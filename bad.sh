exit 3
