"""Data-parallel training two ways (reference iterative reduce):
1. On-mesh per-step gradient averaging (shard_map + pmean over ICI) —
   the TPU-native path; runs on however many devices exist.
2. The coarse epoch-wave parameter-averaging runtime (master/worker
   choreography with heartbeats/eviction) embedded in one process.
"""
import jax
import numpy as np

from deeplearning4j_tpu.config import NeuralNetConfiguration
from deeplearning4j_tpu.datasets import ListDataSetIterator
from deeplearning4j_tpu.datasets.api import DataSet
from deeplearning4j_tpu.datasets.iris import load_iris
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import DataParallelTrainer
from deeplearning4j_tpu.scaleout import (CollectionJobIterator,
                                         DistributedRuntime,
                                         NeuralNetWorkPerformer)

conf = (NeuralNetConfiguration.builder()
        .lr(0.1).n_in(4).activation_function("tanh")
        .optimization_algo("iteration_gradient_descent")
        .num_iterations(5).use_adagrad(False)
        .list(2).hidden_layer_sizes([8])
        .override(1, layer="output", loss_function="mcxent",
                  activation_function="softmax", n_out=3)
        .pretrain(False).build())

x, y = load_iris()
x, y = np.asarray(x), np.asarray(y)

# -- 1: on-mesh DP (per-step pmean all-reduce) ---------------------------
n_dev = len(jax.devices())
net = MultiLayerNetwork(conf)
trainer = DataParallelTrainer(net)  # mesh defaults to all local devices
usable = len(x) // (n_dev * 2) * (n_dev * 2)
it = ListDataSetIterator(DataSet(x[:usable], y[:usable]),
                         batch_size=usable // 2)
trainer.fit(it, epochs=20)
print(f"on-mesh DP over {n_dev} device(s): score {net.score(x, y):.4f}")

# -- 2: epoch-wave parameter averaging (scaleout runtime) ----------------
rng = np.random.RandomState(0)
batches = [DataSet(x[i], y[i]) for i in
           (rng.choice(len(x), 32) for _ in range(8))]
rt = DistributedRuntime(
    CollectionJobIterator(batches),
    lambda: NeuralNetWorkPerformer(conf.to_json(), epochs=1),
    n_workers=2)
final = rt.run(timeout=120)
print(f"epoch-wave averaging: {rt.waves} waves, params {final.shape}")
