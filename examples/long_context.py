"""Long-context attention, three ways (all beyond the 2015 reference):
1. flash_attention — Pallas TPU kernel (blockwise/interpret off-TPU)
2. blockwise_attention — pure-JAX O(T) memory reference
3. ring_attention — sequence parallelism over a device mesh (dp x sp)
"""
import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.attention.blockwise import blockwise_attention
from deeplearning4j_tpu.attention.flash_pallas import flash_attention
from deeplearning4j_tpu.attention.ring import ring_attention
from deeplearning4j_tpu.parallel import make_mesh

B, H, S, D = 2, 4, 1024, 64
key = jax.random.PRNGKey(0)
kq, kk, kv = jax.random.split(key, 3)
q = jax.random.normal(kq, (B, H, S, D), jnp.bfloat16)
k = jax.random.normal(kk, (B, H, S, D), jnp.bfloat16)
v = jax.random.normal(kv, (B, H, S, D), jnp.bfloat16)

on_tpu = jax.devices()[0].platform == "tpu"
out_flash = flash_attention(q, k, v, causal=True, interpret=not on_tpu)
out_block = blockwise_attention(q, k, v, causal=True)
err = float(jnp.max(jnp.abs(out_flash.astype(jnp.float32)
                            - out_block.astype(jnp.float32))))
print(f"flash vs blockwise on {jax.devices()[0].platform}: max err {err:.4f}")

n = len(jax.devices())
if n >= 2 and S % n == 0:
    # sequence-sharded: each device holds S/n of the sequence; K/V rotate
    # via ppermute so every query attends to every key
    mesh = make_mesh({"sp": n})
    q3, k3, v3 = (a.reshape(B * H, S, D) for a in (q, k, v))
    out_ring = ring_attention(q3, k3, v3, mesh, axis="sp", causal=True)
    err = float(jnp.max(jnp.abs(out_ring.reshape(B, H, S, D).astype(jnp.float32)
                                - out_block.astype(jnp.float32))))
    print(f"ring over {n} devices: max err {err:.4f}")
else:
    print(f"ring attention needs >1 device (have {n}); try "
          "XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu")
