"""DBN: layer-wise RBM pretraining + supervised finetune (reference
MultiLayerNetwork.pretrain + finetune over CD-1 RBMs).

DL4J_TPU_EXAMPLE_FAST=1 shrinks the run (CI smoke, tests/test_examples.py)."""
import os

import numpy as np

from deeplearning4j_tpu.config import NeuralNetConfiguration
from deeplearning4j_tpu.datasets.mnist import synthetic_mnist
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

FAST = os.environ.get("DL4J_TPU_EXAMPLE_FAST") == "1"

conf = (NeuralNetConfiguration.builder()
        .lr(2.0)  # adagrad master step; update is lr/batch-scaled (reference semantics)
        .n_in(784).activation_function("sigmoid")
        .optimization_algo("iteration_gradient_descent")
        .num_iterations(8 if FAST else 40).batch_size(512)
        .list(3).hidden_layer_sizes([256, 128])
        .override(0, layer="rbm", k=1)
        .override(1, layer="rbm", k=1)
        .override(2, layer="output", loss_function="mcxent",
                  activation_function="softmax", n_out=10)
        .pretrain(True)  # unsupervised CD-1 pass before finetune
        .build())

net = MultiLayerNetwork(conf)
x, y = synthetic_mnist(4096)
before = net.score(x, y)
net.fit(x, y)
print(f"score: {before:.4f} -> {net.score(x, y):.4f}")
print("accuracy:", float((net.predict(x) == np.argmax(np.asarray(y), 1)).mean()))
