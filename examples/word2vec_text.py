"""Word2Vec skip-gram + nearest neighbors + the moving-window
classification bridge (reference Word2Vec + Word2VecDataSetIterator)."""
from deeplearning4j_tpu.nlp import (LabelAwareSentenceIterator, Word2Vec,
                                    Word2VecDataSetIterator)

corpus = ["the cat sat on the mat", "the dog sat on the rug",
          "the king wears the crown", "the queen wears the crown"] * 50

w2v = Word2Vec(corpus, layer_size=64, window=3, min_word_frequency=2,
               negative=5, iterations=20, seed=7).fit()
print("nearest to 'king':", w2v.words_nearest("king", n=3))

it = Word2VecDataSetIterator(
    w2v,
    LabelAwareSentenceIterator([("animals", "the cat sat on the mat"),
                                ("royalty", "the king wears the crown")]),
    labels=["animals", "royalty"], batch=16)
ds = it.next()
print("window batch:", ds.features.shape, "->", ds.labels.shape)
