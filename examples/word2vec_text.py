"""Word2Vec skip-gram + nearest neighbors + the moving-window
classification bridge (reference Word2Vec + Word2VecDataSetIterator)."""
from deeplearning4j_tpu.nlp import (LabelAwareSentenceIterator, Word2Vec,
                                    Word2VecDataSetIterator)

corpus = ["the cat sat on the mat", "the dog sat on the rug",
          "the cat and the dog play in the yard",
          "the king wears the crown in the castle",
          "the queen wears the crown in the castle",
          "a royal king and a royal queen sit on the throne"] * 40

w2v = Word2Vec(corpus, layer_size=32, window=3, min_word_frequency=3,
               learning_rate=0.1, negative=5, batch_pairs=256,
               iterations=40, seed=7).fit()
print("nearest to 'king':", w2v.words_nearest("king", n=3))
print("king~queen:", round(w2v.similarity("king", "queen"), 3),
      " king~cat:", round(w2v.similarity("king", "cat"), 3))

it = Word2VecDataSetIterator(
    w2v,
    LabelAwareSentenceIterator([("animals", "the cat sat on the mat"),
                                ("royalty", "the king wears the crown")]),
    labels=["animals", "royalty"], batch=16)
ds = it.next()
print("window batch:", ds.features.shape, "->", ds.labels.shape)
