"""Moving-window classification with native annotators, end-to-end.

The reference assembled this pipeline from UIMA glue: ContextLabel span
markup (+ ContextLabelRetriever), PoStagger (OpenNLP maxent behind a
UIMA AnalysisEngine), and SWN3 sentiment scoring. Here the same
capabilities are native framework pieces: `string_with_labels` strips
the span markup, `HmmPosTagger` (trained closed-form, decoded with the
shared Viterbi scan) tags tokens, `SentimentLexicon` scores windows,
and `annotate_windows` fuses them into labeled windows whose word2vec
feature rows train a MultiLayerNetwork classifier.
"""
import numpy as np

from deeplearning4j_tpu.config import NeuralNetConfiguration
from deeplearning4j_tpu.eval import Evaluation
from deeplearning4j_tpu.nlp import Word2Vec
from deeplearning4j_tpu.nlp.pos import HmmPosTagger
from deeplearning4j_tpu.nlp.sentiment import SentimentLexicon
from deeplearning4j_tpu.nlp.windows import (annotate_windows,
                                            string_with_labels,
                                            window_as_vector)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

# 1. span-labeled corpus (ContextLabel markup): the task is labeling
#    each window as describing an ANIMAL or ROYAL context
MARKED = [
    "the <ANIMAL> cat </ANIMAL> sat on the mat",
    "a <ANIMAL> dog </ANIMAL> slept by the door",
    "the <ANIMAL> bird </ANIMAL> sang in the tree",
    "the <ROYAL> king </ROYAL> wears the crown",
    "a <ROYAL> queen </ROYAL> rules the castle",
    "the <ROYAL> prince </ROYAL> rode to the castle",
] * 20

sentences, all_spans = [], []
for m in MARKED:
    toks, spans = string_with_labels(m)
    sentences.append(toks)
    all_spans.append(spans)
print("stripped:", sentences[0], "spans:", all_spans[0])

# 2. native PoS tagger trained on a mini tagged corpus
TAGGED = [
    [("the", "DT"), ("cat", "NN"), ("sat", "VB"), ("on", "IN"),
     ("the", "DT"), ("mat", "NN")],
    [("a", "DT"), ("dog", "NN"), ("slept", "VB"), ("by", "IN"),
     ("the", "DT"), ("door", "NN")],
    [("the", "DT"), ("king", "NN"), ("wears", "VB"), ("the", "DT"),
     ("crown", "NN")],
    [("a", "DT"), ("queen", "NN"), ("rules", "VB"), ("the", "DT"),
     ("castle", "NN")],
]
tagger = HmmPosTagger().train(TAGGED)
print("tagged:", tagger.tag_sentence(["the", "bird", "sat", "on",
                                      "the", "castle"]))

# 3. sentiment lexicon (SWN3 role) for unlabeled windows
lexicon = SentimentLexicon({"sang": 0.4, "rules": 0.3, "slept": -0.1})

# 4. word vectors for the window featurization
flat = [" ".join(s) for s in sentences]
w2v = Word2Vec(flat, layer_size=16, window=3, min_word_frequency=1,
               learning_rate=0.1, negative=5, batch_pairs=128,
               iterations=20, seed=3).fit()

# 5. labeled windows -> example matrix -> MLP classifier
WINDOW = 3
X, y, classes = [], [], ["NONE", "ANIMAL", "ROYAL"]
for toks, spans in zip(sentences, all_spans):
    for w in annotate_windows(toks, WINDOW, tagger=tagger,
                              lexicon=None, span_labels=spans):
        X.append(window_as_vector(w, w2v))
        y.append(classes.index(w.label) if w.label in classes else 0)
X = np.stack(X)
labels = np.eye(len(classes), dtype=np.float32)[y]
print("window dataset:", X.shape, "->", labels.shape)

conf = (NeuralNetConfiguration.builder()
        .lr(0.2).n_in(X.shape[1]).activation_function("tanh")
        .optimization_algo("iteration_gradient_descent")
        .num_iterations(800).use_adagrad(False)
        .list(2).hidden_layer_sizes([64])
        .override(1, layer="output", loss_function="mcxent",
                  activation_function="softmax", n_out=len(classes))
        .pretrain(False).build())
net = MultiLayerNetwork(conf)
net.fit(X, labels)
ev = Evaluation()
ev.eval(labels, np.asarray(net.output(X)))
acc = ev.accuracy()
print(f"window-label train accuracy: {acc:.3f}")
assert acc > 0.9, f"window classifier failed to fit: {acc}"

# 6. sentiment labels where no span annotation exists
for w in annotate_windows(sentences[2], WINDOW, lexicon=lexicon)[:3]:
    print("sentiment window:", w.focus_word(), "->", w.label)
