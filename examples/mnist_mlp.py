"""MNIST 3-layer MLP — the reference MultiLayerTest end-to-end slice.

Run: python examples/mnist_mlp.py  (set JAX_PLATFORMS=cpu to force CPU;
DL4J_TPU_EXAMPLE_FAST=1 shrinks the run for CI smoke)
"""
import os

import numpy as np

FAST = os.environ.get("DL4J_TPU_EXAMPLE_FAST") == "1"

from deeplearning4j_tpu.config import NeuralNetConfiguration
from deeplearning4j_tpu.datasets.mnist import synthetic_mnist
from deeplearning4j_tpu.eval import Evaluation
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize import ScoreIterationListener

conf = (NeuralNetConfiguration.builder()
        .lr(1.0)  # adagrad master step size (reference masterStepSize)
        .n_in(784).activation_function("relu")
        .optimization_algo("iteration_gradient_descent")
        .num_iterations(1).batch_size(512)
        .compute_dtype("bfloat16")
        .list(3).hidden_layer_sizes([256, 128])
        .override(2, layer="output", loss_function="mcxent",
                  activation_function="softmax", n_out=10)
        .pretrain(False).build())

net = MultiLayerNetwork(conf)
net.set_listeners([ScoreIterationListener(10)])

x, y = synthetic_mnist(2048 if FAST else 8192)  # or load_mnist(...) for real IDX
from deeplearning4j_tpu.datasets import ListDataSetIterator
from deeplearning4j_tpu.datasets.api import DataSet

net.fit(ListDataSetIterator(DataSet(np.asarray(x), np.asarray(y)),
                            batch_size=512), epochs=1 if FAST else 3)

ev = Evaluation()
ev.eval(np.asarray(y), np.asarray(net.output(x)))
print(ev.stats())
