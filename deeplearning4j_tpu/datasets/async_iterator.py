"""Async prefetching DataSetIterator over the native BatchQueue.

Parity: the reference's data pipeline feeds the training loop
synchronously (DataSetIterator.next() does its IO/assembly inline);
DL4J grew an AsyncDataSetIterator later for exactly this reason. Here
the wrapper pairs with the C++ bounded ring (`runtime/native/native.cpp`
dl4j_queue_*, consumed through `runtime.native_loader.BatchQueue`): a
producer thread drains the source iterator and pushes (features, labels)
through two lock-stepped native queues, so host-side batch assembly
(CSV/IDX decode, window featurization, augmentation) overlaps the device
step instead of serializing with it.

TPU-relevant because the device step is often sub-millisecond: any
synchronous host work between steps stalls the chip. capacity bounds
the look-ahead (double/triple buffering), keeping memory flat on
arbitrarily long streams.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from deeplearning4j_tpu.datasets.api import DataSet, DataSetIterator
from deeplearning4j_tpu.runtime.native_loader import BatchQueue

log = logging.getLogger(__name__)

__all__ = ["AsyncDataSetIterator"]


class AsyncDataSetIterator(DataSetIterator):
    """Wrap any DataSetIterator; batches are produced ahead of
    consumption on a background thread through the native queue.

    `retries`/`backoff` (opt-in, default off) make the producer survive
    TRANSIENT source errors — a flaky network read, a storage blip: each
    failed has_next()/next() is re-attempted up to `retries` times with
    exponential backoff (backoff, 2*backoff, 4*backoff, ... seconds; the
    attempt budget resets after every successful batch). A source that
    advances its cursor before failing will skip that batch on retry —
    only wrap sources whose next() is repeatable. When the budget is
    exhausted the error relays to the consumer exactly as before."""

    def __init__(self, source: DataSetIterator, capacity: int = 4,
                 reset_timeout: float = 10.0, retries: int = 0,
                 backoff: float = 0.1):
        if retries < 0:
            raise ValueError(f"retries must be >= 0, got {retries}")
        if backoff < 0:
            raise ValueError(f"backoff must be >= 0, got {backoff}")
        self.source = source
        self.capacity = capacity
        self.retries = retries
        self.backoff = backoff
        self.reset_timeout = reset_timeout  # join wait for a slow source
        self._fq: Optional[BatchQueue] = None
        self._lq: Optional[BatchQueue] = None
        self._producer: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._next: Optional[DataSet] = None  # one-batch lookahead
        self._stop = threading.Event()  # interrupts retry backoff sleeps
        super().__init__(batch_size=source.batch(),
                         num_examples=source.num_examples()
                         if self._safe_num_examples() else -1)
        self._start()

    def _safe_num_examples(self) -> bool:
        try:
            self.source.num_examples()
            return True
        except NotImplementedError:
            return False

    # ---------------------------------------------------------- producer
    def _start(self) -> None:
        self._fq = BatchQueue(self.capacity)
        self._lq = BatchQueue(self.capacity)
        self._error = None
        self._next = None
        self._stop = threading.Event()
        stop = self._stop  # this producer generation's own flag

        def next_batch() -> Optional[DataSet]:
            """One (has_next, next) cycle with the bounded retry budget;
            None = stream exhausted. Raises once retries run out — the
            outer handler relays, preserving historical behavior. If a
            failed next() advanced the source PAST its end (has_next goes
            False mid-retry), the saved error is raised rather than
            reporting a clean-but-truncated epoch."""
            attempt = 0
            pending: Optional[Exception] = None
            while True:
                try:
                    if not self.source.has_next():
                        if pending is not None:
                            raise pending
                        return None
                    return self.source.next()
                except MemoryError:
                    # retrying an allocation under memory pressure only
                    # burns backoff sleeps — relay immediately
                    raise
                # KeyboardInterrupt/SystemExit are not Exception: they
                # propagate straight to the outer relay too
                except Exception as e:
                    if e is pending:  # the end-of-stream re-raise above
                        raise
                    pending = e
                    attempt += 1
                    if attempt > self.retries:
                        raise
                    delay = self.backoff * (2 ** (attempt - 1))
                    log.warning(
                        "async producer: source error (attempt %d/%d), "
                        "retrying in %.2fs: %s", attempt, self.retries,
                        delay, e)
                    # interruptible: reset()/close() set the stop flag so
                    # a long backoff can't outlive the consumer (and make
                    # reset()'s join time out on a healthy producer)
                    if stop.wait(delay):
                        raise

        def produce():
            try:
                self.source.reset()
                while True:
                    ds = next_batch()
                    if ds is None:
                        return
                    if not self._fq.push(ds.features):
                        return  # consumer closed
                    if not self._lq.push(ds.labels):
                        return
            except BaseException as e:  # noqa: BLE001 — relay to consumer
                self._error = e
            finally:
                self._fq.close()
                self._lq.close()

        self._producer = threading.Thread(target=produce,
                                          name="async-dsi", daemon=True)
        self._producer.start()

    def _pop(self) -> Optional[DataSet]:
        f = self._fq.pop()
        if f is None:
            if self._error is not None:
                err, self._error = self._error, None
                raise err
            return None
        labels = self._lq.pop()
        return DataSet(f, labels)

    # --------------------------------------------------- iterator surface
    def input_columns(self) -> int:
        return self.source.input_columns()

    def total_outcomes(self) -> int:
        return self.source.total_outcomes()

    def has_next(self) -> bool:
        if self._next is None:
            self._next = self._pop()
        return self._next is not None

    def next(self, num: Optional[int] = None) -> DataSet:
        if not self.has_next():
            raise StopIteration
        ds, self._next = self._next, None
        if self.pre_processor is not None:
            ds = self.pre_processor(ds)
        return ds

    def reset(self) -> None:
        """Tear down the in-flight producer and restart from the source's
        beginning."""
        self._stop.set()  # wake a producer parked in a retry backoff
        self._fq.close()
        self._lq.close()
        if self._producer is not None:
            self._producer.join(timeout=self.reset_timeout)
            if self._producer.is_alive():
                # a second producer over the same source would interleave
                # batches with this stuck one — fail loudly instead
                raise RuntimeError(
                    "AsyncDataSetIterator.reset: producer thread still "
                    f"running (source.next() blocked >{self.reset_timeout}"
                    "s); raise reset_timeout for slow sources rather than "
                    "risking two producers over the same source")
        self._start()

    def close(self) -> None:
        self._stop.set()
        self._fq.close()
        self._lq.close()
