"""Dataset pipeline: DataSet container + iterator protocol.

Parity: reference core/datasets/iterator/DataSetIterator.java:52 (batch /
totalExamples / inputColumns / totalOutcomes / reset / numExamples), the
`BaseDatasetIterator`/`BaseDataFetcher` pair, `ListDataSetIterator`,
`SamplingDataSetIterator`, `MultipleEpochsIterator` (iterator/
MultipleEpochsIterator.java), `TestDataSetIterator` fixture
(core/datasets/test/TestDataSetIterator.java), and `DataSetPreProcessor`.

Host-side numpy throughout — batches cross to device once, at fit time.
"""

from __future__ import annotations

from typing import Callable, Iterator, List, NamedTuple, Optional, Sequence

import numpy as np


class DataSet(NamedTuple):
    """(features, labels) pair — the reference's ND4J `DataSet`."""

    features: np.ndarray
    labels: np.ndarray

    @property
    def num_examples(self) -> int:
        return int(self.features.shape[0])

    def split_test_and_train(self, n_train: int):
        return (DataSet(self.features[:n_train], self.labels[:n_train]),
                DataSet(self.features[n_train:], self.labels[n_train:]))

    def shuffle(self, seed: int = 0) -> "DataSet":
        idx = np.random.RandomState(seed).permutation(self.num_examples)
        return DataSet(self.features[idx], self.labels[idx])

    def sample(self, n: int, seed: int = 0) -> "DataSet":
        idx = np.random.RandomState(seed).choice(self.num_examples, n,
                                                 replace=n > self.num_examples)
        return DataSet(self.features[idx], self.labels[idx])

    @staticmethod
    def merge(datasets: Sequence["DataSet"]) -> "DataSet":
        return DataSet(np.concatenate([d.features for d in datasets]),
                       np.concatenate([d.labels for d in datasets]))


class DataSetPreProcessor:
    def __call__(self, ds: DataSet) -> DataSet:
        raise NotImplementedError


class DataSetIterator:
    """Iterator over minibatches. Subclasses implement `_fetch(i)` or
    override `__next__`."""

    def __init__(self, batch_size: int, num_examples: int):
        self.batch_size = batch_size
        self._num_examples = num_examples
        self.cursor = 0
        self.pre_processor: Optional[DataSetPreProcessor] = None

    # -- reference DataSetIterator surface ------------------------------
    def batch(self) -> int:
        return self.batch_size

    def total_examples(self) -> int:
        return self._num_examples

    def num_examples(self) -> int:
        return self._num_examples

    def input_columns(self) -> int:
        raise NotImplementedError

    def total_outcomes(self) -> int:
        raise NotImplementedError

    def reset(self) -> None:
        self.cursor = 0

    def has_next(self) -> bool:
        return self.cursor < self._num_examples

    def next(self, num: Optional[int] = None) -> DataSet:
        if not self.has_next():
            raise StopIteration
        n = num or self.batch_size
        ds = self._fetch(self.cursor, min(self.cursor + n, self._num_examples))
        self.cursor += n
        if self.pre_processor is not None:
            ds = self.pre_processor(ds)
        return ds

    def _fetch(self, start: int, end: int) -> DataSet:
        raise NotImplementedError

    # -- python iterator protocol ---------------------------------------
    def __iter__(self) -> Iterator[DataSet]:
        return self

    def __next__(self) -> DataSet:
        try:
            return self.next()
        except StopIteration:
            raise


class ListDataSetIterator(DataSetIterator):
    """In-memory iterator over a full DataSet (reference ListDataSetIterator)."""

    def __init__(self, data: DataSet, batch_size: int = 10):
        super().__init__(batch_size, data.num_examples)
        self.data = data

    def input_columns(self) -> int:
        return int(np.prod(self.data.features.shape[1:]))

    def total_outcomes(self) -> int:
        return int(self.data.labels.shape[-1])

    def _fetch(self, start: int, end: int) -> DataSet:
        return DataSet(self.data.features[start:end], self.data.labels[start:end])


class TestDataSetIterator(ListDataSetIterator):
    """Alias fixture (reference core/datasets/test/TestDataSetIterator.java)."""


class SamplingDataSetIterator(DataSetIterator):
    """Draws `total_batches` random-with-replacement batches from a DataSet
    (reference SamplingDataSetIterator)."""

    def __init__(self, data: DataSet, batch_size: int, total_batches: int,
                 seed: int = 0):
        super().__init__(batch_size, batch_size * total_batches)
        self.data = data
        self.total_batches = total_batches
        self._rng = np.random.RandomState(seed)
        self._emitted = 0

    def input_columns(self) -> int:
        return int(np.prod(self.data.features.shape[1:]))

    def total_outcomes(self) -> int:
        return int(self.data.labels.shape[-1])

    def reset(self) -> None:
        super().reset()
        self._emitted = 0

    def has_next(self) -> bool:
        return self._emitted < self.total_batches

    def next(self, num: Optional[int] = None) -> DataSet:
        if not self.has_next():
            raise StopIteration
        self._emitted += 1
        idx = self._rng.choice(self.data.num_examples, num or self.batch_size)
        ds = DataSet(self.data.features[idx], self.data.labels[idx])
        return self.pre_processor(ds) if self.pre_processor else ds


class MultipleEpochsIterator(DataSetIterator):
    """Replays an underlying iterator for N epochs
    (reference iterator/MultipleEpochsIterator.java)."""

    def __init__(self, epochs: int, inner: DataSetIterator):
        super().__init__(inner.batch_size, epochs * inner.num_examples())
        self.epochs = epochs
        self.inner = inner
        self._epoch = 0

    def input_columns(self) -> int:
        return self.inner.input_columns()

    def total_outcomes(self) -> int:
        return self.inner.total_outcomes()

    def reset(self) -> None:
        self._epoch = 0
        self.inner.reset()

    def has_next(self) -> bool:
        return self._epoch < self.epochs - 1 or self.inner.has_next()

    def next(self, num: Optional[int] = None) -> DataSet:
        if not self.inner.has_next():
            if self._epoch >= self.epochs - 1:
                raise StopIteration
            self._epoch += 1
            self.inner.reset()
        return self.inner.next(num)


class ReconstructionDataSetIterator(DataSetIterator):
    """Labels == features, for autoencoder training
    (reference ReconstructionDataSetIterator)."""

    def __init__(self, inner: DataSetIterator):
        super().__init__(inner.batch_size, inner.num_examples())
        self.inner = inner

    def input_columns(self) -> int:
        return self.inner.input_columns()

    def total_outcomes(self) -> int:
        return self.inner.input_columns()

    def reset(self) -> None:
        self.inner.reset()

    def has_next(self) -> bool:
        return self.inner.has_next()

    def next(self, num: Optional[int] = None) -> DataSet:
        ds = self.inner.next(num)
        return DataSet(ds.features, ds.features)
