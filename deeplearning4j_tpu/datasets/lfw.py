"""LFW (Labeled Faces in the Wild) pipeline.

Parity: reference base/LFWLoader.java:1-214 (download + untar lfw.tgz,
'each subdir is a person', per-image vectors via ImageLoader, one-hot
person labels) and datasets/fetchers/LFWDataFetcher.java:31-96 +
LFWDataSetIterator.

This environment has zero egress, so the loader never downloads: it reads
an existing LFW-layout directory (person subdirectories of images; a
downloaded lfw.tgz is unpacked via utils.unzip_file_to if present), and
`synthetic_lfw` writes a deterministic face-shaped fixture with the same
layout for tests — mirroring the synthetic-MNIST approach in mnist.py.
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.api import DataSet, DataSetIterator
from deeplearning4j_tpu.datasets.records import ImageRecordReader


def synthetic_lfw(root: str, num_people: int = 5, images_per_person: int = 4,
                  height: int = 28, width: int = 28, seed: int = 0) -> str:
    """Write an LFW-layout directory of synthetic 'face' images (one blob
    pattern per person + noise) and return its path."""
    from PIL import Image

    rng = np.random.RandomState(seed)
    os.makedirs(root, exist_ok=True)
    yy, xx = np.mgrid[0:height, 0:width]
    for p in range(num_people):
        person_dir = os.path.join(root, f"person_{p:03d}")
        os.makedirs(person_dir, exist_ok=True)
        cy, cx = rng.randint(height // 4, 3 * height // 4, 2)
        base = np.exp(-(((yy - cy) ** 2 + (xx - cx) ** 2)
                        / (2.0 * (2 + p) ** 2)))
        for i in range(images_per_person):
            img = base * 200 + rng.rand(height, width) * 55
            Image.fromarray(img.astype(np.uint8), mode="L").save(
                os.path.join(person_dir, f"img_{i:04d}.png"))
    return root


class LFWLoader:
    """Loads an LFW-layout directory into (features, one-hot labels)."""

    def __init__(self, path: str, height: int = 28, width: int = 28):
        if not os.path.isdir(path):
            archive = path if os.path.isfile(path) else None
            if archive and archive.endswith((".tgz", ".tar.gz")):
                from deeplearning4j_tpu.utils.archive import unzip_file_to

                dest = archive.rsplit(".", 1)[0] + "_extracted"
                unzip_file_to(archive, dest)
                entries = [os.path.join(dest, d) for d in os.listdir(dest)]
                dirs = [d for d in entries if os.path.isdir(d)]
                path = dirs[0] if len(dirs) == 1 else dest
            else:
                raise FileNotFoundError(
                    f"LFW directory {path} not found (no egress in this "
                    "environment — provide an unpacked LFW tree or a local "
                    "lfw.tgz; synthetic_lfw() writes a test fixture)")
        self.path = path
        self.reader = ImageRecordReader(path, height=height, width=width)
        self.height, self.width = height, width

    @property
    def num_names(self) -> int:
        return len(self.reader.labels)

    @property
    def num_pixel_columns(self) -> int:
        return self.height * self.width

    def get_all_images(self) -> DataSet:
        feats: List[np.ndarray] = []
        idx: List[int] = []
        label_to_i = {name: i for i, name in enumerate(self.reader.labels)}
        for rec in self.reader.records():
            feats.append(np.asarray(rec[:-1], np.float32))
            idx.append(label_to_i[rec[-1]])
        features = np.stack(feats) / 255.0
        labels = np.zeros((len(idx), self.num_names), np.float32)
        labels[np.arange(len(idx)), idx] = 1.0
        return DataSet(features, labels)


class LFWDataFetcher:
    """reference LFWDataFetcher.java:31 — cursor-based fetch over the
    loaded images."""

    def __init__(self, path: str, height: int = 28, width: int = 28):
        self.loader = LFWLoader(path, height, width)
        self.data = self.loader.get_all_images()
        self.cursor = 0

    @property
    def total_examples(self) -> int:
        return self.data.num_examples

    def fetch(self, num_examples: int) -> DataSet:
        end = min(self.cursor + num_examples, self.total_examples)
        ds = DataSet(self.data.features[self.cursor:end],
                     self.data.labels[self.cursor:end])
        self.cursor = end
        return ds

    def reset(self) -> None:
        self.cursor = 0


class LFWDataSetIterator(DataSetIterator):
    """reference LFWDataSetIterator (iterator/impl/)."""

    def __init__(self, batch_size: int, path: str,
                 num_examples: Optional[int] = None,
                 height: int = 28, width: int = 28):
        self.fetcher = LFWDataFetcher(path, height, width)
        total = min(num_examples or self.fetcher.total_examples,
                    self.fetcher.total_examples)
        super().__init__(batch_size, total)

    def input_columns(self) -> int:
        return self.fetcher.loader.num_pixel_columns

    def total_outcomes(self) -> int:
        return self.fetcher.loader.num_names

    def _fetch(self, start: int, end: int) -> DataSet:
        return DataSet(self.fetcher.data.features[start:end],
                       self.fetcher.data.labels[start:end])
