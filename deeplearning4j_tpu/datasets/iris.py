"""Iris dataset (reference base/IrisUtils.java + fetchers/IrisDataFetcher.java).

No egress in this environment: loads `data/iris.csv` (sepal_l,sepal_w,petal_l,
petal_w,label) if present, otherwise generates a deterministic 150-example
3-class Gaussian dataset matching the published per-class feature means/stds
of the real Iris data — statistically equivalent for the convergence tests the
reference uses Iris for (MultiLayerTest.java:54-100).
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from deeplearning4j_tpu.datasets.api import DataSet, DataSetIterator

NUM_EXAMPLES = 150
NUM_FEATURES = 4
NUM_CLASSES = 3

# Published per-class feature means/stds (setosa, versicolor, virginica)
_CLASS_MEANS = np.array([
    [5.006, 3.428, 1.462, 0.246],
    [5.936, 2.770, 4.260, 1.326],
    [6.588, 2.974, 5.552, 2.026],
], np.float32)
_CLASS_STDS = np.array([
    [0.352, 0.379, 0.174, 0.105],
    [0.516, 0.314, 0.470, 0.198],
    [0.636, 0.322, 0.552, 0.275],
], np.float32)


def load_iris(data_dir: str = "data", num_examples: Optional[int] = None,
              normalize: bool = True) -> DataSet:
    path = os.path.join(data_dir, "iris.csv")
    if os.path.exists(path):
        raw = np.loadtxt(path, delimiter=",", dtype=np.float32)
        features, raw_labels = raw[:, :NUM_FEATURES], raw[:, NUM_FEATURES].astype(int)
    else:
        rng = np.random.RandomState(6)
        per_class = NUM_EXAMPLES // NUM_CLASSES
        features = np.concatenate([
            _CLASS_MEANS[c] + _CLASS_STDS[c] * rng.randn(per_class, NUM_FEATURES)
            for c in range(NUM_CLASSES)
        ]).astype(np.float32)
        raw_labels = np.repeat(np.arange(NUM_CLASSES), per_class)
    labels = np.zeros((features.shape[0], NUM_CLASSES), np.float32)
    labels[np.arange(features.shape[0]), raw_labels] = 1.0
    # deterministic shuffle so class order doesn't leak into batch order
    idx = np.random.RandomState(0).permutation(features.shape[0])
    features, labels = features[idx], labels[idx]
    if normalize:
        features = (features - features.mean(0)) / (features.std(0) + 1e-8)
    if num_examples is not None:
        features, labels = features[:num_examples], labels[:num_examples]
    return DataSet(features, labels)


class IrisDataSetIterator(DataSetIterator):
    def __init__(self, batch_size: int, num_examples: int = NUM_EXAMPLES,
                 data_dir: str = "data"):
        super().__init__(batch_size, min(num_examples, NUM_EXAMPLES))
        self.data = load_iris(data_dir, num_examples=num_examples)
        self._num_examples = self.data.num_examples

    def input_columns(self) -> int:
        return NUM_FEATURES

    def total_outcomes(self) -> int:
        return NUM_CLASSES

    def _fetch(self, start: int, end: int) -> DataSet:
        return DataSet(self.data.features[start:end],
                       self.data.labels[start:end])
