"""Curves dataset (Hinton's synthetic-curves benchmark used by the
deep-autoencoder literature).

Parity: reference datasets/fetchers/CurvesDataFetcher.java (downloads a
java-serialized `curves.ser` DataSet from S3) + the iterator around it.
The serialized-java artifact is unusable off-JVM and this environment has
no egress, so the fetcher loads a local `.npz` (keys: features, labels)
when given one and otherwise GENERATES curves the way the original
dataset was built: random cubic Bezier curves rasterized into 28x28
grayscale images; labels = features (the dataset is for unsupervised
reconstruction training).
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from deeplearning4j_tpu.datasets.api import DataSet, DataSetIterator

IMAGE_DIM = 28


def _rasterize_bezier(control: np.ndarray, dim: int = IMAGE_DIM,
                      samples: int = 200) -> np.ndarray:
    """Rasterize one cubic Bezier curve (4 control points in [0,1]^2)."""
    t = np.linspace(0.0, 1.0, samples)[:, None]
    p0, p1, p2, p3 = control
    pts = ((1 - t) ** 3 * p0 + 3 * (1 - t) ** 2 * t * p1
           + 3 * (1 - t) * t ** 2 * p2 + t ** 3 * p3)
    img = np.zeros((dim, dim), np.float32)
    ij = np.clip((pts * (dim - 1)).round().astype(int), 0, dim - 1)
    img[ij[:, 1], ij[:, 0]] = 1.0
    return img


def synthetic_curves(n: int, seed: int = 0) -> np.ndarray:
    rng = np.random.RandomState(seed)
    control = rng.rand(n, 4, 2)
    return np.stack([_rasterize_bezier(c) for c in control]).reshape(n, -1)


class CurvesDataFetcher:
    def __init__(self, n_examples: int = 1000, path: Optional[str] = None,
                 seed: int = 0):
        if path and os.path.exists(path):
            with np.load(path) as z:
                features = np.asarray(z["features"], np.float32)
                labels = (np.asarray(z["labels"], np.float32)
                          if "labels" in z else features)
        else:
            features = synthetic_curves(n_examples, seed)
            labels = features
        self.data = DataSet(features, labels)
        self.total_examples = self.data.num_examples


class CurvesDataSetIterator(DataSetIterator):
    def __init__(self, batch_size: int, num_examples: int = 1000,
                 path: Optional[str] = None, seed: int = 0):
        self.fetcher = CurvesDataFetcher(num_examples, path, seed)
        super().__init__(batch_size,
                         min(num_examples, self.fetcher.total_examples))

    def input_columns(self) -> int:
        return int(self.fetcher.data.features.shape[1])

    def total_outcomes(self) -> int:
        return int(self.fetcher.data.labels.shape[1])

    def _fetch(self, start: int, end: int) -> DataSet:
        return DataSet(self.fetcher.data.features[start:end],
                       self.fetcher.data.labels[start:end])
