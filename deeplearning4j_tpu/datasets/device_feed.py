"""Device-feed pipeline: shape-bucketed batch padding + async H2D prefetch.

Sits between the DataSetIterators and the train loops. The whole point of
the TPU-native rewrite is that a training step is one fused XLA program —
but a raw `fit(DataSetIterator)` run re-specializes that program for every
distinct batch shape (the ragged last batch of every epoch), and every
step does a synchronous host->device copy that stalls a sub-millisecond
chip. This layer fixes both:

1. **Shape bucketing** — ragged batches are zero-padded up to a small
   fixed set of bucket sizes (powers of two up to the iterator's batch
   size by default), and the REAL example count rides along as a traced
   scalar (`FeedBatch.n_valid`). The jitted train step derives a 0/1 row
   mask from it, so padded rows contribute zero loss/zero gradient and
   the per-example scaling (loss mean, AdaGrad's ÷batchSize) uses the
   real count — one compiled program per bucket instead of per shape,
   with bit-meaningful math.

2. **Async H2D prefetch** — up to `prefetch` upcoming batches are pushed
   through `jax.device_put` ahead of consumption. `device_put` is
   asynchronous: the transfer runs on the copy engines while the current
   step computes. This composes with `AsyncDataSetIterator` (which
   overlaps host-side batch ASSEMBLY on a producer thread): wrap the
   source in the async iterator for the host leg, then in a DeviceFeed
   for the host->device leg.

Masking semantics and the bucketing policy are documented in
docs/DEVICE_FEED.md.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Iterator, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu import telemetry

__all__ = ["FeedBatch", "DeviceFeed", "feed_mask", "pow2_buckets",
           "bucket_for", "pad_rows"]

# feed pipeline telemetry (docs/OBSERVABILITY.md): process-wide twins of
# the per-feed stats() counters, so bucket behavior and prefetch health
# show up in /metrics without holding a DeviceFeed reference
_M_BATCHES = telemetry.counter(
    "dl4j_feed_batches", "batches staged through DeviceFeed")
_M_PADDED = telemetry.counter(
    "dl4j_feed_padded_examples", "bucketing padding rows shipped")
_M_BUCKET = telemetry.counter(
    "dl4j_feed_bucket_hits", "batches landing in each bucket size")
_M_QUEUE = telemetry.gauge(
    "dl4j_feed_prefetch_depth", "device_put transfers in flight ahead "
    "of the train step (last observed window size)")


def feed_mask(n_rows: int, n_valid):
    """(weights, count) for a bucketed batch inside a jitted train step.

    `n_valid` None means an unbucketed batch: no mask, static count —
    the bit-identical legacy program. Otherwise a traced int32 count
    yields the 0/1 float32 row mask over `n_rows` padded rows. Every
    train-step body derives its masking from here so the FeedBatch
    contract lives in one place."""
    import jax.numpy as jnp

    if n_valid is None:
        return None, n_rows
    return (jnp.arange(n_rows) < n_valid).astype(jnp.float32), n_valid

#: smallest bucket emitted by the default policy — tiny tail batches all
#: share one program instead of one per size
DEFAULT_MIN_BUCKET = 8


def pow2_buckets(batch_size: int, min_bucket: int = DEFAULT_MIN_BUCKET,
                 align: int = 1) -> Tuple[int, ...]:
    """The default bucket ladder: powers of two in [min_bucket,
    batch_size) plus batch_size itself, each rounded up to a multiple of
    `align` (the data-parallel replica count). A ragged batch pads to the
    smallest bucket that holds it, so at most len(buckets) distinct
    programs ever compile for one iterator's stream."""
    if batch_size < 1:
        raise ValueError(f"batch_size must be >= 1, got {batch_size}")
    buckets = set()
    b = max(1, min_bucket)
    while b < batch_size:
        buckets.add(b)
        b *= 2
    buckets.add(batch_size)
    aligned = {-(-b // align) * align for b in buckets}
    return tuple(sorted(aligned))


def bucket_for(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= n; an oversize batch (a source yielding more
    than its declared batch()) gets a next-power-of-two escape bucket
    rather than an error — it still bounds program count."""
    for b in buckets:
        if b >= n:
            return b
    b = max(buckets)
    while b < n:
        b *= 2
    return b


def pad_rows(arr, bucket: int):
    """Zero-pad `arr`'s leading dim up to `bucket` (no-op when already
    there). The inference-side twin of DeviceFeed._pad: forwards are
    per-row independent, so padded rows just get sliced off the output —
    no mask threading needed."""
    n = arr.shape[0]
    if n == bucket:
        return arr
    if n > bucket:
        raise ValueError(f"batch of {n} rows exceeds bucket {bucket}")
    import jax.numpy as jnp

    return jnp.concatenate(
        [jnp.asarray(arr),
         jnp.zeros((bucket - n, *arr.shape[1:]), arr.dtype)])


class FeedBatch(NamedTuple):
    """One device-resident training batch.

    `features`/`labels` are padded to a bucket size; `n_valid` is the
    real example count (int32 scalar). Rows [n_valid:] are zero padding —
    the train step masks them out of the loss and scales by n_valid, so
    they never change the math (see MultiLayerNetwork.loss_fn weights).
    """

    features: Any
    labels: Any
    n_valid: Any

    @property
    def bucket(self) -> int:
        return int(self.features.shape[0])


class DeviceFeed:
    """Wrap a DataSetIterator into a bucketed, prefetching device stream.

    Iterating a DeviceFeed resets the source and yields FeedBatch tuples
    whose arrays are already on (or on their way to) the device. Safe to
    iterate repeatedly — one pass per epoch.

    Parameters
    ----------
    source : DataSetIterator (or any object with reset() + iteration
        yielding DataSet-like (features, labels) pairs).
    buckets : explicit bucket sizes; default `pow2_buckets(source.batch())`.
    prefetch : how many upcoming batches to keep in flight through
        `jax.device_put` (2 = double buffering; 0 disables lookahead).
    sharding : optional `jax.sharding.Sharding` for features/labels
        (e.g. `batch_sharding(mesh)` for per-replica feeding); `n_valid`
        is always placed uncommitted so jit replicates it.
    align : round every bucket up to a multiple of this (set to the
        data-parallel replica count so shards stay equal-sized).
    """

    def __init__(self, source, buckets: Optional[Sequence[int]] = None,
                 prefetch: int = 2, sharding=None, align: int = 1):
        if prefetch < 0:
            raise ValueError(f"prefetch must be >= 0, got {prefetch}")
        self.source = source
        if buckets is None:
            buckets = pow2_buckets(source.batch(), align=align)
        elif align > 1 and any(b % align for b in buckets):
            raise ValueError(
                f"explicit buckets {list(buckets)} must be multiples of "
                f"align={align}")
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError(f"buckets must be positive, got {buckets}")
        self.prefetch = prefetch
        self.sharding = sharding
        # observability: program-shape behavior is the whole point, so
        # count what the stream actually did
        self.bucket_hits = {b: 0 for b in self.buckets}
        self.padded_examples = 0
        self.batches = 0
        #: absolute batch index within the current pass — the guardian's
        #: checkpoint cursor reads as "batches consumed this epoch"
        self.cursor = 0
        self._skip_next = 0

    # ------------------------------------------------------------ padding
    def _pad(self, ds) -> Tuple[Any, Any, np.int32]:
        f, l = ds.features, ds.labels
        n = f.shape[0]
        b = bucket_for(n, self.buckets)
        if b not in self.bucket_hits:
            self.bucket_hits[b] = 0  # escape bucket (oversize source batch)
        self.bucket_hits[b] += 1
        self.padded_examples += b - n
        self.batches += 1
        _M_BATCHES.inc()
        _M_PADDED.inc(b - n)
        _M_BUCKET.labels(bucket=str(b)).inc()
        if b != n:
            # host materialization only when padding is actually needed:
            # a full-bucket batch from a device-resident source passes
            # through untouched (np.asarray on a jax array would be a
            # blocking D2H round trip per batch)
            f, l = np.asarray(f), np.asarray(l)
            f = np.concatenate(
                [f, np.zeros((b - n, *f.shape[1:]), f.dtype)])
            l = np.concatenate(
                [l, np.zeros((b - n, *l.shape[1:]), l.dtype)])
        return f, l, np.int32(n)

    def _put(self, padded) -> FeedBatch:
        import jax

        f, l, n = padded
        if self.sharding is not None:
            f = jax.device_put(f, self.sharding)
            l = jax.device_put(l, self.sharding)
        else:
            f = jax.device_put(f)
            l = jax.device_put(l)
        # n_valid stays uncommitted: jit replicates it wherever the step
        # runs (a committed scalar would pin multi-replica programs)
        return FeedBatch(f, l, jax.device_put(n))

    # ---------------------------------------------------------- streaming
    def fast_forward(self, n: int) -> None:
        """Drop the first `n` source batches of the NEXT pass — the
        mid-epoch resume primitive: position the stream at a checkpoint's
        `iterator_position` without padding/transferring the skipped
        batches. One-shot (the pass after consumes the whole stream
        again); `cursor` starts at `n` for that pass."""
        if n < 0:
            raise ValueError(f"fast_forward must be >= 0, got {n}")
        self._skip_next = int(n)

    def _host_batches(self):
        self.source.reset()
        skip, self._skip_next = self._skip_next, 0
        self.cursor = skip
        for ds in self.source:
            if skip > 0:
                skip -= 1
                continue
            yield self._pad(ds)

    def __iter__(self) -> Iterator[FeedBatch]:
        """One epoch: bucketed batches with up to `prefetch` transfers in
        flight ahead of the consumer. device_put is async, so filling the
        lookahead window overlaps the NEXT batches' H2D copies with the
        current step's compute — no thread needed for the device leg."""
        host = self._host_batches()
        window: deque = deque()
        depth = max(1, self.prefetch)
        for padded in host:
            window.append(self._put(padded))
            if len(window) < depth:
                continue
            self.cursor += 1
            _M_QUEUE.set(len(window) - 1)
            yield window.popleft()
        while window:
            self.cursor += 1
            _M_QUEUE.set(len(window) - 1)
            yield window.popleft()

    # --------------------------------------------------- iterator surface
    def batch(self) -> int:
        return self.source.batch()

    def reset(self) -> None:
        self.source.reset()

    def close(self) -> None:
        close = getattr(self.source, "close", None)
        if close is not None:
            close()

    def stats(self) -> dict:
        """Pipeline counters: how many batches hit each bucket and how
        many padded (masked-out) rows were shipped."""
        return {"buckets": list(self.buckets),
                "bucket_hits": dict(self.bucket_hits),
                "padded_examples": int(self.padded_examples),
                "batches": int(self.batches)}
