"""MNIST: IDX binary readers + iterator.

Parity: reference core/datasets/mnist/ (MnistManager / MnistDbFile /
MnistImageFile / MnistLabelFile — IDX readers), fetchers/MnistDataFetcher.java:37
and base/MnistFetcher.java (download). This environment has no egress, so when
the IDX files are absent a deterministic synthetic MNIST-shaped dataset
(28x28 class-structured images) is generated instead; real files are used when
present at `data_dir`.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.api import DataSet, DataSetIterator

NUM_EXAMPLES = 60000
NUM_TEST = 10000
IMAGE_SIZE = 28 * 28
NUM_CLASSES = 10


def read_idx_images(path: str) -> np.ndarray:
    """Parse an IDX3 image file (reference MnistImageFile.java)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != 2051:
            raise ValueError(f"Bad IDX image magic {magic} in {path}")
        data = np.frombuffer(f.read(n * rows * cols), dtype=np.uint8)
    return data.reshape(n, rows * cols)


def read_idx_labels(path: str) -> np.ndarray:
    """Parse an IDX1 label file (reference MnistLabelFile.java)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        if magic != 2049:
            raise ValueError(f"Bad IDX label magic {magic} in {path}")
        return np.frombuffer(f.read(n), dtype=np.uint8)


def _find(data_dir: str, names) -> Optional[str]:
    for name in names:
        for suffix in ("", ".gz"):
            p = os.path.join(data_dir, name + suffix)
            if os.path.exists(p):
                return p
    return None


def synthetic_mnist(n: int, seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic MNIST-shaped data: each class is a distinct smoothed
    template + pixel noise, so models can actually learn the classes."""
    rng = np.random.RandomState(seed)
    templates = rng.rand(NUM_CLASSES, IMAGE_SIZE).astype(np.float32)
    # smooth templates spatially so they look image-like
    t = templates.reshape(NUM_CLASSES, 28, 28)
    t = (t + np.roll(t, 1, 1) + np.roll(t, -1, 1) + np.roll(t, 1, 2)
         + np.roll(t, -1, 2)) / 5.0
    templates = (t.reshape(NUM_CLASSES, IMAGE_SIZE) > 0.5).astype(np.float32)
    labels = rng.randint(0, NUM_CLASSES, n)
    images = templates[labels] * 0.8 + 0.2 * rng.rand(n, IMAGE_SIZE)
    onehot = np.zeros((n, NUM_CLASSES), np.float32)
    onehot[np.arange(n), labels] = 1.0
    return images.astype(np.float32), onehot


def load_mnist(data_dir: str = "data/mnist", train: bool = True,
               num_examples: Optional[int] = None,
               binarize: bool = False) -> DataSet:
    img_names = (["train-images-idx3-ubyte", "train-images.idx3-ubyte"]
                 if train else ["t10k-images-idx3-ubyte", "t10k-images.idx3-ubyte"])
    lbl_names = (["train-labels-idx1-ubyte", "train-labels.idx1-ubyte"]
                 if train else ["t10k-labels-idx1-ubyte", "t10k-labels.idx1-ubyte"])
    img_path = _find(data_dir, img_names)
    lbl_path = _find(data_dir, lbl_names)
    if img_path and lbl_path:
        images = read_idx_images(img_path).astype(np.float32) / 255.0
        raw = read_idx_labels(lbl_path)
        labels = np.zeros((raw.shape[0], NUM_CLASSES), np.float32)
        labels[np.arange(raw.shape[0]), raw] = 1.0
    else:
        n = num_examples or (NUM_EXAMPLES if train else NUM_TEST)
        images, labels = synthetic_mnist(n, seed=0 if train else 1)
    if binarize:
        images = (images > 0.5).astype(np.float32)
    if num_examples is not None:
        images, labels = images[:num_examples], labels[:num_examples]
    return DataSet(images, labels)


class MnistDataSetIterator(DataSetIterator):
    """Reference MnistDataSetIterator (fetchers/MnistDataFetcher.java:37)."""

    def __init__(self, batch_size: int, num_examples: int,
                 data_dir: str = "data/mnist", train: bool = True,
                 binarize: bool = False):
        super().__init__(batch_size, num_examples)
        self.data = load_mnist(data_dir, train=train,
                               num_examples=num_examples, binarize=binarize)
        self._num_examples = self.data.num_examples

    def input_columns(self) -> int:
        return IMAGE_SIZE

    def total_outcomes(self) -> int:
        return NUM_CLASSES

    def _fetch(self, start: int, end: int) -> DataSet:
        return DataSet(self.data.features[start:end],
                       self.data.labels[start:end])


class RawMnistDataSetIterator(MnistDataSetIterator):
    """MNIST without binarization (reference iterator/impl/
    RawMnistDataSetIterator.java — the raw-pixel variant)."""

    def __init__(self, batch_size: int, num_examples: int,
                 data_dir: str = "data/mnist", train: bool = True):
        super().__init__(batch_size, num_examples, data_dir=data_dir,
                         train=train, binarize=False)
