from deeplearning4j_tpu.datasets.api import (  # noqa: F401
    DataSet,
    DataSetIterator,
    ListDataSetIterator,
    MultipleEpochsIterator,
    ReconstructionDataSetIterator,
    SamplingDataSetIterator,
    TestDataSetIterator,
)
from deeplearning4j_tpu.datasets.async_iterator import (  # noqa: F401
    AsyncDataSetIterator,
)
from deeplearning4j_tpu.datasets.device_feed import (  # noqa: F401
    DeviceFeed,
    FeedBatch,
    bucket_for,
    pad_rows,
    pow2_buckets,
)
from deeplearning4j_tpu.datasets.mnist import (  # noqa: F401
    MnistDataSetIterator,
    RawMnistDataSetIterator,
)
from deeplearning4j_tpu.datasets.iris import IrisDataSetIterator  # noqa: F401
from deeplearning4j_tpu.datasets.csv import CSVDataSetIterator  # noqa: F401
from deeplearning4j_tpu.datasets.records import (  # noqa: F401
    CSVRecordReader,
    ImageRecordReader,
    LineRecordReader,
    ListRecordReader,
    RecordReader,
    RecordReaderDataSetIterator,
)
from deeplearning4j_tpu.datasets.lfw import (  # noqa: F401
    LFWDataFetcher,
    LFWDataSetIterator,
    LFWLoader,
    synthetic_lfw,
)
from deeplearning4j_tpu.datasets.curves import (  # noqa: F401
    CurvesDataSetIterator,
)
from deeplearning4j_tpu.datasets.moving_window import (  # noqa: F401
    MovingWindowDataSetIterator,
)
from deeplearning4j_tpu.datasets.vectorizer import ImageVectorizer  # noqa: F401
