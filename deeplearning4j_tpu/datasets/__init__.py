from deeplearning4j_tpu.datasets.api import (  # noqa: F401
    DataSet,
    DataSetIterator,
    ListDataSetIterator,
    SamplingDataSetIterator,
    MultipleEpochsIterator,
    TestDataSetIterator,
)
from deeplearning4j_tpu.datasets.mnist import MnistDataSetIterator  # noqa: F401
from deeplearning4j_tpu.datasets.iris import IrisDataSetIterator  # noqa: F401
from deeplearning4j_tpu.datasets.csv import CSVDataSetIterator  # noqa: F401
