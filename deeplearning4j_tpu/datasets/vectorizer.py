"""Vectorizers: turn raw artifacts into DataSets.

Parity: reference datasets/vectorizer/Vectorizer.java + ImageVectorizer.java
:32-100 (image file + label -> DataSet, with fluent binarize()/normalize()).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from deeplearning4j_tpu.datasets.api import DataSet


class Vectorizer:
    def vectorize(self) -> DataSet:
        raise NotImplementedError


class ImageVectorizer(Vectorizer):
    """One image file + its label -> a one-example DataSet."""

    def __init__(self, image_path: str, num_labels: int, label: int,
                 height: Optional[int] = None, width: Optional[int] = None):
        from deeplearning4j_tpu.utils.image_loader import ImageLoader

        self.image_path = image_path
        self.num_labels = num_labels
        self.label = label
        self.loader = ImageLoader(height=height, width=width)
        self._binarize_threshold: Optional[int] = None
        self._normalize = False

    def binarize(self, threshold: int = 30) -> "ImageVectorizer":
        self._binarize_threshold = threshold
        self._normalize = False
        return self

    def normalize(self) -> "ImageVectorizer":
        self._normalize = True
        self._binarize_threshold = None
        return self

    def vectorize(self) -> DataSet:
        x = self.loader.as_row_vector(self.image_path)
        if self._binarize_threshold is not None:
            x = (x > self._binarize_threshold).astype(np.float32)
        elif self._normalize:
            x = x / 255.0
        label = np.zeros((1, self.num_labels), np.float32)
        label[0, self.label] = 1.0
        return DataSet(x[None, :], label)
