"""Pluggable record readers + the record -> DataSet bridge.

Parity: the external Canova library's RecordReader contract and the
reference's bridge iterator (core/datasets/canova/
RecordReaderDataSetIterator.java:1-199 — batchSize/labelIndex/
numPossibleLabels, records as value lists with the label at labelIndex)
plus Canova-style readers: CSV/line/list readers and an image-directory
reader (per-label subdirectories, decoded via utils ImageLoader).

Streaming design: readers yield records one at a time and the bridge
assembles batches on the fly — a reader over a directory of images never
materializes the whole dataset in RAM (the reference's next(num) loop
semantics, without its per-record INDArray churn).
"""

from __future__ import annotations

import os
from typing import Iterable, List, Optional, Sequence, Union

import numpy as np

from deeplearning4j_tpu.datasets.api import (DataSet, DataSetIterator,
                                             DataSetPreProcessor)

Record = List[Union[float, str]]


class RecordReader:
    """A stream of records (value lists). Subclasses implement `_iter()`;
    `reset()` restarts the stream."""

    def _iter(self) -> Iterable[Record]:
        raise NotImplementedError

    def __init__(self):
        self._gen = None
        self._pending = None

    def reset(self) -> None:
        self._gen = iter(self._iter())
        self._pending = None

    def has_next(self) -> bool:
        if self._gen is None:
            self.reset()
        if self._pending is None:
            try:
                self._pending = next(self._gen)
            except StopIteration:
                self._pending = None
                return False
        return True

    def next_record(self) -> Record:
        if not self.has_next():
            raise StopIteration
        rec, self._pending = self._pending, None
        return rec

    def peek(self) -> Optional[Record]:
        """First pending record without consuming it (None if exhausted)."""
        return self._pending if self.has_next() else None

    def count(self) -> Optional[int]:
        """Total record count if cheaply known up front, else None.

        Streaming readers return None; composing iterators
        (MultipleEpochs/Reconstruction) need this to size themselves, so
        in-memory readers override it.
        """
        return None

    def records(self) -> Iterable[Record]:
        self.reset()
        while self.has_next():
            yield self.next_record()


class ListRecordReader(RecordReader):
    """In-memory record collection."""

    def __init__(self, records: Sequence[Record]):
        super().__init__()
        self._records = list(records)

    def _iter(self):
        return iter(self._records)

    def count(self) -> int:
        return len(self._records)


class CSVRecordReader(RecordReader):
    """Delimited text file; fields stay strings (the bridge handles
    numeric/label conversion)."""

    def __init__(self, path: str, delimiter: str = ",", skip_lines: int = 0):
        super().__init__()
        self.path = path
        self.delimiter = delimiter
        self.skip_lines = skip_lines

    def _iter(self):
        with open(self.path) as f:
            for i, line in enumerate(f):
                if i < self.skip_lines:
                    continue
                line = line.strip()
                if line:
                    yield line.split(self.delimiter)


class LineRecordReader(RecordReader):
    """One record per line across a list of files (Canova LineRecordReader)."""

    def __init__(self, paths: Sequence[str]):
        super().__init__()
        self.paths = list(paths)

    def _iter(self):
        for path in self.paths:
            with open(path) as f:
                for line in f:
                    line = line.rstrip("\n")
                    if line:
                        yield [line]


class ImageRecordReader(RecordReader):
    """Walks a root directory whose immediate subdirectories are labels
    (the LFW layout); each record is [*pixels, label_name]. Decoding via
    utils ImageLoader (reference ImageRecordReader + LFWLoader.java:104-118
    'each subdir is a person')."""

    def __init__(self, root: str, height: int = 28, width: int = 28,
                 grayscale: bool = True,
                 extensions: Sequence[str] = (".png", ".jpg", ".jpeg",
                                              ".pgm", ".ppm", ".bmp")):
        from deeplearning4j_tpu.utils.image_loader import ImageLoader

        super().__init__()
        self.root = root
        self.loader = ImageLoader(height=height, width=width,
                                  grayscale=grayscale)
        self.extensions = tuple(extensions)
        self.labels = sorted(
            d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d)))
        if not self.labels:
            raise ValueError(f"No label subdirectories under {root}")

    def _iter(self):
        for label in self.labels:
            folder = os.path.join(self.root, label)
            for name in sorted(os.listdir(folder)):
                if name.lower().endswith(self.extensions):
                    pixels = self.loader.as_row_vector(
                        os.path.join(folder, name))
                    yield list(pixels) + [label]


class RecordReaderDataSetIterator(DataSetIterator):
    """Record stream -> DataSet batches (reference
    RecordReaderDataSetIterator.java:1-199).

    label_index: column holding the label (-1 = last column; None = no
    label, features double as labels for reconstruction training).
    num_possible_labels: one-hot width for classification; None with a
    label_index means regression (label kept as a float column). String
    label values are mapped to indices in first-seen order (or pass
    `labels` for a fixed ordering).
    """

    def __init__(self, reader: RecordReader, batch_size: int = 10,
                 label_index: Optional[int] = -1,
                 num_possible_labels: Optional[int] = None,
                 labels: Optional[Sequence[str]] = None):
        super().__init__(batch_size, -1)
        self.reader = reader
        self.label_index = label_index
        self.num_possible_labels = num_possible_labels
        self.label_map = ({str(v): i for i, v in enumerate(labels)}
                          if labels else {})
        self.pre_processor: Optional[DataSetPreProcessor] = None
        self.reader.reset()
        self._seen = 0
        self._record_width: Optional[int] = None

    # Totals: use the reader's up-front count when it has one (in-memory
    # readers); for true streams fall back to the count seen so far, which
    # only becomes the total after exhaustion — composing iterators that
    # size themselves at construction should load_all() streams first.
    def total_examples(self) -> int:
        n = self.reader.count()
        return self._seen if n is None else n

    def num_examples(self) -> int:
        return self.total_examples()

    def input_columns(self) -> int:
        """Feature width, learned by peeking the first record (the
        reference CSVDataSetIterator knows its column count up front);
        cached so it stays known after the stream is drained."""
        if self._record_width is None:
            rec = self.reader.peek()
            if rec is None:
                raise ValueError(
                    "cannot determine input_columns: stream empty")
            self._record_width = len(rec)
        return self._record_width - (0 if self.label_index is None else 1)

    def total_outcomes(self) -> int:
        if self.num_possible_labels:
            return self.num_possible_labels
        if self.label_index is None:  # reconstruction: labels = features
            return self.input_columns()
        return 1  # regression: single float column

    def reset(self) -> None:
        self.reader.reset()
        self._seen = 0

    def has_next(self) -> bool:
        return self.reader.has_next()

    def _label_value(self, raw) -> float:
        if isinstance(raw, str):
            try:
                return float(raw)
            except ValueError:
                if raw not in self.label_map:
                    if self.label_map and self.num_possible_labels and \
                            len(self.label_map) >= self.num_possible_labels:
                        raise ValueError(
                            f"Unseen label {raw!r} beyond "
                            f"num_possible_labels={self.num_possible_labels}")
                    self.label_map[raw] = len(self.label_map)
                return float(self.label_map[raw])
        return float(raw)

    def next(self, num: Optional[int] = None) -> DataSet:
        n = num or self.batch_size
        feats, labels = [], []
        while len(feats) < n and self.reader.has_next():
            rec = self.reader.next_record()
            if self._record_width is None:
                self._record_width = len(rec)
            if self.label_index is None:
                feats.append([float(v) for v in rec])
                continue
            li = self.label_index if self.label_index >= 0 else len(rec) - 1
            labels.append(self._label_value(rec[li]))
            feats.append([float(v) for i, v in enumerate(rec) if i != li])
        if not feats:
            raise StopIteration
        self._seen += len(feats)
        features = np.asarray(feats, np.float32)
        if self.label_index is None:
            ds = DataSet(features, features)
        elif self.num_possible_labels:
            idx = np.asarray(labels, np.int64)
            if idx.min() < 0 or idx.max() >= self.num_possible_labels:
                raise ValueError(
                    f"Label index out of range [0, "
                    f"{self.num_possible_labels}): {idx.min()}..{idx.max()}")
            one_hot = np.zeros((len(idx), self.num_possible_labels),
                               np.float32)
            one_hot[np.arange(len(idx)), idx] = 1.0
            ds = DataSet(features, one_hot)
        else:  # regression
            ds = DataSet(features,
                         np.asarray(labels, np.float32)[:, None])
        return self.pre_processor(ds) if self.pre_processor else ds

    def load_all(self) -> DataSet:
        """Drain the stream into one DataSet (empty-shaped if no records)."""
        self.reset()
        batches = [ds for ds in self]
        if not batches:
            return DataSet(np.zeros((0, 0), np.float32),
                           np.zeros((0, 0), np.float32))
        return DataSet.merge(batches)
