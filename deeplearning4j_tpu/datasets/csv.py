"""CSV dataset iterator.

Parity: reference `CSVDataSetIterator` (core/datasets/fetchers CSV path).
Built on the pluggable record-reader protocol in datasets/records.py
(CSVRecordReader is re-exported from there for back-compat).
"""

from __future__ import annotations

from typing import Optional

from deeplearning4j_tpu.datasets.records import (  # noqa: F401
    CSVRecordReader,
    RecordReaderDataSetIterator,
)


class CSVDataSetIterator(RecordReaderDataSetIterator):
    def __init__(self, path: str, batch_size: int, label_index: int = -1,
                 num_classes: Optional[int] = None, delimiter: str = ",",
                 skip_lines: int = 0):
        if label_index is not None and not num_classes:
            raise ValueError(
                "label_index given without num_classes; pass num_classes "
                "for classification or label_index=None for reconstruction")
        super().__init__(CSVRecordReader(path, delimiter, skip_lines),
                         batch_size, label_index=label_index,
                         num_possible_labels=num_classes)
