"""CSV dataset iterator.

Parity: reference core/datasets/fetchers CSV path + `CSVDataSetIterator` and
the Canova record-reader bridge (core/datasets/canova/
RecordReaderDataSetIterator.java) — here a `RecordReader` is any iterable of
value lists; `CSVRecordReader` parses delimited text files.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.datasets.api import DataSet, DataSetIterator


class CSVRecordReader:
    """Minimal Canova-style record reader over a delimited text file."""

    def __init__(self, path: str, delimiter: str = ",", skip_lines: int = 0):
        self.path = path
        self.delimiter = delimiter
        self.skip_lines = skip_lines

    def records(self) -> Iterable[List[str]]:
        with open(self.path) as f:
            for i, line in enumerate(f):
                if i < self.skip_lines:
                    continue
                line = line.strip()
                if line:
                    yield line.split(self.delimiter)


class RecordReaderDataSetIterator(DataSetIterator):
    """Bridge record reader -> DataSet batches (reference
    RecordReaderDataSetIterator.java). `label_index` column becomes a one-hot
    label over `num_classes`; remaining columns are features. With
    label_index=None the features are also the labels (reconstruction)."""

    def __init__(self, reader, batch_size: int,
                 label_index: Optional[int] = -1,
                 num_classes: Optional[int] = None):
        records = [[float(v) for v in rec] for rec in reader.records()]
        arr = np.asarray(records, np.float32)
        if label_index is not None and not num_classes:
            raise ValueError(
                "label_index given without num_classes; pass num_classes for "
                "classification or label_index=None for reconstruction")
        if label_index is not None and num_classes:
            li = label_index if label_index >= 0 else arr.shape[1] - 1
            raw = arr[:, li].astype(int)
            features = np.delete(arr, li, axis=1)
            labels = np.zeros((arr.shape[0], num_classes), np.float32)
            labels[np.arange(arr.shape[0]), raw] = 1.0
        else:
            features = arr
            labels = arr
        super().__init__(batch_size, features.shape[0])
        self.data = DataSet(features, labels)

    def input_columns(self) -> int:
        return int(self.data.features.shape[1])

    def total_outcomes(self) -> int:
        return int(self.data.labels.shape[1])

    def _fetch(self, start: int, end: int) -> DataSet:
        return DataSet(self.data.features[start:end],
                       self.data.labels[start:end])


class CSVDataSetIterator(RecordReaderDataSetIterator):
    def __init__(self, path: str, batch_size: int, label_index: int = -1,
                 num_classes: Optional[int] = None, delimiter: str = ",",
                 skip_lines: int = 0):
        super().__init__(CSVRecordReader(path, delimiter, skip_lines),
                         batch_size, label_index, num_classes)
