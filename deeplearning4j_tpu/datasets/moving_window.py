"""Moving-window dataset expansion: every example image is sliced into all
rotated sub-windows to generate more training examples.

Parity: reference datasets/iterator/impl/MovingWindowDataSetFetcher.java
(each example -> MovingWindowMatrix(..., addRotate=true).windows(true),
labels copied) + MovingWindowBaseDataSetIterator.java. The reference's
inner loop indexed windows.get(i) instead of .get(j) (an alpha-era bug
that duplicated one window per example); not reproduced.
"""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.datasets.api import DataSet, DataSetIterator
from deeplearning4j_tpu.utils.moving_window_matrix import MovingWindowMatrix


def expand_with_windows(data: DataSet, rows: int, cols: int,
                        window_rows: int, window_cols: int) -> DataSet:
    """All rotated windows of every (rows x cols) example; labels are
    copied to each derived window. (The reference also re-appended the
    raw example, whose width differs from the windows' — merging that
    into one matrix is shape-inconsistent, so only windows are kept; pass
    window == image size to include originals.)"""
    feats, labels = [], []
    for x, y in zip(data.features, data.labels):
        img = np.asarray(x, np.float32).reshape(rows, cols)
        windows = MovingWindowMatrix(img, window_rows, window_cols,
                                     add_rotate=True).windows(flattened=True)
        for w in windows:
            feats.append(w)
            labels.append(y)
    return DataSet(np.stack(feats), np.stack(labels))


class MovingWindowDataSetIterator(DataSetIterator):
    def __init__(self, batch_size: int, data: DataSet, rows: int, cols: int,
                 window_rows: int, window_cols: int):
        self.data = expand_with_windows(data, rows, cols, window_rows,
                                        window_cols)
        super().__init__(batch_size, self.data.num_examples)

    def input_columns(self) -> int:
        return int(self.data.features.shape[1])

    def total_outcomes(self) -> int:
        return int(self.data.labels.shape[1])

    def _fetch(self, start: int, end: int) -> DataSet:
        return DataSet(self.data.features[start:end],
                       self.data.labels[start:end])
