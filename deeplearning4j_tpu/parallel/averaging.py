"""Epoch-wave parameter averaging on a device mesh.

Parity: the reference's iterative-reduce semantics — each worker takes K
local fit steps on its own shard, then parameters are averaged
(`MultiLayerNetwork.merge` :1361 / INDArrayAggregator.java:35-59 /
Spark fold(Add)/÷n, SparkDl4jMultiLayer.java:172-174). The reference moves
packed parameter vectors through Hazelcast/Akka/Spark to a master; here each
replica's K-step inner loop is a `lax.scan` compiled into ONE XLA program
per wave, and the "averaging" is a `pmean` collective that rides ICI — no
host round-trip, no serialization.

This trainer exists for behavioral parity (coarse-grained averaging waves);
`DataParallelTrainer` (per-step gradient all-reduce) is the tighter-sync
mode that usually trains better per FLOP.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

try:  # jax>=0.4.35 exposes shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

from deeplearning4j_tpu.optimize.updater import NetworkGradientUpdater
from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, make_mesh


class ParameterAveragingTrainer:
    """K local steps per replica, then a pmean parameter average per wave."""

    def __init__(self, network, mesh: Optional[jax.sharding.Mesh] = None,
                 axis: str = DATA_AXIS, local_steps: int = 4):
        self.network = network
        self.mesh = mesh if mesh is not None else make_mesh()
        self.axis = axis
        self.local_steps = local_steps
        self.n_devices = int(np.prod(self.mesh.devices.shape))
        self.updater = NetworkGradientUpdater.for_network(network)
        self._wave = self._build_wave()

    def _build_wave(self):
        net, updater, axis = self.network, self.updater, self.axis

        def replica_wave(params, upd_state, xs, ys, keys):
            # per-device shapes: xs (1, K, b, f) — drop the shard dim
            xs, ys, keys = xs[0], ys[0], keys[0]

            def body(carry, xyk):
                p, s = carry
                x, y, k = xyk
                score, g = jax.value_and_grad(net.loss_fn)(
                    p, x, y, rng=k, training=True)
                upd, s = updater.update(g, s, p)
                p = jax.tree_util.tree_map(lambda pp, uu: pp - uu, p, upd)
                return (p, s), score

            (p, s), scores = lax.scan(body, (params, upd_state),
                                      (xs, ys, keys))
            # THE iterative-reduce average, as an ICI collective. Integer
            # leaves (e.g. the updater's iteration counter — identical on
            # every replica) use pmax to stay integer-typed; pmean would
            # drift them to float and retrigger compilation.
            def avg(a):
                if jnp.issubdtype(a.dtype, jnp.floating):
                    return lax.pmean(a, axis)
                return lax.pmax(a, axis)

            p = jax.tree_util.tree_map(avg, p)
            s = jax.tree_util.tree_map(avg, s)
            return p, s, lax.pmean(jnp.mean(scores), axis)

        fn = _shard_map(
            replica_wave, mesh=self.mesh,
            in_specs=(P(), P(), P(axis), P(axis), P(axis)),
            out_specs=(P(), P(), P()),
        )
        return jax.jit(fn)

    def fit(self, iterator, epochs: int = 1) -> None:
        """Consume the iterator in waves of n_devices*local_steps batches."""
        net = self.network
        params = net._params
        upd_state = (net._updater_state if net._updater_state is not None
                     else self.updater.init(params))
        score = None
        waves = 0
        for _ in range(epochs):
            iterator.reset()
            batch = []
            for ds in iterator:
                batch.append((np.asarray(ds.features), np.asarray(ds.labels)))
                if len(batch) == self.n_devices * self.local_steps:
                    params, upd_state, score = self._run_wave(
                        params, upd_state, batch)
                    waves += 1
                    batch = []
            if batch:  # tail wave: tile to fill the grid
                need = self.n_devices * self.local_steps
                idx = np.arange(need) % len(batch)
                params, upd_state, score = self._run_wave(
                    params, upd_state, [batch[i] for i in idx])
                waves += 1
        net._params = params
        net._updater_state = upd_state
        if waves:
            for listener in net.listeners:
                listener.iteration_done(net, waves - 1, float(score))

    @staticmethod
    def _pad_rows(arr: np.ndarray, rows: int) -> np.ndarray:
        """Tile a ragged tail batch up to the wave's uniform batch size."""
        if arr.shape[0] == rows:
            return arr
        idx = np.arange(rows) % arr.shape[0]
        return arr[idx]

    def _run_wave(self, params, upd_state, batch):
        d, k = self.n_devices, self.local_steps
        rows = max(b[0].shape[0] for b in batch)
        batch = [(self._pad_rows(x, rows), self._pad_rows(y, rows))
                 for x, y in batch]
        xs = np.stack([b[0] for b in batch]).reshape(
            d, k, *batch[0][0].shape)
        ys = np.stack([b[1] for b in batch]).reshape(
            d, k, *batch[0][1].shape)
        keys = jax.random.split(self.network.next_key(), d * k).reshape(
            d, k, -1)
        with self.mesh:
            return self._wave(params, upd_state, jnp.asarray(xs),
                              jnp.asarray(ys), keys)
