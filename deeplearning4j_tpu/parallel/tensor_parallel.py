"""Tensor (model) parallelism for the dense stack — beyond parity.

The reference is data-parallel only (SURVEY §2.8: TP/PP/SP "ABSENT in
reference"); on TPU, tensor parallelism is how a layer that doesn't fit
(or saturate) one chip spans the mesh. Design follows the scaling-book
recipe — pick a mesh, annotate shardings, let XLA insert collectives:

- eligible 2-D weight matrices alternate **column split**
  (W: P(None, model), b: P(model) — activations come out
  feature-sharded) and **row split** (W: P(model, None), b replicated —
  XLA inserts the psum over `model` to unshard the products), so
  consecutive layers chain with exactly one all-reduce per row-split
  layer and no resharding of activations in between (Megatron-style
  pairing, expressed purely as GSPMD shardings);
- the batch is simultaneously sharded over the `data` axis, giving
  tp x dp on one 2-D mesh;
- optimizer state (AdaGrad hist / momentum velocity) shards exactly like
  its parameter, so update math is local to each shard (the ZeRO-spirit
  follow-on to parallel/sharded_update.py, here falling out of the
  sharding annotations for free).

Non-2-D layers (conv stacks etc.) and the small output layer stay
replicated; uneven splits raise rather than silently padding.

Fault tolerance: `fit(guardian=..., checkpoint_every=...)` inherits the
DataParallelTrainer guardian wiring — the guarded commit's finite
predicate reduces over the model-sharded gradients (GSPMD all-reduces
the scalar across BOTH mesh axes), so a NaN on any tp or dp shard skips
the update everywhere; the GuardianState carry rides replicated.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.optimize.updater import UpdaterState
from deeplearning4j_tpu.parallel.data_parallel import DataParallelTrainer
from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS


class TensorParallelTrainer(DataParallelTrainer):
    """tp x dp training: batch over `data`, alternating column/row weight
    splits over `model`. Mesh must carry BOTH axes."""

    def __init__(self, network, mesh, model_axis: str = MODEL_AXIS,
                 axis: str = DATA_AXIS):
        if model_axis not in mesh.axis_names:
            raise ValueError(
                f"mesh {mesh.axis_names} has no {model_axis!r} axis")
        self.model_axis = model_axis
        self.tp = int(mesh.shape[model_axis])
        super().__init__(network, mesh, axis=axis)

    # ------------------------------------------------------------ shardings
    def _param_specs(self):
        """Per-layer {name: PartitionSpec}, alternating col/row splits
        over eligible layers; the LAST layer (output head) replicates."""
        net = self.network
        specs = {}
        col_next = True
        last = len(net.layers) - 1
        for i in range(len(net.layers)):
            table = net._params[str(i)]
            layer_spec = {name: P() for name in table}
            w = table.get("W")
            eligible = (w is not None and getattr(w, "ndim", 0) == 2
                        and set(table) <= {"W", "b"} and i != last)
            if eligible:
                n_in, n_out = w.shape
                if col_next and n_out % self.tp == 0:
                    layer_spec["W"] = P(None, self.model_axis)
                    if "b" in table:  # b is (1, n_out): split its lanes
                        b = table["b"]
                        layer_spec["b"] = (
                            P(None, self.model_axis)
                            if getattr(b, "ndim", 1) == 2
                            else P(self.model_axis))
                    col_next = False
                elif not col_next and n_in % self.tp == 0:
                    layer_spec["W"] = P(self.model_axis, None)
                    # b adds to the psum-unsharded output: replicated
                    col_next = True
                # an indivisible dim leaves the layer replicated and the
                # alternation state unchanged (the chain stays coherent)
            specs[str(i)] = layer_spec
        return specs

    def _step_shardings(self):
        mesh = self.mesh
        specs = self._param_specs()
        if not any(s != P() for table in specs.values()
                   for s in table.values()):
            raise ValueError(
                f"no layer is splittable over {self.tp} model shards "
                "(need 2-D dense weights with divisible dims)")

        def named(spec_tree):
            return jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s), spec_tree,
                is_leaf=lambda x: isinstance(x, P))

        param_sh = named(specs)
        # optimizer state mirrors params leaf-for-leaf; iteration scalar
        # replicates
        upd_sh = {
            k: UpdaterState(hist=param_sh[k], velocity=param_sh[k],
                            iteration=NamedSharding(mesh, P()))
            for k in param_sh
        }
        rep = NamedSharding(mesh, P())
        bsh = NamedSharding(mesh, P(self.axis))
        return ((param_sh, upd_sh, bsh, bsh, rep, rep),
                (param_sh, upd_sh, rep))

    def sharding_summary(self):
        """{layer: {param: spec}} for logging/tests."""
        return {k: {n: str(s) for n, s in t.items()}
                for k, t in self._param_specs().items()}


__all__ = ["TensorParallelTrainer"]
