"""Weight-update (optimizer-state) sharding for data-parallel training.

Technique: "Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training" (Xu et al., arXiv:2004.13336 — the XLA/GSPMD
weight-update sharding that became ZeRO-1): in plain data parallelism
every replica redundantly holds the full optimizer state and applies the
full weight update. Sharding the UPDATE along the replica axis turns the
gradient all-reduce into reduce-scatter + per-shard update + all-gather
of the new params — same math, 1/n the optimizer memory and update FLOPs
per device.

TPU-native construction: no manual collectives. Parameters stay
replicated; the FLAT optimizer state carries a `P("data")` sharding, and
two `with_sharding_constraint`s (flat gradient → sharded, updated flat
params → replicated) let GSPMD place the reduce-scatter/all-gather
exactly as the paper describes. The elementwise update runs on flat
vectors with per-element hyperparameter tables (each layer's lr /
adagrad flag / momentum broadcast over its own slice), reproducing
NetworkGradientUpdater's per-layer GradientAdjustment semantics
bit-for-math — except `constrain_gradient_to_unit_norm`, which needs a
global norm and is rejected.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.optimize.updater import ADAGRAD_EPS
from deeplearning4j_tpu.datasets.device_feed import feed_mask
from deeplearning4j_tpu.parallel.data_parallel import DataParallelTrainer
from deeplearning4j_tpu.parallel.mesh import batch_sharding, replicated

__all__ = ["ShardedUpdateTrainer"]


class ShardedUpdateTrainer(DataParallelTrainer):
    """DataParallelTrainer with ZeRO-1-style sharded optimizer state.

    Same fit() surface; optimizer state lives as flat (padded) vectors
    sharded over the mesh's data axis."""

    def __init__(self, network, mesh=None, axis: str = "data"):
        # per-element hyperparameter tables, built from each layer's conf
        # over its slice of the packed vector (must exist before
        # _build_step runs in the parent constructor)
        self._prep_tables(network)
        super().__init__(network, mesh, axis)
        if any(layer.conf.constrain_gradient_to_unit_norm
               for layer in network.layers):
            raise ValueError(
                "constrain_gradient_to_unit_norm needs a global norm; "
                "use DataParallelTrainer")
        self._flat_state = None

    def _prep_tables(self, network) -> None:
        # ravel_pytree flattens the string-keyed params dict in SORTED KEY
        # order ('0', '1', '10', '11', '2', ...), which diverges from
        # numeric layer order at 11+ layers — the tables must be built in
        # that same flatten order or hyperparameters land on the wrong
        # layers' slices.
        sizes = []
        lrs, adagrads, moms = [], [], []
        self._layer_confs = []
        for key in sorted(network._params):
            layer = network.layers[int(key)]
            flat_i, _ = ravel_pytree(network._params[key])
            sizes.append(flat_i.size)
            c = layer.conf
            self._layer_confs.append(c)
            lrs.append(np.full(flat_i.size, c.lr, np.float32))
            adagrads.append(np.full(flat_i.size, float(c.use_adagrad),
                                    np.float32))
            moms.append(np.full(flat_i.size, c.momentum, np.float32))
        self._sizes = sizes
        self._lr_vec = np.concatenate(lrs)
        self._adagrad_vec = np.concatenate(adagrads)
        self._mom_vec = np.concatenate(moms)

    # ------------------------------------------------------------- padding
    def _pad(self, n: int) -> int:
        return (n + self.n_devices - 1) // self.n_devices * self.n_devices

    def _build_step(self):
        net = self.network
        rep = replicated(self.mesh)
        bsh = batch_sharding(self.mesh, self.axis)
        flat0, unravel = ravel_pytree(net._params)
        n = flat0.size
        n_pad = self._pad(n)
        pad = n_pad - n
        shard = NamedSharding(self.mesh, P(self.axis))

        lr_vec = jnp.asarray(np.pad(self._lr_vec, (0, pad)))
        ada_vec = jnp.asarray(np.pad(self._adagrad_vec, (0, pad)))
        mom_vec = jnp.asarray(np.pad(self._mom_vec, (0, pad)))
        # momentum_after schedules: piecewise per layer on the carried
        # iteration; built dynamically per step below
        offsets = np.cumsum([0, *self._sizes])

        def mom_at(it):
            m = mom_vec
            for i, c in enumerate(self._layer_confs):
                if c.momentum_after:
                    mi = jnp.asarray(c.momentum, jnp.float32)
                    for after, value in sorted(c.momentum_after.items()):
                        mi = jnp.where(it >= after, value, mi)
                    seg = jnp.zeros(n_pad, jnp.float32).at[
                        offsets[i]:offsets[i + 1]].set(1.0)
                    m = m * (1 - seg) + mi * seg
            return m

        def step(params, hist, vel, it, x, labels, rng, n_valid=None):
            # n_valid: device-feed real-example count (rows >= n_valid are
            # shape-bucketing padding — masked from the loss, and the
            # adagrad ÷batchSize uses the real count)
            weights, count = feed_mask(x.shape[0], n_valid)
            if weights is not None:
                count = jnp.maximum(count, 1).astype(jnp.float32)
            score, grads = jax.value_and_grad(net.loss_fn)(
                params, x, labels, rng=rng, training=True, weights=weights)
            flat_g, _ = ravel_pytree(grads)
            flat_g = jnp.pad(flat_g, (0, pad))
            # reduce-scatter point: the gradient becomes replica-sharded
            flat_g = jax.lax.with_sharding_constraint(flat_g, shard)
            hist = hist + ada_vec * jnp.square(flat_g)
            scaled = jnp.where(
                ada_vec > 0,
                lr_vec * flat_g / (jnp.sqrt(jnp.maximum(hist, 0.0))
                                   + ADAGRAD_EPS),
                lr_vec * flat_g)
            vel = mom_at(it) * vel + scaled
            # reference GradientAdjustment divides the FINAL update — the
            # whole velocity — by batchSize on the adagrad branch
            # (GradientUpdater does the same). Dividing only the fresh
            # contribution agrees at constant batch size but diverges
            # from NetworkGradientUpdater on ragged/masked streams where
            # the count varies step to step.
            update = jnp.where(ada_vec > 0, vel / count, vel)
            flat_p, _ = ravel_pytree(params)
            flat_p = jnp.pad(flat_p, (0, pad)) - update
            # all-gather point: updated params become replicated again
            flat_p = jax.lax.with_sharding_constraint(flat_p[:n], rep)
            return unravel(flat_p), hist, vel, it + 1, score

        return jax.jit(
            step,
            in_shardings=(rep, shard, shard, rep, bsh, bsh, rep, rep),
            out_shardings=(rep, shard, shard, rep, rep),
            donate_argnums=(0, 1, 2),
        )

    def fit(self, iterator, epochs: int = 1,
            device_feed: Optional[bool] = None) -> None:
        net = self.network
        feed = self._make_feed(iterator, device_feed)
        flat0, _ = ravel_pytree(net._params)
        n_pad = self._pad(flat0.size)
        if self._flat_state is None:
            shard = NamedSharding(self.mesh, P(self.axis))
            zeros = jnp.zeros(n_pad, jnp.float32)
            self._flat_state = (jax.device_put(zeros, shard),
                                jax.device_put(zeros, shard),
                                jnp.zeros((), jnp.int32))
        hist, vel, it = self._flat_state
        params = net._params
        score = None
        steps = 0
        try:
            with self.mesh:
                for _ in range(epochs):
                    for x, labels, n_valid in self._epoch_batches(iterator,
                                                                  feed):
                        params, hist, vel, it, score = self._step(
                            params, hist, vel, it, x, labels,
                            net.next_key(), n_valid)
                        steps += 1
        finally:
            net._params = params
            self._flat_state = (hist, vel, it)
        if steps:
            for listener in net.listeners:
                listener.iteration_done(net, steps - 1, float(score))
