"""Weight-update (optimizer-state) sharding for data-parallel training.

Technique: "Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training" (Xu et al., arXiv:2004.13336 — the XLA/GSPMD
weight-update sharding that became ZeRO-1): in plain data parallelism
every replica redundantly holds the full optimizer state and applies the
full weight update. Sharding the UPDATE along the replica axis turns the
gradient all-reduce into reduce-scatter + per-shard update + all-gather
of the new params — same math, 1/n the optimizer memory and update FLOPs
per device.

TPU-native construction: no manual collectives. Parameters stay
replicated; the FLAT optimizer state carries a `P("data")` sharding, and
two `with_sharding_constraint`s (flat gradient → sharded, updated flat
params → replicated) let GSPMD place the reduce-scatter/all-gather
exactly as the paper describes. The elementwise update runs on flat
vectors with per-element hyperparameter tables (each layer's lr /
adagrad flag / momentum broadcast over its own slice), reproducing
NetworkGradientUpdater's per-layer GradientAdjustment semantics
bit-for-math — except `constrain_gradient_to_unit_norm`, which needs a
global norm and is rejected.
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.optimize.guardian import (GuardianAbort, advance,
                                                  all_finite, make_guard)
from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.optimize.updater import ADAGRAD_EPS
from deeplearning4j_tpu.datasets.device_feed import feed_mask
from deeplearning4j_tpu.parallel.data_parallel import DataParallelTrainer
from deeplearning4j_tpu.parallel.mesh import batch_sharding, replicated
from deeplearning4j_tpu.telemetry.trace import span

# same families the other trainers publish into (get-or-create by name)
_M_STEPS = telemetry.counter("dl4j_train_steps")
_M_EXAMPLES = telemetry.counter("dl4j_train_examples")
_M_EPOCHS = telemetry.counter("dl4j_train_epochs")
_M_LOSS = telemetry.gauge("dl4j_train_loss")
_M_STEP_S = telemetry.histogram("dl4j_train_step_seconds")

__all__ = ["ShardedUpdateTrainer"]


class ShardedUpdateTrainer(DataParallelTrainer):
    """DataParallelTrainer with ZeRO-1-style sharded optimizer state.

    Same fit() surface; optimizer state lives as flat (padded) vectors
    sharded over the mesh's data axis."""

    def __init__(self, network, mesh=None, axis: str = "data"):
        # per-element hyperparameter tables, built from each layer's conf
        # over its slice of the packed vector (must exist before
        # _build_step runs in the parent constructor)
        self._prep_tables(network)
        super().__init__(network, mesh, axis)
        if any(layer.conf.constrain_gradient_to_unit_norm
               for layer in network.layers):
            raise ValueError(
                "constrain_gradient_to_unit_norm needs a global norm; "
                "use DataParallelTrainer")
        self._flat_state = None

    def _prep_tables(self, network) -> None:
        # ravel_pytree flattens the string-keyed params dict in SORTED KEY
        # order ('0', '1', '10', '11', '2', ...), which diverges from
        # numeric layer order at 11+ layers — the tables must be built in
        # that same flatten order or hyperparameters land on the wrong
        # layers' slices.
        sizes = []
        lrs, adagrads, moms = [], [], []
        self._layer_confs = []
        for key in sorted(network._params):
            layer = network.layers[int(key)]
            flat_i, _ = ravel_pytree(network._params[key])
            sizes.append(flat_i.size)
            c = layer.conf
            self._layer_confs.append(c)
            lrs.append(np.full(flat_i.size, c.lr, np.float32))
            adagrads.append(np.full(flat_i.size, float(c.use_adagrad),
                                    np.float32))
            moms.append(np.full(flat_i.size, c.momentum, np.float32))
        self._sizes = sizes
        self._lr_vec = np.concatenate(lrs)
        self._adagrad_vec = np.concatenate(adagrads)
        self._mom_vec = np.concatenate(moms)

    # ------------------------------------------------------------- padding
    def _pad(self, n: int) -> int:
        return (n + self.n_devices - 1) // self.n_devices * self.n_devices

    def _build_step(self, guarded: bool = False):
        net = self.network
        rep = replicated(self.mesh)
        bsh = batch_sharding(self.mesh, self.axis)
        flat0, unravel = ravel_pytree(net._params)
        n = flat0.size
        n_pad = self._pad(n)
        pad = n_pad - n
        shard = NamedSharding(self.mesh, P(self.axis))

        lr_vec = jnp.asarray(np.pad(self._lr_vec, (0, pad)))
        ada_vec = jnp.asarray(np.pad(self._adagrad_vec, (0, pad)))
        mom_vec = jnp.asarray(np.pad(self._mom_vec, (0, pad)))
        # momentum_after schedules: piecewise per layer on the carried
        # iteration; built dynamically per step below
        offsets = np.cumsum([0, *self._sizes])

        def mom_at(it):
            m = mom_vec
            for i, c in enumerate(self._layer_confs):
                if c.momentum_after:
                    mi = jnp.asarray(c.momentum, jnp.float32)
                    for after, value in sorted(c.momentum_after.items()):
                        mi = jnp.where(it >= after, value, mi)
                    seg = jnp.zeros(n_pad, jnp.float32).at[
                        offsets[i]:offsets[i + 1]].set(1.0)
                    m = m * (1 - seg) + mi * seg
            return m

        def body(params, hist, vel, it, x, labels, rng, n_valid, gstate):
            # n_valid: device-feed real-example count (rows >= n_valid are
            # shape-bucketing padding — masked from the loss, and the
            # adagrad ÷batchSize uses the real count)
            weights, count = feed_mask(x.shape[0], n_valid)
            if weights is not None:
                count = jnp.maximum(count, 1).astype(jnp.float32)
            score, grads = jax.value_and_grad(net.loss_fn)(
                params, x, labels, rng=rng, training=True, weights=weights)
            flat_g, _ = ravel_pytree(grads)
            flat_g = jnp.pad(flat_g, (0, pad))
            # reduce-scatter point: the gradient becomes replica-sharded
            flat_g = jax.lax.with_sharding_constraint(flat_g, shard)
            new_hist = hist + ada_vec * jnp.square(flat_g)
            scaled = jnp.where(
                ada_vec > 0,
                lr_vec * flat_g / (jnp.sqrt(jnp.maximum(new_hist, 0.0))
                                   + ADAGRAD_EPS),
                lr_vec * flat_g)
            new_vel = mom_at(it) * vel + scaled
            # reference GradientAdjustment divides the FINAL update — the
            # whole velocity — by batchSize on the adagrad branch
            # (GradientUpdater does the same). Dividing only the fresh
            # contribution agrees at constant batch size but diverges
            # from NetworkGradientUpdater on ragged/masked streams where
            # the count varies step to step.
            update = jnp.where(ada_vec > 0, new_vel / count, new_vel)
            flat_p, _ = ravel_pytree(params)
            flat_p = jnp.pad(flat_p, (0, pad))
            if gstate is None:
                new_flat_p = flat_p - update
                # all-gather point: updated params replicate again
                out_p = jax.lax.with_sharding_constraint(new_flat_p[:n], rep)
                return unravel(out_p), new_hist, new_vel, it + 1, score
            # guarded: the finite predicate reduces over the SHARDED flat
            # gradient — GSPMD all-reduces the scalar, so every replica
            # sees the same commit/skip decision (the cross-replica
            # agreement of arXiv:2004.13336, for the fault path)
            ok = all_finite(score, flat_g)
            new_flat_p = flat_p - update * gstate.lr_scale
            out_p = jnp.where(ok, new_flat_p, flat_p)
            out_p = jax.lax.with_sharding_constraint(out_p[:n], rep)
            hist = jnp.where(ok, new_hist, hist)
            vel = jnp.where(ok, new_vel, vel)
            it = jnp.where(ok, it + 1, it)
            return unravel(out_p), hist, vel, it, advance(gstate, ok), score

        if not guarded:
            def step(params, hist, vel, it, x, labels, rng, n_valid=None):
                return body(params, hist, vel, it, x, labels, rng, n_valid,
                            None)

            from deeplearning4j_tpu import compilecache
            return compilecache.maybe_wrap(
                jax.jit(
                    step,
                    in_shardings=(rep, shard, shard, rep, bsh, bsh, rep,
                                  rep),
                    out_shardings=(rep, shard, shard, rep, rep),
                    donate_argnums=(0, 1, 2),
                ),
                self._aot_key("step"))

        def gstep(params, hist, vel, it, gstate, x, labels, rng,
                  n_valid=None):
            return body(params, hist, vel, it, x, labels, rng, n_valid,
                        gstate)

        from deeplearning4j_tpu import compilecache
        return compilecache.maybe_wrap(
            jax.jit(
                gstep,
                in_shardings=(rep, shard, shard, rep, rep, bsh, bsh, rep,
                              rep),
                out_shardings=(rep, shard, shard, rep, rep, rep),
                donate_argnums=(0, 1, 2),
            ),
            self._aot_key("gstep"))

    def _build_guarded_step(self):
        return self._build_step(guarded=True)

    def fit(self, iterator, epochs: int = 1,
            device_feed: Optional[bool] = None, guardian=None,
            checkpoint_every: Optional[int] = None, saver=None) -> None:
        """ZeRO-1 fit; guardian/autosave semantics as DataParallelTrainer.
        Autosaves host-gather the replica-sharded flat optimizer state
        into the checkpoint's canonical per-layer form (unpadded — any
        device count restores it); reinstall with
        `restore_flat_state(info['metadata'])` after rebuilding the
        trainer on the restored network (docs/CHECKPOINTS.md)."""
        net = self.network

        def gather(a):
            # multi-host mesh: each process holds only its local shards,
            # and np.asarray on a non-addressable jax.Array raises —
            # allgather the replica-sharded flat vectors first (this is
            # the pod-preemption flush path; correctness over bandwidth)
            if getattr(a, "is_fully_addressable", True):
                return np.asarray(a)
            from jax.experimental import multihost_utils
            return np.asarray(multihost_utils.process_allgather(a,
                                                                tiled=True))

        def save_flat(saver_, position, meta):
            hist_, vel_, it_ = self._flat_state
            meta = dict(meta)
            if (meta.get("save_kind") == "preempt"
                    and not getattr(hist_, "is_fully_addressable", True)):
                # preemption flush: SIGTERM lands on hosts at different
                # batches, so entering the allgather here would mismatch
                # collective order across processes (hang/crash). Save
                # params-only; periodic autosaves (same position on every
                # process) carry the full flat state.
                meta["zero1_flat_state_skipped"] = (
                    "multi-host preemption flush skips the optimizer-state "
                    "allgather; resume optimizer state from the last "
                    "periodic autosave")
            else:
                n = ravel_pytree(net._params)[0].size
                # the gathered vectors land UNPADDED (padding is a
                # property of the SAVING mesh's device count) in the
                # CANONICAL per-layer form on the network — one copy in
                # the checkpoint, restorable bit-identically by
                # DP/TP/single-device runs directly and by any-device-
                # count ZeRO-1 via restore_flat_state (which still also
                # reads the legacy metadata['zero1_flat_state'] blob
                # older checkpoints carry). checkpoint/convert.py keeps
                # this a pure host reshape — no device round trip on the
                # save path.
                from deeplearning4j_tpu.checkpoint.convert import \
                    flat_to_updater_state
                net._updater_state = flat_to_updater_state(
                    gather(hist_)[:n], gather(vel_)[:n], np.asarray(it_),
                    net._params)
            return saver_.save(net, iterator_position=position,
                               metadata=meta)

        guard = make_guard(net, guardian, checkpoint_every, saver,
                           save_fn=save_flat)
        guarded = guard is not None and guard.guarded
        if guarded and self._gstep is None:
            self._gstep = self._build_guarded_step()
        feed = self._make_feed(iterator, device_feed)
        flat0, _ = ravel_pytree(net._params)
        n_pad = self._pad(flat0.size)
        if self._flat_state is None:
            shard = NamedSharding(self.mesh, P(self.axis))
            zeros = jnp.zeros(n_pad, jnp.float32)
            self._flat_state = (jax.device_put(zeros, shard),
                                jax.device_put(zeros, shard),
                                jnp.zeros((), jnp.int32))
        hist, vel, it = self._flat_state
        params = net._params
        score = None
        steps = 0
        ctx = guard if guard is not None else contextlib.nullcontext()
        try:
            with ctx, self.mesh:
                if guarded:
                    guard.arm_once((params, hist, vel, it))
                step_child = _M_STEP_S.labels(source="parallel")
                for _ in range(epochs):
                    _M_EPOCHS.inc()
                    if guard is not None:
                        guard.begin_epoch()
                    for x, labels, n_valid in self._epoch_batches(iterator,
                                                                  feed):
                        t0 = time.perf_counter()
                        if guarded:
                            with span("parallel_train_step", guarded=True):
                                (params, hist, vel, it, gstate,
                                 score) = self._gstep(params, hist, vel, it,
                                                      guard.gstate, x,
                                                      labels, net.next_key(),
                                                      n_valid)
                            try:
                                ((params, hist, vel, it),
                                 _) = guard.post_step((params, hist, vel, it),
                                                      gstate, score)
                            except GuardianAbort as e:
                                params, hist, vel, it = e.last_good
                                raise
                        else:
                            with span("parallel_train_step"):
                                params, hist, vel, it, score = self._step(
                                    params, hist, vel, it, x, labels,
                                    net.next_key(), n_valid)
                        step_child.observe(time.perf_counter() - t0)
                        _M_STEPS.inc()
                        _M_EXAMPLES.inc(x.shape[0])
                        steps += 1
                        if guard is not None:
                            net._params = params
                            self._flat_state = (hist, vel, it)
                            guard.tick()
        finally:
            net._params = params
            self._flat_state = (hist, vel, it)
        if steps and net.listeners:  # float() only where it always was
            score_f = float(score)
            _M_LOSS.set(score_f)
            for listener in net.listeners:
                listener.iteration_done(net, steps - 1, score_f)

    def restore_flat_state(self, metadata: Optional[dict] = None) -> None:
        """Reinstall the optimizer state from a checkpoint, re-sharding
        it over THIS trainer's mesh — the device count/parallelism it
        was saved under no longer matters:

        - `metadata` carrying `zero1_flat_state` (a LEGACY ZeRO-1
          autosave): vectors are taken unpadded (older checkpoints saved
          them padded to the SOURCE mesh — the tail is stripped),
          re-padded to this mesh's width, and re-sharded over the data
          axis.
        - `metadata=None` (or no flat state present): the canonical
          per-layer UpdaterState tree on the network — i.e. a checkpoint
          written by a DP/TP/single-device run — is flattened into the
          ZeRO-1 vectors (checkpoint/convert.py). Bit-identical either
          way: both conversions are pure reshapes.
        """
        net = self.network
        n = ravel_pytree(net._params)[0].size
        state = (metadata or {}).get("zero1_flat_state")
        if state is not None:
            hist = np.asarray(state["hist"])
            vel = np.asarray(state["velocity"])
            it = np.asarray(state["iteration"])
            if hist.size < n or vel.size < n:
                raise ValueError(
                    f"zero1_flat_state packs {min(hist.size, vel.size)} "
                    f"elements but this network packs {n} — checkpoint "
                    "does not match the architecture")
            hist, vel = hist[:n], vel[:n]
        else:
            if net._updater_state is None:
                raise ValueError(
                    "no optimizer state to restore: metadata carries no "
                    "zero1_flat_state and the network has no updater "
                    "state (checkpoint saved before any training step?)")
            from deeplearning4j_tpu.checkpoint.convert import \
                updater_state_to_flat
            hist, vel, it = updater_state_to_flat(net._updater_state,
                                                  net._params)
        pad = self._pad(n) - n
        shard = NamedSharding(self.mesh, P(self.axis))
        self._flat_state = (
            jax.device_put(jnp.asarray(np.pad(hist, (0, pad))), shard),
            jax.device_put(jnp.asarray(np.pad(vel, (0, pad))), shard),
            jnp.asarray(it, jnp.int32))
