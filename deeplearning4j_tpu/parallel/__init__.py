from deeplearning4j_tpu.parallel.mesh import make_mesh  # noqa: F401
from deeplearning4j_tpu.parallel.data_parallel import (  # noqa: F401
    DataParallelTrainer,
)
from deeplearning4j_tpu.parallel.averaging import (  # noqa: F401
    ParameterAveragingTrainer,
)
from deeplearning4j_tpu.parallel import multihost  # noqa: F401
from deeplearning4j_tpu.parallel.sharded_update import (  # noqa: F401
    ShardedUpdateTrainer,
)
from deeplearning4j_tpu.parallel.tensor_parallel import (  # noqa: F401
    TensorParallelTrainer,
)
from deeplearning4j_tpu.parallel import pipeline  # noqa: F401
from deeplearning4j_tpu.parallel import expert_parallel  # noqa: F401
