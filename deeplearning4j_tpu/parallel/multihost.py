"""Multi-host bootstrap: the distributed communication backend.

Parity: the reference's cross-machine data plane was Akka remoting +
Hazelcast replication (params serialized over TCP, SURVEY §5
communication backend); training-time parameter exchange on TPU instead
rides XLA collectives — ICI within a slice, DCN across slices/hosts —
once every process has joined one JAX distributed runtime.

This module owns that join step and the resulting global mesh:
`initialize` wraps `jax.distributed.initialize` (coordinator bootstrap —
the role ZooKeeper/Akka seed nodes played); `global_data_mesh` builds a
Mesh over ALL processes' devices, so `DataParallelTrainer` and
`shard_map` collectives (psum/pmean/ppermute) span hosts with no code
changes — each process feeds its local shard, XLA moves bytes over
ICI/DCN (Gloo on CPU test clusters).

Validated without TPU pods by `tests/test_multihost.py`: two CPU
processes join one runtime and train data-parallel to identical params.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh

log = logging.getLogger(__name__)

__all__ = ["initialize", "global_data_mesh", "process_info",
           "local_batch_slice"]


def initialize(coordinator_address: str, num_processes: int,
               process_id: int, **kw) -> None:
    """Join this process to the JAX distributed runtime (reference
    equivalent: worker joining the Akka cluster via seed node /
    ZooKeeper-registered address). Call once, before any backend use."""
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id, **kw)
    log.info("joined distributed runtime: process %d/%d, %d global devices",
             process_id, num_processes, len(jax.devices()))


def global_data_mesh(axis: str = "data") -> Mesh:
    """One data axis over every device of every joined process."""
    return Mesh(np.array(jax.devices()), (axis,))


def process_info() -> Dict[str, int]:
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }


def local_batch_slice(n: int, process_index: Optional[int] = None,
                      process_count: Optional[int] = None) -> slice:
    """This process's contiguous share of a global batch of n examples
    (the per-host data split the reference's JobIterator did per worker).
    n must divide evenly by the process count."""
    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    if n % pc:
        raise ValueError(f"global batch {n} not divisible by "
                         f"{pc} processes")
    per = n // pc
    return slice(pi * per, (pi + 1) * per)
