"""Multi-host bootstrap: the distributed communication backend.

Parity: the reference's cross-machine data plane was Akka remoting +
Hazelcast replication (params serialized over TCP, SURVEY §5
communication backend); training-time parameter exchange on TPU instead
rides XLA collectives — ICI within a slice, DCN across slices/hosts —
once every process has joined one JAX distributed runtime.

This module owns that join step and the resulting global mesh:
`initialize` wraps `jax.distributed.initialize` (coordinator bootstrap —
the role ZooKeeper/Akka seed nodes played); `global_data_mesh` builds a
Mesh over ALL processes' devices, so `DataParallelTrainer` and
`shard_map` collectives (psum/pmean/ppermute) span hosts with no code
changes — each process feeds its local shard, XLA moves bytes over
ICI/DCN (Gloo on CPU test clusters).

Validated without TPU pods by `tests/test_multihost.py`: two CPU
processes join one runtime and train data-parallel to identical params.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh

log = logging.getLogger(__name__)

__all__ = ["initialize", "global_data_mesh", "process_info",
           "local_batch_slice"]


def _wait_for_coordinator(coordinator_address: str, process_id: int,
                          num_processes: int, timeout: float,
                          retries: int, backoff: float) -> None:
    """Probe the coordinator's TCP port before handing control to
    jax.distributed.initialize. This is what makes a dead coordinator a
    catchable Python error: jax's own deadline path ends in an abseil
    CHECK-failure that KILLS the process (client.h "Terminating process
    ... DEADLINE_EXCEEDED"), which no retry wrapper can recover. Each
    attempt polls the port for up to `timeout` seconds (covers a
    coordinator that is still starting); attempts back off
    exponentially."""
    import socket

    host, _, port_s = coordinator_address.rpartition(":")
    # gRPC-style bracketed IPv6 ("[::1]:1234"): sockets want the bare host
    if host.startswith("[") and host.endswith("]"):
        host = host[1:-1]
    try:
        port = int(port_s)
    except ValueError:
        raise ValueError(
            f"coordinator_address must be host:port, got "
            f"{coordinator_address!r}") from None
    attempt = 0
    last_err: Optional[Exception] = None
    while True:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                with socket.create_connection(
                        (host or "127.0.0.1", port),
                        timeout=max(0.1, min(2.0, timeout))):
                    return
            except OSError as e:  # refused (instant) or unreachable
                last_err = e
                time.sleep(min(0.5, timeout / 4))
        attempt += 1
        if attempt > retries:
            raise RuntimeError(
                f"could not join the JAX distributed runtime as process "
                f"{process_id}/{num_processes}: coordinator "
                f"{coordinator_address} did not respond within "
                f"{timeout:g}s ({attempt} attempt(s)); is the coordinator "
                f"process up and the address reachable? "
                f"[{last_err}]") from last_err
        delay = backoff * (2 ** (attempt - 1))
        log.warning(
            "coordinator %s unreachable (attempt %d/%d: %s); retrying "
            "in %.1fs", coordinator_address, attempt, retries + 1,
            last_err, delay)
        time.sleep(delay)


def initialize(coordinator_address: str, num_processes: int,
               process_id: int, *, timeout: float = 300.0,
               retries: int = 0, backoff: float = 2.0, **kw) -> None:
    """Join this process to the JAX distributed runtime (reference
    equivalent: worker joining the Akka cluster via seed node /
    ZooKeeper-registered address). Call once, before any backend use.

    A dead or unreachable coordinator fails with a catchable, BOUNDED
    error naming the address, instead of jax's hang that ends in a
    process-killing CHECK failure: non-coordinator processes first probe
    the coordinator port (up to `retries`+1 attempts of `timeout`
    seconds each, exponential `backoff` between them — tolerating a
    coordinator that boots late), and only then enter
    jax.distributed.initialize, whose own barrier stays bounded by
    initialization_timeout (defaulted to `timeout`). The default
    `timeout` matches jax's 300 s so slow cluster bring-up keeps
    working; drop it (e.g. timeout=30, retries=2) where fail-fast
    matters more."""
    if process_id != 0:  # process 0 IS the coordinator: it binds the port
        _wait_for_coordinator(coordinator_address, process_id,
                              num_processes, timeout, retries, backoff)
    kw.setdefault("initialization_timeout", max(1, int(timeout)))
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id, **kw)
    log.info("joined distributed runtime: process %d/%d, %d global devices",
             process_id, num_processes, len(jax.devices()))


def global_data_mesh(axis: str = "data") -> Mesh:
    """One data axis over every device of every joined process."""
    return Mesh(np.array(jax.devices()), (axis,))


def process_info() -> Dict[str, int]:
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }


def local_batch_slice(n: int, process_index: Optional[int] = None,
                      process_count: Optional[int] = None) -> slice:
    """This process's contiguous share of a global batch of n examples
    (the per-host data split the reference's JobIterator did per worker).
    n must divide evenly by the process count."""
    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    if n % pc:
        raise ValueError(f"global batch {n} not divisible by "
                         f"{pc} processes")
    per = n // pc
    return slice(pi * per, (pi + 1) * per)
