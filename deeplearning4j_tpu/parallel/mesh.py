"""Device-mesh helpers.

The TPU-native replacement for the reference's cluster formation (Akka
cluster join, DeepLearning4jDistributed.java:143-210; Spark context; YARN
container allocation): a `jax.sharding.Mesh` over the slice's chips, with
named axes for data/model/pipeline parallelism. Collectives ride ICI inside
a slice and DCN across slices — no NCCL/MPI, XLA inserts them from sharding
annotations.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: F401

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(axes: Optional[Dict[str, int]] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a Mesh. Default: all local devices on one data axis.

    `axes` maps axis name -> size; sizes must multiply to the device count
    (one axis may be -1 to infer).
    """
    devices = list(devices if devices is not None else jax.devices())
    if axes is None:
        axes = {DATA_AXIS: len(devices)}
    names = list(axes.keys())
    sizes = list(axes.values())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = len(devices) // known
    total = int(np.prod(sizes))
    if total != len(devices):
        raise ValueError(f"Mesh axes {dict(zip(names, sizes))} need {total} "
                         f"devices, have {len(devices)}")
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, tuple(names))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """Shard the leading (batch) dimension over `axis`."""
    return NamedSharding(mesh, P(axis))
