"""Pipeline (stage) parallelism — beyond parity.

The reference is data-parallel only (SURVEY §2.8: TP/PP/SP "ABSENT in
reference"). This is GPipe-style microbatch pipelining expressed the TPU
way: stages live on a `pipe` mesh axis, activations travel between
neighboring stages via `ppermute` over ICI, and the schedule is a
`lax.scan` over S + M - 1 ticks (S stages, M microbatches) — the
pipeline bubble is exactly the (S-1)-tick fill/drain the schedule
implies. Autodiff runs straight through the scan + ppermute (the
transpose of a ppermute is the reverse ppermute), so one `jax.grad`
trains the whole pipeline; composing a `data` axis into the mesh gives
pp x dp with the gradient psum inserted by shard_map's transpose.

Scope: uniform stages (each stage = one dense block of identical shape,
params stacked on a leading stage axis). That is the honest shape of
GPipe — heterogeneous stages need per-stage programs, which is a
compiler-level feature, not a framework primitive.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.4.35 exposes shard_map at top level
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

PIPE_AXIS = "pipe"


def init_pipeline_params(key, n_stages: int, width: int, scale=0.5):
    """Uniform stack: W (S, d, d), b (S, 1, d)."""
    kw, _ = jax.random.split(key)
    w = jax.random.uniform(kw, (n_stages, width, width), jnp.float32,
                           -scale / width, scale / width)
    return {"W": w, "b": jnp.zeros((n_stages, 1, width), jnp.float32)}


def sequential_apply(params, x, act: Callable = jnp.tanh):
    """Ground truth: apply the S stacked stages one after another.
    x: (..., width)."""
    s = params["W"].shape[0]
    for i in range(s):
        x = act(x @ params["W"][i] + params["b"][i])
    return x


def pipeline_apply(params, xm, mesh: Mesh, axis: str = PIPE_AXIS,
                   act: Callable = jnp.tanh,
                   data_axis: Optional[str] = None):
    """Run microbatches through the stage pipeline.

    params: {"W": (S, d, d), "b": (S, 1, d)} sharded over `axis`;
    xm: (M, B, d) microbatches (B sharded over `data_axis` if given).
    Returns (M, B, d) pipeline outputs == sequential_apply per microbatch.
    """
    s = int(mesh.shape[axis])
    if params["W"].shape[0] != s:
        raise ValueError(f"{params['W'].shape[0]} stages vs pipe={s}")
    m = xm.shape[0]
    perm = [(i, (i + 1) % s) for i in range(s)]

    def per_stage(p, xs):
        # local views: p leaves have a leading stage axis of length 1
        w = p["W"][0]
        b = p["b"][0]
        idx = jax.lax.axis_index(axis)
        # mark the (replicated) microbatches as device-varying over the
        # pipe axis so the scan carry types stay consistent once values
        # mix with the per-stage params (new shard_map's vma tracking;
        # a no-op under the older experimental API)
        if hasattr(jax.lax, "pcast"):
            xs = jax.lax.pcast(xs, (axis,), to="varying")
        elif hasattr(jax.lax, "pvary"):  # pre-pcast jax
            xs = jax.lax.pvary(xs, (axis,))
        buf = jnp.zeros_like(xs[0])   # activation arriving from the left
        outs = jnp.zeros_like(xs)     # last stage's collected outputs

        def tick(carry, t):
            buf, outs = carry
            # stage 0 ingests microbatch t while they last; later stages
            # consume what the previous tick's ppermute delivered
            feed = jax.lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, m - 1), keepdims=False)
            inp = jnp.where((idx == 0) & (t < m), feed, buf)
            out = act(inp @ w + b)
            nxt = jax.lax.ppermute(out, axis, perm)
            # the LAST stage finishes microbatch t-(S-1) at this tick
            mb = t - (s - 1)
            done = (idx == s - 1) & (mb >= 0)
            slot = jnp.clip(mb, 0, m - 1)
            cur = jax.lax.dynamic_index_in_dim(outs, slot, keepdims=False)
            outs = jax.lax.dynamic_update_index_in_dim(
                outs, jnp.where(done, out, cur), slot, axis=0)
            return (nxt, outs), None

        (_, outs), _ = jax.lax.scan(tick, (buf, outs),
                                    jnp.arange(s + m - 1))
        # outputs exist only on the last stage; psum with masking
        # broadcasts them pipeline-wide (zero elsewhere)
        return jax.lax.psum(jnp.where(idx == s - 1, outs, 0.0), axis)

    batch_dim = P(*([None, data_axis] if data_axis else [None]))
    return shard_map(
        per_stage, mesh=mesh,
        in_specs=(P(axis), batch_dim),
        out_specs=batch_dim,
    )(params, xm)


def pipeline_grad_step(params, xm, ym, mesh: Mesh, axis: str = PIPE_AXIS,
                       lr: float = 0.1, act: Callable = jnp.tanh,
                       data_axis: Optional[str] = None):
    """One SGD step through the pipeline (mean-squared error over all
    microbatches); returns (params, loss). Grad flows backward through
    the scan/ppermute schedule — the pp analogue of backprop's reverse
    pipeline pass."""

    def loss_fn(p):
        out = pipeline_apply(p, xm, mesh, axis, act, data_axis)
        return jnp.mean((out - ym) ** 2)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
    return params, loss


__all__ = ["PIPE_AXIS", "init_pipeline_params", "sequential_apply",
           "pipeline_apply", "pipeline_grad_step"]
