"""Data-parallel training over a device mesh.

This replaces the reference's four data-parallel runtimes (Akka iterative
reduce, Spark fold/average, YARN Avro supersteps, in-process Parallelization —
SURVEY §2.8) with two TPU-native modes:

1. `DataParallelTrainer` — per-step synchronous DP: batch sharded over the
   `data` mesh axis, params replicated; XLA inserts the gradient all-reduce
   over ICI from the sharding annotations. Mathematically the tight-sync
   version of the reference's `IterativeReduceWorkRouter` (all workers report
   every wave, akka workrouter/IterativeReduceWorkRouter.java:46).

2. `ParameterAveragingTrainer` (parallel/averaging.py) — epoch-wave parameter
   averaging for behavioral parity with `MultiLayerNetwork.merge`/
   `INDArrayAggregator` (each replica takes K local steps, then params are
   pmean-averaged).
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.datasets.device_feed import DeviceFeed, feed_mask
from deeplearning4j_tpu.telemetry.trace import span
from deeplearning4j_tpu.optimize.guardian import (GuardianAbort,
                                                  guarded_update, make_guard)
from deeplearning4j_tpu.optimize.updater import NetworkGradientUpdater
from deeplearning4j_tpu.parallel.mesh import (
    DATA_AXIS,
    batch_sharding,
    make_mesh,
    replicated,
)

# the trainers share the nn.multilayer step/example counters (same
# metric names, get-or-create) but tag their step-time source so a DP
# dispatch loop is distinguishable from the single-chip fit loop
_M_STEPS = telemetry.counter(
    "dl4j_train_steps", "supervised train steps dispatched")
_M_EXAMPLES = telemetry.counter(
    "dl4j_train_examples", "example rows dispatched (incl. bucket padding)")
_M_EPOCHS = telemetry.counter("dl4j_train_epochs", "training epochs run")
_M_LOSS = telemetry.gauge(
    "dl4j_train_loss", "last host-synced training score")
# same family nn.multilayer registers (get-or-create by name)
_M_STEP_S = telemetry.histogram("dl4j_train_step_seconds")


class DataParallelTrainer:
    """Per-step synchronous data parallelism for a MultiLayerNetwork."""

    def __init__(self, network, mesh: Optional[jax.sharding.Mesh] = None,
                 axis: str = DATA_AXIS):
        self.network = network
        self.mesh = mesh if mesh is not None else make_mesh()
        self.axis = axis
        self.n_devices = int(np.prod(self.mesh.devices.shape))
        self.updater = NetworkGradientUpdater.for_network(network)
        self._step = self._build_step()
        self._gstep = None  # guarded variant, built on first guarded fit

    def _step_fn(self, guarded: bool = False):
        """The shared train-step body; subclasses vary only shardings.
        `n_valid` is None (legacy pad_batch path — bit-identical program)
        or a traced int32 real-example count from the device feed: rows
        >= n_valid are bucketing padding, masked out of the loss and the
        updater's ÷batchSize.

        `guarded` adds the guardian commit (optimize/guardian.py): the
        all-leaves-finite predicate is reduced over the GLOBAL (already
        all-reduced under GSPMD) gradients, so every replica computes the
        same scalar and the whole mesh commits or skips the update
        together — no replica can diverge from the others."""
        net = self.network
        updater = self.updater

        def body(params, upd_state, x, labels, rng, n_valid, gstate):
            weights, count = feed_mask(x.shape[0], n_valid)
            score, grads = jax.value_and_grad(net.loss_fn)(
                params, x, labels, rng=rng, training=True, weights=weights)
            updates, new_state = updater.update(grads, upd_state, params,
                                                count)
            if gstate is None:
                params = jax.tree_util.tree_map(lambda p, u: p - u, params,
                                                updates)
                return params, new_state, score
            params, upd_state, gstate = guarded_update(
                params, upd_state, updates, new_state, gstate, score, grads)
            return params, upd_state, gstate, score

        if not guarded:
            def step(params, upd_state, x, labels, rng, n_valid=None):
                return body(params, upd_state, x, labels, rng, n_valid, None)

            return step

        def gstep(params, upd_state, gstate, x, labels, rng, n_valid=None):
            return body(params, upd_state, x, labels, rng, n_valid, gstate)

        return gstep

    def _aot_key(self, tag: str) -> Optional[str]:
        """Persistent-compile-cache key (docs/WARMUP.md): config digest
        + class (subclasses change shardings) + mesh/axis + device set
        — serialized executables are device- and sharding-bound."""
        from deeplearning4j_tpu import compilecache

        if compilecache.active_compiler() is None:
            return None
        try:
            digest = compilecache.config_digest(self.network.to_json())
        except Exception:
            return None
        shape = "x".join(str(s) for s in self.mesh.devices.shape)
        return (f"{type(self).__name__}.{tag}:{digest}|mesh={shape}"
                f"|axis={self.axis}"
                f"|dev={jax.devices()[0]}x{self.n_devices}")

    def _step_shardings(self):
        """(in_shardings, out_shardings) for (params, upd_state, x,
        labels, rng, n_valid) -> (params, upd_state, score)."""
        rep = replicated(self.mesh)
        bsh = batch_sharding(self.mesh, self.axis)
        return (rep, rep, bsh, bsh, rep, rep), (rep, rep, rep)

    def _build_step(self):
        ins, outs = self._step_shardings()
        # donate params/updater state (outputs alias their HBM; fit()
        # rebinds both from the outputs every step)
        from deeplearning4j_tpu import compilecache
        return compilecache.maybe_wrap(
            jax.jit(
                self._step_fn(),
                in_shardings=ins,
                out_shardings=outs,
                donate_argnums=(0, 1),
            ),
            self._aot_key("step"))

    def _build_guarded_step(self):
        """The guarded step under the subclass's own shardings: the
        GuardianState carry slots in replicated after (params, state)."""
        ins, outs = self._step_shardings()
        rep = replicated(self.mesh)
        from deeplearning4j_tpu import compilecache
        return compilecache.maybe_wrap(
            jax.jit(
                self._step_fn(guarded=True),
                in_shardings=(ins[0], ins[1], rep, *ins[2:]),
                out_shardings=(outs[0], outs[1], rep, outs[2]),
                donate_argnums=(0, 1),
            ),
            self._aot_key("gstep"))

    def pad_batch(self, x: np.ndarray, labels: np.ndarray):
        """Pad the batch to a multiple of the mesh's data-axis size (static
        shapes keep XLA from recompiling; padding rows get zero weight via
        duplication — negligible for throughput training)."""
        n = x.shape[0]
        rem = n % self.n_devices
        if rem:
            pad = self.n_devices - rem
            idx = np.arange(pad) % n  # tile when pad > n (tiny last batch)
            x = np.concatenate([x, x[idx]])
            labels = np.concatenate([labels, labels[idx]])
        return x, labels

    def _make_feed(self, iterator, device_feed) -> Optional[DeviceFeed]:
        """The per-replica device feed for fit(): buckets aligned to the
        data-axis size (equal shards), features/labels device_put with the
        batch sharding so the H2D transfer lands pre-sharded and
        prefetches ahead of the step. None = legacy pad_batch path."""
        # the batch only shards over the DATA axis — divisibility by the
        # full device count would over-pad (and over-reject) on tp x dp
        # meshes where model shards don't split the batch
        data_shards = int(self.mesh.shape[self.axis])
        if isinstance(iterator, DeviceFeed):
            bad = [b for b in iterator.buckets if b % data_shards]
            if bad:
                # fail here with the real constraint, not later with an
                # opaque GSPMD divisibility error at step dispatch
                raise ValueError(
                    f"DeviceFeed buckets {bad} are not multiples of the "
                    f"data-axis size {data_shards}; build the feed "
                    f"with align={data_shards} (or let the trainer "
                    "wrap the raw iterator itself)")
            return iterator
        if device_feed is False:
            return None
        if device_feed is None and jax.process_count() > 1 \
                and jax.devices()[0].platform == "cpu":
            # Gloo/CPU test clusters cannot device_put host data against a
            # cross-process sharding ("Multiprocess computations aren't
            # implemented on the CPU backend" from the consistency check
            # inside device_put); the legacy path feeds host numpy straight
            # into the jitted step, which shards it correctly. Explicit
            # device_feed=True keeps the override for backends that can.
            return None
        return DeviceFeed(iterator, align=data_shards,
                          sharding=batch_sharding(self.mesh, self.axis))

    def _epoch_batches(self, iterator, feed):
        """One epoch of (x, labels, n_valid) device triples."""
        if feed is not None:
            for fb in feed:
                yield fb.features, fb.labels, fb.n_valid
            return
        iterator.reset()
        for ds in iterator:
            x, labels = self.pad_batch(np.asarray(ds.features),
                                       np.asarray(ds.labels))
            yield jnp.asarray(x), jnp.asarray(labels), None

    def fit(self, iterator, epochs: int = 1,
            device_feed: Optional[bool] = None, guardian=None,
            checkpoint_every: Optional[int] = None, saver=None) -> None:
        """Data-parallel fit. `guardian=`/`checkpoint_every=`/`saver=`
        arm the training guardian exactly as in MultiLayerNetwork.fit —
        the guarded commit decision is computed from the globally
        all-reduced gradients, so all replicas commit or skip each step
        together (docs/FAULT_TOLERANCE.md)."""
        net = self.network
        guard = make_guard(net, guardian, checkpoint_every, saver)
        guarded = guard is not None and guard.guarded
        if guarded and self._gstep is None:
            self._gstep = self._build_guarded_step()
        feed = self._make_feed(iterator, device_feed)
        upd_state = (net._updater_state if net._updater_state is not None
                     else self.updater.init(net._params))
        params = net._params
        score = None
        steps = 0
        ctx = guard if guard is not None else contextlib.nullcontext()
        try:
            with ctx, self.mesh:
                if guarded:
                    guard.arm_once((params, upd_state))
                step_child = _M_STEP_S.labels(source="parallel")
                for _ in range(epochs):
                    _M_EPOCHS.inc()
                    if guard is not None:
                        guard.begin_epoch()
                    for x, labels, n_valid in self._epoch_batches(iterator,
                                                                  feed):
                        t0 = time.perf_counter()
                        if guarded:
                            with span("parallel_train_step", guarded=True):
                                params, upd_state, gstate, score = \
                                    self._gstep(
                                        params, upd_state, guard.gstate, x,
                                        labels, net.next_key(), n_valid)
                            try:
                                ((params, upd_state),
                                 _) = guard.post_step((params, upd_state),
                                                      gstate, score)
                            except GuardianAbort as e:
                                params, upd_state = e.last_good
                                raise
                        else:
                            with span("parallel_train_step"):
                                params, upd_state, score = self._step(
                                    params, upd_state, x, labels,
                                    net.next_key(), n_valid)
                        step_child.observe(time.perf_counter() - t0)
                        _M_STEPS.inc()
                        _M_EXAMPLES.inc(x.shape[0])
                        steps += 1
                        if guard is not None:
                            # keep the net's view current so autosave /
                            # preemption flush checkpoint the live state
                            net._params = params
                            net._updater_state = upd_state
                            guard.tick()
        finally:
            # the step donates the params/state passed in — the net must
            # always point at the live outputs, even on an interrupted fit
            net._params = params
            net._updater_state = upd_state
        if steps and net.listeners:  # float() only where it always was:
            score_f = float(score)   # no-listener fits stay sync-free
            _M_LOSS.set(score_f)
            for listener in net.listeners:
                listener.iteration_done(net, steps - 1, score_f)
