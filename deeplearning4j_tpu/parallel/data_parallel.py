"""Data-parallel training over a device mesh.

This replaces the reference's four data-parallel runtimes (Akka iterative
reduce, Spark fold/average, YARN Avro supersteps, in-process Parallelization —
SURVEY §2.8) with two TPU-native modes:

1. `DataParallelTrainer` — per-step synchronous DP: batch sharded over the
   `data` mesh axis, params replicated; XLA inserts the gradient all-reduce
   over ICI from the sharding annotations. Mathematically the tight-sync
   version of the reference's `IterativeReduceWorkRouter` (all workers report
   every wave, akka workrouter/IterativeReduceWorkRouter.java:46).

2. `ParameterAveragingTrainer` (parallel/averaging.py) — epoch-wave parameter
   averaging for behavioral parity with `MultiLayerNetwork.merge`/
   `INDArrayAggregator` (each replica takes K local steps, then params are
   pmean-averaged).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.datasets.device_feed import DeviceFeed, feed_mask
from deeplearning4j_tpu.optimize.updater import NetworkGradientUpdater
from deeplearning4j_tpu.parallel.mesh import (
    DATA_AXIS,
    batch_sharding,
    make_mesh,
    replicated,
)


class DataParallelTrainer:
    """Per-step synchronous data parallelism for a MultiLayerNetwork."""

    def __init__(self, network, mesh: Optional[jax.sharding.Mesh] = None,
                 axis: str = DATA_AXIS):
        self.network = network
        self.mesh = mesh if mesh is not None else make_mesh()
        self.axis = axis
        self.n_devices = int(np.prod(self.mesh.devices.shape))
        self.updater = NetworkGradientUpdater.for_network(network)
        self._step = self._build_step()

    def _step_fn(self):
        """The shared train-step body; subclasses vary only shardings.
        `n_valid` is None (legacy pad_batch path — bit-identical program)
        or a traced int32 real-example count from the device feed: rows
        >= n_valid are bucketing padding, masked out of the loss and the
        updater's ÷batchSize."""
        net = self.network
        updater = self.updater

        def step(params, upd_state, x, labels, rng, n_valid=None):
            weights, count = feed_mask(x.shape[0], n_valid)
            score, grads = jax.value_and_grad(net.loss_fn)(
                params, x, labels, rng=rng, training=True, weights=weights)
            updates, upd_state = updater.update(grads, upd_state, params,
                                                count)
            params = jax.tree_util.tree_map(lambda p, u: p - u, params, updates)
            return params, upd_state, score

        return step

    def _step_shardings(self):
        """(in_shardings, out_shardings) for (params, upd_state, x,
        labels, rng, n_valid) -> (params, upd_state, score)."""
        rep = replicated(self.mesh)
        bsh = batch_sharding(self.mesh, self.axis)
        return (rep, rep, bsh, bsh, rep, rep), (rep, rep, rep)

    def _build_step(self):
        ins, outs = self._step_shardings()
        # donate params/updater state (outputs alias their HBM; fit()
        # rebinds both from the outputs every step)
        return jax.jit(
            self._step_fn(),
            in_shardings=ins,
            out_shardings=outs,
            donate_argnums=(0, 1),
        )

    def pad_batch(self, x: np.ndarray, labels: np.ndarray):
        """Pad the batch to a multiple of the mesh's data-axis size (static
        shapes keep XLA from recompiling; padding rows get zero weight via
        duplication — negligible for throughput training)."""
        n = x.shape[0]
        rem = n % self.n_devices
        if rem:
            pad = self.n_devices - rem
            idx = np.arange(pad) % n  # tile when pad > n (tiny last batch)
            x = np.concatenate([x, x[idx]])
            labels = np.concatenate([labels, labels[idx]])
        return x, labels

    def _make_feed(self, iterator, device_feed) -> Optional[DeviceFeed]:
        """The per-replica device feed for fit(): buckets aligned to the
        data-axis size (equal shards), features/labels device_put with the
        batch sharding so the H2D transfer lands pre-sharded and
        prefetches ahead of the step. None = legacy pad_batch path."""
        if isinstance(iterator, DeviceFeed):
            bad = [b for b in iterator.buckets if b % self.n_devices]
            if bad:
                # fail here with the real constraint, not later with an
                # opaque GSPMD divisibility error at step dispatch
                raise ValueError(
                    f"DeviceFeed buckets {bad} are not multiples of the "
                    f"data-axis size {self.n_devices}; build the feed "
                    f"with align={self.n_devices} (or let the trainer "
                    "wrap the raw iterator itself)")
            return iterator
        if device_feed is False:
            return None
        return DeviceFeed(iterator, align=self.n_devices,
                          sharding=batch_sharding(self.mesh, self.axis))

    def _epoch_batches(self, iterator, feed):
        """One epoch of (x, labels, n_valid) device triples."""
        if feed is not None:
            for fb in feed:
                yield fb.features, fb.labels, fb.n_valid
            return
        iterator.reset()
        for ds in iterator:
            x, labels = self.pad_batch(np.asarray(ds.features),
                                       np.asarray(ds.labels))
            yield jnp.asarray(x), jnp.asarray(labels), None

    def fit(self, iterator, epochs: int = 1,
            device_feed: Optional[bool] = None) -> None:
        net = self.network
        feed = self._make_feed(iterator, device_feed)
        upd_state = (net._updater_state if net._updater_state is not None
                     else self.updater.init(net._params))
        params = net._params
        score = None
        steps = 0
        try:
            with self.mesh:
                for _ in range(epochs):
                    for x, labels, n_valid in self._epoch_batches(iterator,
                                                                  feed):
                        params, upd_state, score = self._step(
                            params, upd_state, x, labels, net.next_key(),
                            n_valid)
                        steps += 1
        finally:
            # the step donates the params/state passed in — the net must
            # always point at the live outputs, even on an interrupted fit
            net._params = params
            net._updater_state = upd_state
        if steps:
            for listener in net.listeners:
                listener.iteration_done(net, steps - 1, float(score))
