"""Expert (MoE) parallelism — beyond parity.

The reference predates mixture-of-experts entirely (SURVEY §2.8). This
is the TPU-native expert-parallel primitive completing the mesh-axis
family (dp/sp/tp/pp/ep): experts live sharded on an `expert` mesh axis,
tokens are gated top-1, and each device computes its local experts'
contribution for the tokens routed to them, combined with one `psum`
over the expert axis.

Design notes:
- Gating is a learned linear router with top-1 (switch-style) hard
  assignment; the gate probability scales the expert output so the
  router receives gradient (the straight-through-free formulation
  switch transformers use).
- Dispatch is the dense/masked formulation: every device multiplies the
  full token batch masked down to its experts' tokens. No token
  dropping, no capacity factor, deterministic — the right baseline for
  correctness and small expert counts; capacity-based all-to-all
  dispatch is a bandwidth optimization on top, not a semantic change.
- A `data` axis composes: tokens shard over `data`, experts over
  `expert`, giving ep x dp on one 2-D mesh (`jax.grad` handles the
  psum transposes).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.4.35 exposes shard_map at top level
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

EXPERT_AXIS = "expert"


def init_moe_params(key, n_experts: int, d_in: int, d_hidden: int,
                    scale: float = 0.5):
    """Router + per-expert 2-layer MLP. W1: (E, d_in, d_hidden),
    W2: (E, d_hidden, d_in) — a standard MoE FFN block."""
    kg, k1, k2 = jax.random.split(key, 3)
    u = lambda k, shape, d: jax.random.uniform(  # noqa: E731
        k, shape, jnp.float32, -scale / d, scale / d)
    return {
        "gate": u(kg, (d_in, n_experts), d_in),
        "W1": u(k1, (n_experts, d_in, d_hidden), d_in),
        "b1": jnp.zeros((n_experts, 1, d_hidden), jnp.float32),
        "W2": u(k2, (n_experts, d_hidden, d_in), d_hidden),
        "b2": jnp.zeros((n_experts, 1, d_in), jnp.float32),
    }


def _expert_ffn(w1, b1, w2, b2, x, act):
    return act(x @ w1 + b1) @ w2 + b2


def moe_reference(params, x, act: Callable = jnp.tanh):
    """Unsharded ground truth: top-1 gate, run every expert densely,
    combine. x: (N, d_in)."""
    logits = x @ params["gate"]                      # (N, E)
    probs = jax.nn.softmax(logits, axis=-1)
    choice = jnp.argmax(logits, axis=-1)             # (N,)
    n_experts = params["W1"].shape[0]
    out = jnp.zeros_like(x)
    for e in range(n_experts):
        mask = (choice == e)[:, None]
        y = _expert_ffn(params["W1"][e], params["b1"][e],
                        params["W2"][e], params["b2"][e], x, act)
        out = out + jnp.where(mask, probs[:, e:e + 1] * y, 0.0)
    return out


def moe_apply(params, x, mesh: Mesh, axis: str = EXPERT_AXIS,
              act: Callable = jnp.tanh,
              data_axis: Optional[str] = None):
    """Expert-parallel forward: experts sharded over `axis`, tokens
    (optionally) sharded over `data_axis`; one psum combines the local
    expert contributions. Matches moe_reference exactly."""
    ep = int(mesh.shape[axis])
    n_experts = params["W1"].shape[0]
    if n_experts % ep:
        raise ValueError(f"{n_experts} experts not divisible by "
                         f"expert-axis size {ep}")
    local = n_experts // ep

    def per_device(p, xb):
        # p's expert leaves have leading dim n_experts/ep; gate is
        # replicated so routing is identical everywhere
        logits = xb @ p["gate"]                      # (n_local_tokens, E)
        probs = jax.nn.softmax(logits, axis=-1)
        choice = jnp.argmax(logits, axis=-1)
        first = jax.lax.axis_index(axis) * local
        out = jnp.zeros_like(xb)
        for j in range(local):
            e = first + j
            mask = choice == e
            y = _expert_ffn(p["W1"][j], p["b1"][j], p["W2"][j],
                            p["b2"][j], xb, act)
            # unrouted tokens are zeroed by the gate mask, so the
            # psum-combined result equals the dense reference
            gp = jnp.where(mask, jnp.take(probs, e, axis=1), 0.0)
            out = out + gp[:, None] * y
        return jax.lax.psum(out, axis)

    param_specs = {"gate": P(), "W1": P(axis), "b1": P(axis),
                   "W2": P(axis), "b2": P(axis)}
    x_spec = P(data_axis) if data_axis else P()
    return shard_map(
        per_device, mesh=mesh,
        in_specs=(param_specs, x_spec),
        out_specs=x_spec,
    )(params, x)


def moe_apply_a2a(params, x, mesh: Mesh, axis: str = EXPERT_AXIS,
                  act: Callable = jnp.tanh,
                  data_axis: Optional[str] = None,
                  capacity_factor: float = 1.0,
                  return_stats: bool = False):
    """Capacity-factor all-to-all dispatch — the bandwidth-optimal form.

    Where `moe_apply` has every device touch the FULL token batch
    (dense masked compute, traffic O(N·d) via psum), this variant moves
    each token ONCE to the device owning its expert and once back:
    tokens shard over the expert axis (composed with `data_axis` when
    given), each device packs its local tokens into per-expert buffers
    of static capacity `ceil(capacity_factor · n_local / n_experts)`,
    one `all_to_all` delivers them to the owning devices, the local
    experts run, and a second `all_to_all` returns the outputs to be
    unpermuted and gate-scaled. Tokens beyond an expert's capacity are
    DROPPED (output 0) — switch-transformer semantics; with
    `capacity_factor >= n_experts` capacity covers every local token,
    nothing can drop, and the result matches `moe_reference` exactly
    (tested). Overflow rows land in a garbage slot (`cap` index of a
    cap+1-deep buffer) so they never overwrite kept tokens.

    `return_stats` additionally returns the number of dropped tokens
    (scalar, summed over all devices).
    """
    ep = int(mesh.shape[axis])
    n_experts = params["W1"].shape[0]
    if n_experts % ep:
        raise ValueError(f"{n_experts} experts not divisible by "
                         f"expert-axis size {ep}")
    local = n_experts // ep
    shards = ep * (int(mesh.shape[data_axis]) if data_axis else 1)
    n_tokens = x.shape[0]
    if n_tokens % shards:
        raise ValueError(f"{n_tokens} tokens not divisible by "
                         f"{shards} token shards")
    n_loc = n_tokens // shards
    cap = max(1, int(-(-capacity_factor * n_loc // n_experts)))  # ceil

    def per_device(p, xb):
        logits = xb @ p["gate"]                      # (n_loc, E)
        probs = jax.nn.softmax(logits, axis=-1)
        choice = jnp.argmax(logits, axis=-1)         # (n_loc,)
        prob = jnp.take_along_axis(probs, choice[:, None], 1)[:, 0]
        # slot of each token within its expert's buffer = its rank among
        # local tokens choosing the same expert (deterministic,
        # first-come-first-served like the switch router)
        onehot = choice[:, None] == jnp.arange(n_experts)[None, :]
        ranks = jnp.cumsum(onehot, axis=0) - 1       # (n_loc, E)
        rank = jnp.take_along_axis(ranks, choice[:, None], 1)[:, 0]
        keep = rank < cap
        # overflow tokens scatter into the cap-index garbage slot
        slot = jnp.where(keep, rank, cap)
        buf = jnp.zeros((n_experts, cap + 1, xb.shape[-1]), xb.dtype)
        buf = buf.at[choice, slot].set(xb)[:, :cap]  # (E, cap, d)

        # deliver: chunk e of dim 0 goes to expert e's owner; received
        # row (s·local + j) = what device s packed for my local expert j
        recv = jax.lax.all_to_all(buf, axis, split_axis=0, concat_axis=0,
                                  tiled=True)        # (ep·local, cap, d)
        recv = recv.reshape(ep, local, cap, -1)

        ys = []
        for j in range(local):
            t = recv[:, j].reshape(ep * cap, -1)     # all tokens for my j
            yj = _expert_ffn(p["W1"][j], p["b1"][j], p["W2"][j],
                             p["b2"][j], t, act)
            ys.append(yj.reshape(ep, cap, -1))
        out_buf = jnp.stack(ys, axis=1)              # (ep, local, cap, d)
        out_buf = out_buf.reshape(ep * local, cap, -1)

        # return trip: symmetric all_to_all; back[e, c] = my token that
        # sat in slot c of the buffer I sent toward expert e
        back = jax.lax.all_to_all(out_buf, axis, split_axis=0,
                                  concat_axis=0, tiled=True)
        gathered = back[choice, jnp.clip(slot, 0, cap - 1)]
        out = jnp.where(keep[:, None], prob[:, None] * gathered, 0.0)
        if not return_stats:
            return (out,)
        # stats cost extra collectives — only when asked for
        dropped = jax.lax.psum(jnp.sum(~keep), axis)
        if data_axis:
            dropped = jax.lax.psum(dropped, data_axis)
        return out, dropped

    param_specs = {"gate": P(), "W1": P(axis), "b1": P(axis),
                   "W2": P(axis), "b2": P(axis)}
    # tokens shard over data x expert (just expert on a 1-D mesh): the
    # all_to_all runs within each data group's expert peers
    x_spec = P((data_axis, axis)) if data_axis else P(axis)
    out_specs = (x_spec, P()) if return_stats else (x_spec,)
    res = shard_map(
        per_device, mesh=mesh,
        in_specs=(param_specs, x_spec),
        out_specs=out_specs,
    )(params, x)
    return res if return_stats else res[0]


def moe_grad_step(params, x, y, mesh: Mesh, axis: str = EXPERT_AXIS,
                  lr: float = 0.1, act: Callable = jnp.tanh,
                  data_axis: Optional[str] = None,
                  dispatch: str = "dense",
                  capacity_factor: float = 1.0):
    """One SGD step on MSE through the expert-parallel block.
    dispatch: 'dense' (masked psum combine) or 'a2a' (capacity-factor
    all-to-all)."""

    if dispatch not in ("dense", "a2a"):
        raise ValueError(f"unknown dispatch {dispatch!r}; "
                         "expected 'dense' or 'a2a'")

    def loss_fn(p):
        if dispatch == "a2a":
            out = moe_apply_a2a(p, x, mesh, axis, act, data_axis,
                                capacity_factor=capacity_factor)
        else:
            out = moe_apply(p, x, mesh, axis, act, data_axis)
        return jnp.mean((out - y) ** 2)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
    return params, loss


__all__ = ["EXPERT_AXIS", "init_moe_params", "moe_reference", "moe_apply",
           "moe_apply_a2a", "moe_grad_step"]
