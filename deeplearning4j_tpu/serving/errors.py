"""Serving-plane error types shared by the single-host server and the
fleet router.

`OverloadedError` is the machine-actionable shedding signal: every
admission point that can saturate (the micro-batcher's coalescing
queue, the decode loop's admission queue, the fleet's global
outstanding-request high-water mark) raises it instead of a generic
RuntimeError, and every HTTP front end maps it to the same wire shape —
`503` with a `Retry-After` header and a JSON body
`{"error": "overloaded", "retry_after_ms": N}` — so clients and load
balancers can back off without parsing prose (docs/FLEET.md).

`TIER_INTERACTIVE` / `TIER_BATCH` are the two SLO tiers every serving
request carries (an `X-Priority` header, or a `"priority"` body field
where the body is parsed anyway; absent -> interactive). The tier rides
the whole admission path — router select, fleet dispatch, micro-batcher
queue, decode-loop slot/page accounting — so shedding and preemption
can favor the user who is watching: batch sheds first (at a lower
water mark), and an interactive arrival may preempt a batch decode
slot, turning the batch row into a durable-stream resume record
(docs/SERVING.md "Priority tiers"). A shed reply names the tier that
was shed and derives `Retry-After` from THAT tier's backlog, so a bulk
client backs off long while an interactive client retries soon.

`Deadline` / `DeadlineExceededError` are the end-to-end time-budget
twins: a client sends `deadline_ms` (an `X-Deadline-Ms` header, or a
`deadline_ms` body field where the body is parsed anyway), every hop
re-derives its socket timeout from the REMAINING budget instead of a
fixed constant, the router forwards the shrunk budget downstream, and
every admission point (router select, micro-batcher submit AND
dispatch, decode-loop submit AND admission) sheds already-expired work
with the machine-readable shape `504` +
`{"error": "deadline_exceeded", "deadline_ms": D, "elapsed_ms": E}`
BEFORE any compute starts (docs/SERVING.md "Deadlines").
"""

from __future__ import annotations

import math
import time
from typing import Optional

__all__ = ["OverloadedError", "overload_body",
           "Deadline", "DeadlineExceededError", "deadline_body",
           "DEADLINE_HEADER", "replica_failed_body",
           "TIER_INTERACTIVE", "TIER_BATCH", "TIERS",
           "PRIORITY_HEADER", "parse_tier", "backlog_retry_ms"]

#: the wire header carrying the REMAINING budget in milliseconds; each
#: forwarding hop rewrites it smaller (never larger)
DEADLINE_HEADER = "X-Deadline-Ms"

#: the wire header carrying the request's SLO tier; the router forwards
#: it so replicas never need to re-parse the body
PRIORITY_HEADER = "X-Priority"

#: the latency tier: a user is watching — sheds last, may preempt batch
TIER_INTERACTIVE = "interactive"
#: the throughput tier: bulk generation/eval — sheds first, preemptible
TIER_BATCH = "batch"
TIERS = (TIER_INTERACTIVE, TIER_BATCH)


def parse_tier(headers=None, body=None) -> str:
    """Parse a request's SLO tier: the `X-Priority` header wins, else a
    `"priority"` body field; absent -> interactive (the safe default —
    an untagged client is a user). Unknown values raise ValueError so a
    typo'd `"bacth"` fails loudly instead of silently racing users."""
    raw = headers.get(PRIORITY_HEADER) if headers is not None else None
    if raw is None and isinstance(body, dict):
        raw = body.get("priority")
    if raw is None:
        return TIER_INTERACTIVE
    tier = str(raw).strip().lower()
    if tier not in TIERS:
        raise ValueError(
            f"unknown priority tier {raw!r} (expected one of {TIERS})")
    return tier


def backlog_retry_ms(backlog: int, per_item_ms: float,
                     floor_ms: int = 50, cap_ms: int = 30_000) -> int:
    """Retry-After derived from the shed tier's OWN backlog: roughly
    how long the queue ahead of a retry takes to drain (`backlog` items
    at `per_item_ms` estimated service each), floored so a race with an
    emptying queue still backs off a beat, capped so a deep bulk
    backlog never tells a client "come back in an hour"."""
    est = int(max(0, backlog) * max(0.0, per_item_ms))
    return max(floor_ms, min(cap_ms, est if est > 0 else floor_ms))


class OverloadedError(RuntimeError):
    """An admission queue is full (or a shed high-water mark is hit);
    the caller should retry after `retry_after_ms`. `tier` names which
    SLO tier was shed (None on legacy untiered sites) so the 503 body
    tells a bulk client "YOUR lane is full" even when interactive
    admission is wide open."""

    def __init__(self, message: str, retry_after_ms: int = 1000,
                 tier: Optional[str] = None):
        super().__init__(message)
        self.retry_after_ms = max(1, int(retry_after_ms))
        self.tier = tier

    @property
    def retry_after_s(self) -> int:
        """Whole seconds for the `Retry-After` header (ceil, >= 1)."""
        return max(1, math.ceil(self.retry_after_ms / 1000.0))


def overload_body(exc: OverloadedError) -> dict:
    """The JSON body every 503-overloaded reply carries."""
    out = {"error": "overloaded",
           "retry_after_ms": exc.retry_after_ms,
           "detail": str(exc)}
    if exc.tier is not None:
        out["tier"] = exc.tier
    return out


class DeadlineExceededError(RuntimeError):
    """The request's end-to-end time budget ran out. Raised by every
    admission point BEFORE compute starts (shedding expired work is
    free; finishing it is worthless), and by result waits that hit the
    budget. HTTP front ends map it to 504 + `deadline_body`."""

    def __init__(self, message: str,
                 deadline_ms: Optional[int] = None,
                 elapsed_ms: Optional[int] = None):
        super().__init__(message)
        self.deadline_ms = deadline_ms
        self.elapsed_ms = elapsed_ms


def deadline_body(exc: DeadlineExceededError) -> dict:
    """The JSON body every 504-deadline-exceeded reply carries."""
    out = {"error": "deadline_exceeded", "detail": str(exc)}
    if exc.deadline_ms is not None:
        out["deadline_ms"] = exc.deadline_ms
    if exc.elapsed_ms is not None:
        out["elapsed_ms"] = exc.elapsed_ms
    return out


def replica_failed_body(replica_id, detail: str,
                        resume_attempts: Optional[int] = None) -> dict:
    """The structured shape every router-side replica failure speaks —
    as a 502 body when no byte reached the client, or as the final
    in-band NDJSON line of an already-started stream. Always
    `retryable`: the request itself is sound, only its placement
    failed. `resume_attempts` records how many failover resumes the
    router burned before giving up (docs/FLEET.md "Stream failover"),
    so a client can distinguish "never placed" from "resumed N times
    and the fleet still could not finish it"."""
    out = {"error": "replica_failed",
           "replica": replica_id,
           "detail": detail,
           "retryable": True}
    if resume_attempts is not None:
        out["resume_attempts"] = int(resume_attempts)
    return out


class Deadline:
    """A monotonic end-to-end budget: created once where the request
    enters the process, consulted at every hop.

    `None` deadlines are represented by the absence of a Deadline (the
    constructors return None), so hot paths stay `if deadline is None`
    checks and legacy fixed timeouts apply unchanged."""

    __slots__ = ("budget_ms", "_expires")

    def __init__(self, budget_ms: float):
        self.budget_ms = int(budget_ms)
        self._expires = time.monotonic() + self.budget_ms / 1000.0

    # ------------------------------------------------------ constructors
    @classmethod
    def from_ms(cls, ms) -> Optional["Deadline"]:
        """Budget in milliseconds from NOW; None/absent -> no deadline.
        0 is legal and already expired (the canonical "shed me at every
        admission point" probe)."""
        if ms is None:
            return None
        ms = float(ms)
        if ms < 0:
            raise ValueError(f"deadline_ms must be >= 0, got {ms}")
        return cls(ms)

    @classmethod
    def from_request(cls, headers=None, body=None) -> Optional["Deadline"]:
        """Parse a request's budget: the `X-Deadline-Ms` header wins
        (the router forwards budgets as headers so replicas never need
        to parse the body), else a `deadline_ms` body field."""
        raw = headers.get(DEADLINE_HEADER) if headers is not None else None
        if raw is None and isinstance(body, dict):
            raw = body.get("deadline_ms")
        return cls.from_ms(raw) if raw is not None else None

    # --------------------------------------------------------- the clock
    def remaining_s(self) -> float:
        return self._expires - time.monotonic()

    def remaining_ms(self) -> float:
        return self.remaining_s() * 1000.0

    @property
    def expired(self) -> bool:
        return self.remaining_s() <= 0.0

    def elapsed_ms(self) -> int:
        return int(self.budget_ms - self.remaining_ms())

    def check(self, where: str) -> None:
        """Raise DeadlineExceededError if the budget is spent — the
        one-liner every admission point calls before doing work."""
        if self.expired:
            raise DeadlineExceededError(
                f"deadline exceeded before {where} "
                f"({self.budget_ms}ms budget spent)",
                deadline_ms=self.budget_ms,
                elapsed_ms=self.elapsed_ms())

    def timeout(self, default: float, floor: float = 0.05) -> float:
        """Per-hop socket/wait timeout derived from the remaining
        budget: min(default, remaining), floored so an almost-spent
        budget still makes a bounded attempt instead of a 0s timeout
        (the admission-point `check()` is what sheds truly expired
        work)."""
        return max(floor, min(float(default), self.remaining_s()))

    def header_value(self) -> str:
        """Remaining budget for the forwarded `X-Deadline-Ms` header
        (ceil, >= 1 — a still-unexpired budget never forwards as 0)."""
        return str(max(1, math.ceil(self.remaining_ms())))

    def __repr__(self) -> str:
        return (f"Deadline(budget_ms={self.budget_ms}, "
                f"remaining_ms={self.remaining_ms():.0f})")
