"""Serving-plane error types shared by the single-host server and the
fleet router.

`OverloadedError` is the machine-actionable shedding signal: every
admission point that can saturate (the micro-batcher's coalescing
queue, the decode loop's admission queue, the fleet's global
outstanding-request high-water mark) raises it instead of a generic
RuntimeError, and every HTTP front end maps it to the same wire shape —
`503` with a `Retry-After` header and a JSON body
`{"error": "overloaded", "retry_after_ms": N}` — so clients and load
balancers can back off without parsing prose (docs/FLEET.md).
"""

from __future__ import annotations

import math

__all__ = ["OverloadedError", "overload_body"]


class OverloadedError(RuntimeError):
    """An admission queue is full (or a shed high-water mark is hit);
    the caller should retry after `retry_after_ms`."""

    def __init__(self, message: str, retry_after_ms: int = 1000):
        super().__init__(message)
        self.retry_after_ms = max(1, int(retry_after_ms))

    @property
    def retry_after_s(self) -> int:
        """Whole seconds for the `Retry-After` header (ceil, >= 1)."""
        return max(1, math.ceil(self.retry_after_ms / 1000.0))


def overload_body(exc: OverloadedError) -> dict:
    """The JSON body every 503-overloaded reply carries."""
    return {"error": "overloaded",
            "retry_after_ms": exc.retry_after_ms,
            "detail": str(exc)}
