"""Inference serving: compiled engines, dynamic micro-batching,
continuous-batching paged-KV decode, and multi-replica dispatch behind
a stdlib HTTP front end.

The training side compiles one program per shape bucket and keeps the
host off the critical path (datasets/device_feed.py); this package
applies the same discipline to the inference workload: an
`InferenceEngine` holds one jitted forward per bucket, a `MicroBatcher`
coalesces concurrent `/predict` requests into those buckets, `KVCache`
makes autoregressive decode O(1) per token, and a `DecodeLoop`
slot-schedules concurrent generate streams over a paged KV block pool
(`PagedKVPool`) under ONE compiled decode step — requests join/leave at
token boundaries, KV memory scales with written tokens, `/generate`
streams tokens as they emit. A `ReplicaSet` spreads engines across
local devices (least-outstanding dispatch). Above the single process,
a `Fleet` + router tier (`serving/fleet.py`, `serving/router.py`)
dispatches over N out-of-process replica servers with health-based
eviction/readmission, load shedding, rolling checkpoint reload and an
autoscaling hook. See docs/SERVING.md and docs/FLEET.md.
"""

from deeplearning4j_tpu.serving.batcher import MicroBatcher  # noqa: F401
from deeplearning4j_tpu.serving.errors import (  # noqa: F401
    Deadline,
    DeadlineExceededError,
    OverloadedError,
)
from deeplearning4j_tpu.serving.fleet import (  # noqa: F401
    Autoscaler,
    CircuitBreaker,
    Fleet,
    FleetReplica,
    NoReadyReplicas,
    ReplicaSpawner,
)
from deeplearning4j_tpu.serving.router import (  # noqa: F401
    FleetHandle,
    ReplicaClient,
    serve_fleet,
)
from deeplearning4j_tpu.serving.decode_loop import (  # noqa: F401
    DecodeLoop,
    GenerationStream,
)
from deeplearning4j_tpu.serving.engine import (  # noqa: F401
    EngineStats,
    InferenceEngine,
)
from deeplearning4j_tpu.serving.kv_cache import (  # noqa: F401
    KVCache,
    decode_step,
    generate_cached,
    init_cache,
    kv_cache_bytes,
    prefill,
)
from deeplearning4j_tpu.serving.paged_kv import (  # noqa: F401
    PagedKVPool,
    init_paged_pool,
    paged_decode_step,
    paged_kv_bytes,
    paged_prefill,
)
from deeplearning4j_tpu.serving.replicas import ReplicaSet  # noqa: F401
from deeplearning4j_tpu.serving.server import serve_network  # noqa: F401
