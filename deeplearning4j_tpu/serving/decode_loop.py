"""Continuous-batching decode loop over a paged KV pool.

The per-request `generate_cached` path compiles one whole-decode scan
per (B, T0, n_tokens) signature and serves requests one at a time: one
slow request blocks everything behind it, and every request pays its
full `n_tokens` even after EOS. `DecodeLoop` replaces that with the
modern serving shape (the PagedAttention / continuous-batching lineage;
ROADMAP "Continuous batching + paged KV cache"):

- a fixed pool of **S slots** rides ONE jitted decode step
  (`paged_kv.paged_decode_step` + on-device argmax feedback). Slot
  membership is a traced per-slot `stop` bound, never a shape — the
  step compiles exactly once and requests join/leave without
  recompiling for the life of the server (`decode_step_programs()`
  pins this in tests and bench);
- KV lives in a **paged block pool**: a request holds
  `ceil(tokens/page_size)` pages, pages return to the free list the
  moment it completes, and admission is a free-page check — memory
  scales with tokens actually written, not `max_len × requests`;
- a **scheduler thread** admits queued prompts into freed slots between
  steps (bucketed compiled prefill scatters the prompt's K/V into the
  slot's pages), and emits tokens onto per-request `GenerationStream`s
  as they come off the chip — the HTTP layer streams them to clients
  (`server.py /generate`);
- per-slot **max_tokens / EOS** termination: a finished stream frees
  its slot and pages immediately; the other slots never notice.

The device carry — last tokens, pool, page table, lengths, stop bounds
— feeds straight back into the next dispatch; the host re-uploads the
(S,)/(S,P) control arrays only after a visible event (admission,
completion, page grant). Steady-state per-token cost is one dispatch
slice plus the token D2H the streams need anyway. On accelerators the
pool is donated to the step, so KV updates alias in place; CPU ignores
donation (gated off to avoid the warning, same as InferenceEngine).

**Decode horizon**: `horizon=K` runs K decode steps inside one compiled
dispatch (a `lax.scan` feeding each slot's argmax back on device). The
per-slot `stop` bound makes ragged membership exact — a slot never
writes past its token budget or its allocated pages, whatever K is —
and the host trims EOS overshoot (at most K-1 speculative tokens are
discarded; admission waits at most one chunk). K=1 (the default) is
pure token-boundary scheduling; dispatch-bound hosts raise it to
amortize the per-step round trip (`bench.py serve` runs the CPU smoke
at K=8).

Backpressure: a request is admitted only when the pool can cover its
prompt plus the first decode write; a mid-flight slot that needs a page
with the pool empty simply stops advancing (its `stop` clamps to the
allocated frontier) until a completion frees pages. If every occupied
slot is stalled and nothing can ever free a page, the stalled streams
fail with a clear error instead of deadlocking — size the pool with
`paged_kv_bytes` (docs/SERVING.md).

**Prefix caching** (`prefix_cache=True`, the default): a
content-addressed index (`prefix_cache.PrefixIndex`, a radix trie over
page-aligned token-id chunks) sits in front of admission. Pages become
REFCOUNTED: a request whose prompt starts with cached chunks maps those
pool pages into its page table by reference and prefills only the
uncovered tail (`paged_prefill_ctx` — the tail attends to the shared
prefix through the pool); a fully-covered prompt skips prefill
entirely and replays its last prompt token through the decode step.
Shared pages are read-only: the first divergent write — the decode
cursor entering a page another reader or the cache retains —
copy-on-write forks it into a private page (`copy_page`, the one small
jitted helper sharing adds; `decode_step_programs()` stays 1 for the
life of the server). A page returns to the free list only when its
last reader retires; full PROMPT pages of a retiring request seed the
cache instead, and an LRU tier evicts unreferenced-but-cached pages on
demand — the cache never starves live admission or decode growth.
Because shared pages are read-only until forked, cached-prefix output
is bit-identical to the cold prefill's by construction for the shared
positions (tests pin whole-output equality). Per-request opt-out:
`submit*(..., prefix_cache=False)` neither matches nor seeds the cache
(secret-bearing prompts must not leak into shared pages).

**Decode kernel** (`kernel="auto"|"pallas"|"gather"`): the attention
read inside the compiled step. "pallas" streams each slot's WRITTEN
pages straight from the pool (`attention/paged_pallas.py` — per-step
KV traffic O(written pages)); "gather" materializes the dense
`S × max_len` window (the legacy path, O(reservation)). "auto"
resolves ONCE at construction — the kernel on TPU inside its
calibrated envelope, gather everywhere else (never a silent
interpret-mode slowdown off-TPU) — so the step stays one compiled
program either way. Both figures are exported every dispatch as
dl4j_decode_kv_read_bytes{path="kernel"|"gather"} so the traffic win
is visible whichever lane runs.

**Speculative decoding** (`speculation=k`, default off): each scheduler
round, a drafter (serving/speculation.py — "ngram" prompt-lookup fed by
the slot's own history and the prefix-cache trie, or "model" with a
small draft transformer) proposes up to k continuation tokens per slot,
and ONE widened verify dispatch (`paged_kv.paged_verify_step` — the
horizon idea turned sideways: k+1 positions of one step instead of k+1
chained steps) scores every position against the target model. The
longest prefix where the draft matches the target's own argmax is
accepted, plus the target's token at the first mismatch — so emitted
output is BIT-IDENTICAL to non-speculative greedy decode by
construction, and a wrong draft costs acceptance rate, never
correctness. Accept/rollback is pure host bookkeeping: the per-slot
length cursor advances by `accepted + 1`; rejected positions' K/V
writes landed in pages the slot privately owns (the CoW guard forks the
whole write range `[length, stop)` before dispatch, exactly as for
horizon), are never readable (attention masks key positions past every
query's cursor), and are overwritten before the cursor passes them.
Opt-out per request with `submit*(..., speculation=False)` (HTTP
`"speculation": false`) — that slot rides every verify at width 1,
i.e. a plain decode step. Speculation and `horizon>1` are mutually
exclusive: speculation is its own chunking. The compiled surface grows
by exactly one program (decode + verify; `decode_step_programs()`
counts both and tests/bench pin <= 2). Telemetry:
dl4j_spec_{proposed,accepted,rounds} counters and an acceptance-rate
gauge in snapshot()/stats (docs/SERVING.md "Speculative decoding").

**SLO tiers + preemption** (`tier="interactive"|"batch"` on submit):
every stream carries a priority tier. Interactive (the default) is the
latency tier; batch is the bulk lane riding the same slots and pages.
Admission is tier-priority (every interactive arrival goes ahead of
every batch one, FIFO within a tier), batch holds at most a
weighted-fair share of the slots while interactive work wants the
machine (`batch_share`, default half) and soaks ALL idle capacity when
none does, batch sheds at its own lower `batch_max_waiting` bound with
a Retry-After derived from the batch backlog, and a blocked interactive
admission PREEMPTS batch slots: the victim (fewest tokens emitted — the
cheapest resume) retires with finish_reason `"preempted"`, its pages
return to the pool, and its full prompt pages seed the prefix cache so
the router-side durable-stream resume replays the prefix nearly for
free. Preemption is pure host bookkeeping — slot retirement, exactly
the cancel/deadline path — so `decode_step_programs()` stays pinned
(docs/SERVING.md "Priority tiers").

Telemetry: dl4j_kv_pages_total / dl4j_kv_pages_in_use /
dl4j_kv_pages_shared / dl4j_kv_pages_cached /
dl4j_decode_active_slots gauges, dl4j_decode_requests /
dl4j_decode_tokens_streamed / dl4j_decode_admission_waits /
dl4j_kv_prefix_{hits,misses,forks,evictions} /
dl4j_decode_kv_read_bytes{path} counters, dl4j_decode_step_seconds
histogram (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
import weakref
from collections import deque
from typing import Iterator, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.attention.paged_pallas import resolve_decode_kernel
from deeplearning4j_tpu.models.transformer import TransformerConfig
from deeplearning4j_tpu.serving.errors import (TIER_BATCH,
                                               TIER_INTERACTIVE, TIERS,
                                               Deadline,
                                               DeadlineExceededError,
                                               OverloadedError,
                                               backlog_retry_ms)
from deeplearning4j_tpu.serving import fleetkv
from deeplearning4j_tpu.serving.paged_kv import (copy_page,
                                                 decode_read_bytes,
                                                 extract_page,
                                                 init_paged_pool,
                                                 install_page,
                                                 paged_decode_step,
                                                 paged_kv_bytes,
                                                 paged_prefill,
                                                 paged_prefill_ctx,
                                                 paged_verify_step,
                                                 pages_for_tokens,
                                                 pages_per_slot,
                                                 prompt_buckets)
from deeplearning4j_tpu.serving.prefix_cache import PrefixIndex
from deeplearning4j_tpu.serving.speculation import build_drafter
from deeplearning4j_tpu.testing import chaos
from deeplearning4j_tpu.utils.jitcache import jit_cache_size

__all__ = ["GenerationStream", "DecodeLoop", "ROLES", "ROLE_UNIFIED",
           "ROLE_PREFILL", "ROLE_DECODE"]

_DONE = object()
_loop_seq = itertools.count()

#: replica roles (docs/FLEET.md "Disaggregated roles"): a `unified`
#: loop serves prefill AND decode (the default — existing deployments
#: are unchanged); a `prefill` loop only computes prompt KV into its
#: trie for `/kv/export` handoff (submit/generate are refused, so its
#: compiled surface never grows a decode program); a `decode` loop is
#: a unified loop the fleet routes streams at — the tag exists so the
#: router/fleet can place work, not to change loop behavior.
ROLE_UNIFIED = "unified"
ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"
ROLES = (ROLE_UNIFIED, ROLE_PREFILL, ROLE_DECODE)

#: per-queued-item service estimate feeding the backlog-derived
#: Retry-After on a tier shed: interactive items are short user turns,
#: batch items long bulk rows — a deep batch backlog should tell its
#: client to come back much later than an interactive blip would
_TIER_ITEM_MS = {TIER_INTERACTIVE: 50.0, TIER_BATCH: 250.0}


class GenerationStream:
    """One in-flight generate request: a token queue the scheduler
    pushes into as the slot emits, plus the blocking `result()` the
    non-streaming path uses.

    `tokens()` yields generated token ids as they come off the chip
    (the HTTP streaming response iterates it); `result()` blocks until
    the stream finishes and returns the full generated list;
    `full_sequence()` is prompt + generated — the backward-compatible
    `/generate` response row. `finish_reason` is "eos", "max_tokens",
    "cancelled", "deadline_exceeded", "preempted" (a batch slot evicted
    for an interactive arrival — error stays None so already-emitted
    tokens relay, and the router re-admits the row as a durable-stream
    resume) or "error" once done."""

    def __init__(self, prompt: Sequence[int], max_tokens: int,
                 eos_id: Optional[int],
                 deadline: Optional[Deadline] = None):
        self.prompt: List[int] = [int(t) for t in prompt]
        self.max_tokens = int(max_tokens)
        self.eos_id = None if eos_id is None else int(eos_id)
        self.deadline = deadline
        #: False = this request neither matches nor seeds the shared
        #: prefix cache (set by submit_many's per-request opt-out)
        self.prefix_cache = True
        #: False = no speculative drafts for this request (its slot
        #: rides every verify round at width 1 — a plain decode step).
        #: Output is bit-identical either way; the opt-out exists for
        #: latency A/Bs and for keeping draft-model compute off a
        #: request entirely (set by submit_many)
        self.speculation = True
        #: SLO tier (set by submit_many): "interactive" requests go
        #: ahead of "batch" ones at admission and may preempt their
        #: slots; "batch" rides the weighted-fair bulk lane
        #: (docs/SERVING.md "Priority tiers")
        self.tier = TIER_INTERACTIVE
        #: absolute index of the FIRST token this stream will emit —
        #: non-zero when the request is a failover continuation whose
        #: already-delivered tokens ride in as prompt context. The
        #: streaming front end adds it to each emitted token's
        #: `token_index`, which is the router's exactly-once dedupe key
        #: (docs/SERVING.md "Streaming", docs/FLEET.md failover)
        self.token_index_base = 0
        self.finish_reason: Optional[str] = None
        self.error: Optional[BaseException] = None
        self._generated: List[int] = []
        self._q: "queue.Queue" = queue.Queue()
        self._done = threading.Event()
        self._cancelled = threading.Event()
        self._loop_ref = None  # weakref to the owning loop, set at submit

    # ------------------------------------------------- scheduler side
    def _emit(self, token: int) -> None:
        self._generated.append(int(token))
        self._q.put(int(token))

    def _finish(self, reason: str,
                error: Optional[BaseException] = None) -> None:
        self.finish_reason = reason
        self.error = error
        self._q.put(_DONE)
        self._done.set()

    # --------------------------------------------------- client side
    def tokens(self, timeout: Optional[float] = None) -> Iterator[int]:
        """Yield generated tokens as they are emitted; raises the
        stream's error (if it failed) after the last delivered token.
        `timeout` bounds the wait BETWEEN tokens (a stalled scheduler
        raises TimeoutError, matching result())."""
        while True:
            try:
                item = self._q.get(timeout=timeout)
            except queue.Empty:
                raise TimeoutError(
                    f"no token emitted within {timeout}s") from None
            if item is _DONE:
                if self.error is not None:
                    raise self.error
                return
            yield item

    def indexed_tokens(self, timeout: Optional[float] = None
                       ) -> Iterator[tuple]:
        """`tokens()` with each token's ABSOLUTE index attached:
        yields `(token_index_base + n, token)` for the n-th emitted
        token. The streaming HTTP front end relays the index on every
        NDJSON chunk so a resuming router can deduplicate replayed
        tokens by position (exactly-once delivery across failover)."""
        for n, tok in enumerate(self.tokens(timeout=timeout)):
            yield self.token_index_base + n, tok

    def __iter__(self) -> Iterator[int]:
        return self.tokens()

    @property
    def done(self) -> bool:
        return self._done.is_set()

    @property
    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    def cancel(self) -> bool:
        """Retire this request: its decode slot is released and its KV
        pages return to the pool at the scheduler's NEXT pass (one
        dispatch boundary — the disconnect-handling contract,
        docs/SERVING.md "Cancellation"). Idempotent; returns True when
        the cancel was accepted (the stream had not already finished).
        The stream then finishes with `finish_reason == "cancelled"`
        and `result()` returns the tokens generated so far."""
        if self._done.is_set():
            return False
        self._cancelled.set()
        loop = self._loop_ref() if self._loop_ref is not None else None
        if loop is not None:
            with loop._cond:
                loop._cond.notify_all()  # wake an idle scheduler now
        return True

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until finished; return the generated token ids (EOS
        included when it fired)."""
        if not self._done.wait(timeout):
            raise TimeoutError("generation still in flight")
        if self.error is not None:
            raise self.error
        return list(self._generated)

    def full_sequence(self, timeout: Optional[float] = None) -> List[int]:
        return self.prompt + self.result(timeout)


class _Slot:
    __slots__ = ("stream", "pages", "awaiting_first", "emitted",
                 "stop_len", "no_cache")

    def __init__(self, stream: GenerationStream, pages: List[int],
                 stop_len: int):
        self.stream = stream
        self.pages = pages        # physical page ids, in logical order
        #: prefill's first token is still ON DEVICE (in a group batch —
        #: DecodeLoop._deferred); admission never blocks on a D2H
        self.awaiting_first = True
        self.emitted = 0          # tokens pushed onto the stream so far
        self.stop_len = stop_len  # final length: prompt + max_tokens - 1
        #: pages whose bytes diverged from the pure prompt sequence
        #: (CoW forks) — they must never seed the prefix cache
        self.no_cache: set = set()


class DecodeLoop:
    """Owns the paged pool, the page tables, the single compiled decode
    step, and the scheduler thread. `submit()` is thread-safe and
    returns a `GenerationStream`."""

    def __init__(self, params, cfg: TransformerConfig, *, slots: int = 8,
                 page_size: int = 16, n_pages: Optional[int] = None,
                 horizon: int = 1, max_waiting: Optional[int] = None,
                 prefix_cache: bool = True, fleet_kv: str = "on",
                 kv_ship_timeout: float = 2.0,
                 kernel: str = "auto",
                 speculation: int = 0, drafter: str = "ngram",
                 draft_params=None, draft_cfg=None,
                 draft_window: int = 32, ngram: int = 3,
                 batch_share: float = 0.5,
                 batch_max_waiting: Optional[int] = None,
                 role: str = ROLE_UNIFIED,
                 start: bool = True, name: Optional[str] = None):
        import jax
        import jax.numpy as jnp

        if role not in ROLES:
            raise ValueError(
                f"role must be one of {ROLES}, got {role!r}")
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if horizon < 1:
            raise ValueError(f"horizon must be >= 1, got {horizon}")
        if speculation < 0:
            raise ValueError(
                f"speculation must be >= 0, got {speculation}")
        if speculation and horizon > 1:
            raise ValueError(
                "speculation and horizon>1 are mutually exclusive: "
                "speculation replaces the horizon chain with "
                "draft-and-verify chunking (pick one)")
        if max_waiting is not None and max_waiting < 0:
            raise ValueError(
                f"max_waiting must be >= 0, got {max_waiting}")
        if not 0.0 < batch_share <= 1.0:
            raise ValueError(
                f"batch_share must be in (0, 1], got {batch_share}")
        if batch_max_waiting is not None and batch_max_waiting < 0:
            raise ValueError(
                f"batch_max_waiting must be >= 0, "
                f"got {batch_max_waiting}")
        self.cfg = cfg
        self.params = params
        self.role = role
        self.slots = int(slots)
        self.page_size = int(page_size)
        self.horizon = int(horizon)
        #: drafts per verify round (0 = speculation off)
        self.spec_k = int(speculation)
        # resolve "auto" ONCE, before jitting: the lane is a
        # compile-time constant of the single step program
        self.kernel_requested = kernel
        self.decode_kernel = resolve_decode_kernel(
            kernel, cfg, self.page_size)
        self._pps = pages_per_slot(cfg, self.page_size)
        if n_pages is None:
            # safe default: worst case (every slot at max_len) — callers
            # chasing HBM set it lower and lean on the backpressure
            n_pages = self.slots * self._pps
        self.n_pages = int(n_pages)
        #: admission-queue bound: a submit that cannot start immediately
        #: while this many requests already wait sheds with
        #: OverloadedError (None = queue unboundedly, legacy behavior)
        self.max_waiting = None if max_waiting is None else int(max_waiting)
        #: weighted-fair share: while interactive work wants the
        #: machine, batch holds at most this many slots; with no
        #: interactive demand batch soaks everything (SLO tiers)
        self.batch_share = float(batch_share)
        self._batch_slot_cap = max(1, int(round(self.slots
                                                * self.batch_share)))
        #: the bulk lane's OWN (lower) admission-queue bound — batch
        #: sheds first; defaults to half the interactive bound
        if batch_max_waiting is not None:
            self.batch_max_waiting: Optional[int] = int(batch_max_waiting)
        elif self.max_waiting is not None:
            self.batch_max_waiting = self.max_waiting // 2
        else:
            self.batch_max_waiting = None
        #: live per-tier admission-queue depth (kept exact under the
        #: lock so the backlog gauge/shed math never iterates the deque
        #: racily)
        self._tier_waiting = {t: 0 for t in TIERS}
        self._buckets = prompt_buckets(cfg, self.page_size)

        # device state ------------------------------------------------
        self._pool = init_paged_pool(cfg, self.n_pages, self.page_size)
        self._trash = self._pool.trash_page
        self._d_tokens = None       # (S,) int32
        self._d_table = None        # (S, P) int32
        self._d_lengths = None      # (S,) int32
        self._d_stop = None         # (S,) int32
        # host mirrors (scheduler-thread-owned) -----------------------
        self._table = np.full((self.slots, self._pps), self._trash,
                              np.int32)
        self._lengths = np.zeros((self.slots,), np.int32)
        self._stop = np.zeros((self.slots,), np.int32)
        self._pending = np.zeros((self.slots,), np.int32)
        self._dirty = True          # mirrors changed since last upload
        self._free: deque = deque(range(self.n_pages))
        self._slot_state: List[Optional[_Slot]] = [None] * self.slots
        #: prefill-group first tokens still on device:
        #: [(device (B,) array, [(row, slot_idx), ...])]
        self._deferred: List = []
        # prefix sharing: per-page reader refcounts + the chunk trie.
        # Every page is in exactly ONE of: the free list, in use
        # (ref > 0), or the cached tier (ref == 0 but trie-retained) —
        # snapshot()/tests pin that the three always sum to n_pages.
        self.prefix_cache_enabled = bool(prefix_cache)
        self._prefix: Optional[PrefixIndex] = (
            PrefixIndex(self.page_size) if self.prefix_cache_enabled
            else None)
        self._ref = np.zeros((self.n_pages,), np.int32)
        self._prefill_token_count = 0  # real tokens through prefill
        # fleet KV plane (serving/fleetkv.py, docs/FLEET.md): affinity
        # summaries + peer page shipping. The plane rides the prefix
        # trie, so without a trie it is forced off.
        if fleet_kv not in fleetkv.MODES:
            raise ValueError(
                f"fleet_kv must be one of {fleetkv.MODES}, "
                f"got {fleet_kv!r}")
        self.fleet_kv = (fleet_kv if self.prefix_cache_enabled
                         else fleetkv.MODE_OFF)
        if self.role == ROLE_PREFILL and self.fleet_kv != fleetkv.MODE_ON:
            # the trie + /kv/export wire ARE a prefill replica's whole
            # product: without them it could never hand pages to anyone
            raise ValueError(
                "a prefill-role loop needs prefix_cache=True and "
                "fleet_kv='on' — its only output is cached KV pages "
                "shipped over /kv/export")
        #: install jobs queued for the scheduler thread — pool swaps
        #: happen OUTSIDE the lock on that thread, so a shipped-page
        #: scatter from a handler thread would race a prefill's swap;
        #: routing installs through the tick serializes them for free
        self._kv_jobs: deque = deque()
        #: cumulative ship stats, reported in the /readyz summary so
        #: the fleet's probe can delta them into router-side counters
        self._ship_stats = {"page_ships": 0, "ship_bytes": 0,
                            "ship_failures": 0}
        #: default budget for one donor fetch + install (seconds);
        #: request deadlines cap it further (server._generate). Raise
        #: it when donors run compute-starved (interpret mode, shared
        #: cores) — a slow export is still far cheaper than a cold
        #: head prefill, and ANY expiry just falls back to prefill.
        if kv_ship_timeout <= 0:
            raise ValueError(f"kv_ship_timeout must be > 0, "
                             f"got {kv_ship_timeout}")
        self.kv_ship_timeout = float(kv_ship_timeout)

        # speculative decoding ----------------------------------------
        # the drafter proposes; the verify program below is the only
        # authority on emitted tokens (serving/speculation.py)
        self._drafter = None
        if self.spec_k:
            corpus = ((lambda: self._prefix.iter_sequences())
                      if self._prefix is not None else None)
            self._drafter = build_drafter(
                drafter, k=self.spec_k, cfg=cfg,
                draft_params=draft_params, draft_cfg=draft_cfg,
                draft_window=draft_window, ngram=ngram, corpus=corpus)

        # compiled programs -------------------------------------------
        # donation lets XLA update the pool in place on accelerators;
        # CPU ignores donation with a warning, so gate it off there
        donate_step = () if jax.default_backend() == "cpu" else (2,)
        donate_pre = () if jax.default_backend() == "cpu" else (3,)
        k_steps = self.horizon

        def step_fn(params, tokens, pool, table, lengths, stop):
            """K chained decode steps in one dispatch. Per-slot
            activity is `lengths < stop` — a slot out of budget or out
            of allocated pages stops advancing mid-chunk exactly where
            it should, so horizon never corrupts state."""
            def inner(carry, _):
                tokens, lengths, pool = carry
                act = lengths < stop
                logits, pool = paged_decode_step(
                    params, tokens, pool, table, lengths, act, cfg,
                    kernel=self.decode_kernel)
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                tokens = jnp.where(act, nxt, tokens)
                lengths = lengths + act.astype(lengths.dtype)
                return (tokens, lengths, pool), nxt

            (tokens, lengths, pool), toks = jax.lax.scan(
                inner, (tokens, lengths, pool), None, length=k_steps)
            return toks, tokens, lengths, pool

        def prefill_fn(params, tokens, true_len, pool, page_ids):
            logits, pool = paged_prefill(params, tokens, true_len, pool,
                                         page_ids, cfg)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), pool

        def prefill_ctx_fn(params, tokens, true_len, pool, page_ids,
                           ctx_table, ctx_len):
            logits, pool = paged_prefill_ctx(
                params, tokens, true_len, pool, page_ids, ctx_table,
                ctx_len, cfg)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), pool

        def verify_fn(params, tokens, pool, table, lengths, widths):
            """ONE widened step over (S, W) tokens: every real column
            writes K/V at `lengths + j` and the returned argmax row is
            the target model's own next-token choice after each draft
            prefix — the exact-accept rule's ground truth."""
            logits, pool = paged_verify_step(
                params, tokens, pool, table, lengths, widths, cfg,
                kernel=self.decode_kernel)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), pool

        donate_copy = () if jax.default_backend() == "cpu" else (0,)
        self._step = jax.jit(step_fn, donate_argnums=donate_step)
        self._verify = jax.jit(verify_fn, donate_argnums=donate_step)
        self._prefill = jax.jit(prefill_fn, donate_argnums=donate_pre)
        self._prefill_ctx = jax.jit(prefill_ctx_fn,
                                    donate_argnums=donate_pre)
        # the one compiled surface sharing adds: scalar src/dst are
        # traced, so every CoW fork for the life of the server is ONE
        # program
        self._copy = jax.jit(copy_page, donate_argnums=donate_copy)
        # persistent compile cache (docs/WARMUP.md): no-op unless the
        # process activated one. The key pins every closure constant
        # that changes the program at identical input shapes — model
        # config, kernel lane, horizon, spec width — plus the device,
        # because serialized executables are device-bound.
        from deeplearning4j_tpu import compilecache as _cc

        self.cache_key = (
            f"decode:{_cc.config_digest(cfg)}|ps={self.page_size}"
            f"|k={self.decode_kernel}|h={self.horizon}"
            f"|spec={self.spec_k}|dev={jax.devices()[0]}")
        self._step = _cc.maybe_wrap(self._step, self.cache_key + "|step")
        self._verify = _cc.maybe_wrap(self._verify,
                                      self.cache_key + "|verify")
        self._prefill = _cc.maybe_wrap(self._prefill,
                                       self.cache_key + "|prefill")
        self._prefill_ctx = _cc.maybe_wrap(
            self._prefill_ctx, self.cache_key + "|prefill_ctx")
        self._copy = _cc.maybe_wrap(self._copy, self.cache_key + "|copy")
        #: program-usage record for plan_fragment(): (bb, tb) /
        #: (bb, cb, tb) prefill groups actually dispatched, plus flags
        #: for the fixed-shape programs actually run — the plan must
        #: list exactly the programs a boot like this one compiles, or
        #: replay would add programs the record run never had
        self._plan_prefill: set = set()
        self._plan_prefill_ctx: set = set()
        self._plan_step = False
        self._plan_verify = False
        self._plan_copy = False

        # queueing / lifecycle ----------------------------------------
        self._cond = threading.Condition()
        self._waiting: deque = deque()  # GenerationStreams not yet admitted
        self._closed = False
        self._peak_pages = 0
        self._thread: Optional[threading.Thread] = None

        # telemetry ----------------------------------------------------
        reg = telemetry.get_registry()
        self.label = name if name is not None else f"d{next(_loop_seq)}"
        lab = {"loop": self.label}
        self._m_requests = reg.counter(
            "dl4j_decode_requests",
            "generate requests submitted to the slot scheduler"
        ).labels(**lab)
        self._m_tokens = reg.counter(
            "dl4j_decode_tokens_streamed",
            "tokens emitted onto generation streams").labels(**lab)
        self._m_waits = reg.counter(
            "dl4j_decode_admission_waits",
            "scheduler passes where a queued request could not be "
            "admitted for lack of free pages or slots").labels(**lab)
        self._m_steps = reg.counter(
            "dl4j_decode_steps",
            "compiled decode dispatches run (each covers `horizon` "
            "token steps)").labels(**lab)
        self._m_shed = reg.counter(
            "dl4j_decode_shed",
            "generate requests rejected at submit because the admission "
            "queue was at max_waiting").labels(**lab)
        self._m_deadline = reg.counter(
            "dl4j_decode_deadline_exceeded",
            "generate requests shed at submit/admission, or reaped "
            "mid-flight, because their deadline budget was spent"
        ).labels(**lab)
        self._m_cancelled = reg.counter(
            "dl4j_decode_cancelled",
            "generate requests cancelled (client disconnect or "
            "GenerationStream.cancel) — slot retired, pages freed"
        ).labels(**lab)
        self._m_hits = reg.counter(
            "dl4j_kv_prefix_hits",
            "admissions whose prompt matched >= 1 cached prefix chunk "
            "(shared pool pages mapped by reference)").labels(**lab)
        self._m_misses = reg.counter(
            "dl4j_kv_prefix_misses",
            "cache-eligible admissions that matched no cached chunk "
            "(full cold prefill)").labels(**lab)
        self._m_forks = reg.counter(
            "dl4j_kv_prefix_forks",
            "copy-on-write page forks (decode cursor entered a shared "
            "page; it was duplicated into a private one)").labels(**lab)
        self._m_evictions = reg.counter(
            "dl4j_kv_prefix_evictions",
            "unreferenced cached prefix pages evicted (LRU) to satisfy "
            "an allocation under page pressure").labels(**lab)
        _tier_req = reg.counter(
            "dl4j_tier_requests",
            "generate requests submitted per SLO tier (interactive "
            "goes ahead at admission; batch rides the weighted-fair "
            "bulk lane)")
        tscope = {"scope": f"loop:{self.label}"}
        self._m_tier_requests = {
            t: _tier_req.labels(tier=t, **tscope) for t in TIERS}
        _tier_shed = reg.counter(
            "dl4j_tier_shed",
            "generate requests shed at submit per SLO tier (batch "
            "sheds first, at its own lower batch_max_waiting bound)")
        self._m_tier_shed = {
            t: _tier_shed.labels(tier=t, **tscope) for t in TIERS}
        self._m_preempt = reg.counter(
            "dl4j_tier_preemptions",
            "batch decode slots preempted for a blocked interactive "
            "admission (lossless: the row resumes via the router's "
            "durable-stream record)").labels(tier=TIER_BATCH, **tscope)
        self._m_spec_proposed = reg.counter(
            "dl4j_spec_proposed",
            "draft tokens proposed to speculative verify rounds"
        ).labels(**lab)
        self._m_spec_accepted = reg.counter(
            "dl4j_spec_accepted",
            "draft tokens the target model's verify accepted (each one "
            "a decode dispatch saved)").labels(**lab)
        self._m_spec_rounds = reg.counter(
            "dl4j_spec_rounds",
            "widened verify dispatches run (speculative rounds; plain "
            "fallback rounds when no slot had a draft are not counted "
            "here)").labels(**lab)
        _kv_read = reg.counter(
            "dl4j_decode_kv_read_bytes",
            "KV bytes the decode attention read must touch, summed "
            "over token steps: path=\"kernel\" is the streamed-pages "
            "figure (written pages only — what the pallas lane reads), "
            "path=\"gather\" the dense-window figure (the full "
            "S x max_len reservation); their ratio is the paged "
            "kernel's traffic win")
        self._m_kv_read = {
            path: _kv_read.labels(path=path, **lab)
            for path in ("kernel", "gather")}
        self._m_step_s = reg.histogram(
            "dl4j_decode_step_seconds",
            "wall time of one compiled decode dispatch (covers "
            "`horizon` token steps), dispatch through the token D2H "
            "sync").labels(**lab)
        reg.gauge(
            "dl4j_kv_pages_total",
            "usable KV pages in the block pool").labels(**lab).set(
                self.n_pages)
        ref = weakref.ref(self)
        reg.gauge(
            "dl4j_kv_pages_in_use",
            "KV pages currently held by in-flight requests"
        ).labels(**lab).set_function(
            lambda: (lambda o: o.pages_in_use if o else 0)(ref()))
        reg.gauge(
            "dl4j_kv_pages_shared",
            "KV pages an in-flight slot may not write without a CoW "
            "fork (>= 2 readers, or referenced while cache-retained)"
        ).labels(**lab).set_function(
            lambda: (lambda o: o.pages_shared if o else 0)(ref()))
        reg.gauge(
            "dl4j_kv_pages_cached",
            "KV pages retained by the prefix index (the unreferenced "
            "ones form the LRU-evictable tier)").labels(
                **lab).set_function(
            lambda: (lambda o: o.pages_cached if o else 0)(ref()))
        reg.gauge(
            "dl4j_decode_active_slots",
            "slots holding an in-flight request").labels(
                **lab).set_function(
            lambda: (lambda o: o.occupied_slots if o else 0)(ref()))
        _backlog = reg.gauge(
            "dl4j_tier_backlog",
            "generate requests queued for admission per SLO tier (the "
            "batch figure is the signal the autoscaler and the "
            "backlog-derived Retry-After key on)")
        for t in TIERS:
            _backlog.labels(tier=t, **tscope).set_function(
                (lambda _t: lambda: (lambda o: o._tier_waiting[_t]
                                     if o else 0)(ref()))(t))
        reg.gauge(
            "dl4j_spec_acceptance_rate",
            "accepted / proposed draft tokens over the loop's lifetime "
            "(0.0 while speculation is off or nothing was proposed)"
        ).labels(**lab).set_function(
            lambda: (lambda o: o.spec_acceptance_rate if o else 0.0)(
                ref()))

        if start:
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name=f"decode-loop-{self.label}")
            self._thread.start()

    # ----------------------------------------------------- public API
    @staticmethod
    def _per_row(value, n_rows: int, name: str) -> List[int]:
        """Normalize a scalar-or-per-row int parameter to one int per
        row (submit_many's max_tokens / token_index_base contract)."""
        if isinstance(value, (list, tuple, np.ndarray)):
            if len(value) != n_rows:
                raise ValueError(
                    f"per-row {name} needs {n_rows} entries, "
                    f"got {len(value)}")
            return [int(v) for v in value]
        return [int(value)] * n_rows

    def validate(self, prompt, max_tokens: int) -> np.ndarray:
        """Check one request without enqueueing it (raises ValueError);
        returns the normalized 1-D prompt. Callers submitting several
        rows as one unit (the HTTP /generate handler) validate ALL rows
        first, so a malformed row never orphans its row-mates'
        already-running streams."""
        prompt = np.asarray(prompt).ravel().astype(np.int64)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if max_tokens < 1:
            raise ValueError(f"max_tokens must be >= 1, got {max_tokens}")
        if prompt.size + max_tokens > self.cfg.max_len:
            raise ValueError(
                f"generation would exceed max_len ({prompt.size} prompt "
                f"+ {max_tokens} new > {self.cfg.max_len})")
        need = pages_for_tokens(int(prompt.size) + 1, self.page_size)
        if need > self.n_pages:
            raise ValueError(
                f"prompt needs {need} pages but the pool only has "
                f"{self.n_pages}")
        return prompt

    def submit(self, prompt, max_tokens: int,
               eos_id: Optional[int] = None,
               deadline: Optional[Deadline] = None,
               prefix_cache: bool = True,
               speculation: bool = True,
               tier: str = TIER_INTERACTIVE) -> GenerationStream:
        """Queue one prompt (1-D int sequence). The stream's first token
        arrives after admission + prefill; termination on EOS (when
        given), `max_tokens`, or the model window. `prefix_cache=False`
        opts this request out of the shared prefix cache — it neither
        reuses cached pages nor seeds new ones (benchmark cold runs;
        secret-bearing prompts). `speculation=False` opts it out of
        speculative drafting (plain one-token rounds; output is
        bit-identical either way). `tier="batch"` rides the bulk lane:
        admitted behind every interactive arrival, capped at the
        weighted-fair slot share under interactive demand, shed first,
        and preemptible (finish_reason "preempted")."""
        return self.submit_many([prompt], max_tokens, eos_id,
                                deadline=deadline,
                                prefix_cache=prefix_cache,
                                speculation=speculation,
                                tier=tier)[0]

    def submit_many(self, prompts, max_tokens,
                    eos_id: Optional[int] = None,
                    deadline: Optional[Deadline] = None,
                    prefix_cache: bool = True,
                    token_index_base=0,
                    speculation: bool = True,
                    tier: str = TIER_INTERACTIVE
                    ) -> List[GenerationStream]:
        """Admit several rows as ONE unit: all rows enqueue or none do.
        A shed that fired between a multi-row request's submits would
        orphan the already-queued row-mates in running slots (no
        consumer ever reads them), so the /generate handler routes
        every multi-row body through here. An already-expired `deadline`
        sheds the whole group here; one that expires while queued sheds
        at admission — either way before any prefill compute.

        `max_tokens` and `token_index_base` accept either one scalar
        for every row or a per-row sequence (length == len(prompts)).
        Per-row budgets are what a failover continuation needs: rows
        interrupted at different depths re-admit as one group, each
        with its own remaining budget and absolute-index offset. Both
        per-row lists are length- and value-checked UP FRONT with a
        named error — a short or negative list must fail before any
        row-mate is enqueued, not deep in slot admission.

        `tier` ("interactive" default, "batch") applies to the whole
        group. Batch sheds at its own `batch_max_waiting` bound — the
        bulk lane fills and sheds FIRST — and both tiers' shed replies
        carry the shed tier plus a Retry-After derived from that
        tier's backlog, so a bulk client backs off proportionally to
        the lane it actually waits in."""
        if self.role == ROLE_PREFILL:
            # a prefill replica owns no streams: its compiled surface
            # must never grow the decode/verify ladder (role-scoped
            # warmup plans pin key-set disjointness on exactly this)
            raise ValueError(
                "this replica has role 'prefill' — it computes prompt "
                "KV for handoff (/prefill) and serves /kv/export; "
                "generate streams belong on a decode/unified replica")
        if tier not in TIERS:
            raise ValueError(
                f"unknown tier {tier!r} (expected one of {TIERS})")
        if deadline is not None and deadline.expired:
            self._m_deadline.inc()
            deadline.check("decode admission")  # raises
        per_row_max = self._per_row(max_tokens, len(prompts),
                                    "max_tokens")
        per_row_base = self._per_row(token_index_base, len(prompts),
                                     "token_index_base")
        for base in per_row_base:
            if base < 0:
                raise ValueError(
                    f"per-row token_index_base must be >= 0, got {base}")
        prompts = [self.validate(p, mt)
                   for p, mt in zip(prompts, per_row_max)]
        streams = [GenerationStream(p, mt, eos_id, deadline=deadline)
                   for p, mt in zip(prompts, per_row_max)]
        loop_ref = weakref.ref(self)
        for stream, base in zip(streams, per_row_base):
            stream._loop_ref = loop_ref
            stream.prefix_cache = bool(prefix_cache)
            stream.speculation = bool(speculation)
            stream.token_index_base = base
            stream.tier = tier
        with self._cond:
            if self._closed:
                raise RuntimeError("decode loop is closed")
            bound = (self.batch_max_waiting if tier == TIER_BATCH
                     else self.max_waiting)
            if bound is not None:
                # free-page starvation / slot saturation sheds at the
                # door once the TIER's admission queue is at its bound
                # — a group that could start right now is never
                # rejected, and a deep bulk backlog never sheds the
                # interactive lane (those arrivals preempt instead)
                need = sum(pages_for_tokens(p.size + 1, self.page_size)
                           for p in prompts)
                free_slots = sum(1 for s in self._slot_state
                                 if s is None)
                can_now = (not self._waiting
                           and self._avail_pages() >= need
                           and free_slots >= len(prompts))
                tier_q = self._tier_waiting[tier]
                if not can_now and tier_q + len(prompts) > bound:
                    self._m_shed.inc()
                    self._m_tier_shed[tier].inc()
                    raise OverloadedError(
                        f"decode admission queue full for tier "
                        f"{tier!r} ({tier_q} waiting, "
                        f"{len(self._free)}/{self.n_pages} pages free)",
                        retry_after_ms=backlog_retry_ms(
                            tier_q + len(prompts),
                            _TIER_ITEM_MS[tier]),
                        tier=tier)
            for stream in streams:
                self._m_requests.inc()
                self._m_tier_requests[tier].inc()
                self._waiting.append(stream)
                self._tier_waiting[tier] += 1
            self._cond.notify_all()
        return streams

    def generate(self, prompt, max_tokens: int,
                 eos_id: Optional[int] = None,
                 timeout: Optional[float] = 120.0) -> List[int]:
        """Blocking convenience: submit + wait, returns prompt+generated
        (the `/generate` non-streaming row shape)."""
        return self.submit(prompt, max_tokens, eos_id).full_sequence(timeout)

    @property
    def pages_in_use(self) -> int:
        """Pages held by in-flight requests (reader refcount > 0).
        Cached-but-unreferenced prefix pages are NOT in use — they are
        reclaimable on demand (`pages_cached`)."""
        return int(np.count_nonzero(self._ref))

    @property
    def pages_cached(self) -> int:
        """Pages retained by the prefix index (shared prefix K/V)."""
        return 0 if self._prefix is None else len(self._prefix)

    @property
    def pages_shared(self) -> int:
        """Pages some in-flight slot may not write in place: >= 2
        readers, or >= 1 reader while the cache retains the page."""
        shared = int(np.count_nonzero(self._ref >= 2))
        if self._prefix is not None:
            shared += sum(1 for p in self._prefix.pages()
                          if self._ref[p] == 1)
        return shared

    def _cached_unref(self) -> int:
        """The evictable LRU tier: cache-retained pages no slot reads."""
        if self._prefix is None:
            return 0
        return sum(1 for p in self._prefix.pages() if self._ref[p] == 0)

    def _avail_pages(self) -> int:
        """Pages an allocation could obtain right now: free list plus
        the evictable cached tier (the cache never starves admission)."""
        return len(self._free) + self._cached_unref()

    def _alloc_page(self) -> Optional[int]:
        """Take one page for a new reader (ref -> 1): from the free
        list, else by LRU-evicting an unreferenced cached prefix page.
        None when neither has a page (callers stall, not crash)."""
        if self._free:
            page = self._free.popleft()
        elif self._prefix is not None:
            page = self._prefix.evict_lru(
                lambda p: self._ref[p] == 0)
            if page is not None:
                self._m_evictions.inc()
        else:
            page = None
        if page is not None:
            self._ref[page] += 1
        return page

    def _release_page(self, page: int) -> None:
        """Drop one reader; the page returns to the free list only when
        the LAST reader is gone AND the cache does not retain it."""
        self._ref[page] -= 1
        if self._ref[page] < 0:  # pragma: no cover — accounting bug
            raise AssertionError(f"page {page} refcount underflow")
        if (self._ref[page] == 0
                and (self._prefix is None
                     or not self._prefix.owns(page))):
            self._free.append(page)

    def _is_shared(self, page: int) -> bool:
        """True when a slot must CoW-fork before writing this page."""
        return (self._ref[page] > 1
                or (self._prefix is not None
                    and self._prefix.owns(page)))

    @property
    def occupied_slots(self) -> int:
        return sum(1 for s in self._slot_state if s is not None)

    @property
    def load(self) -> int:
        """Live in-flight pressure: queued + occupied slots. The
        replica-set and fleet least-loaded selectors key on this."""
        with self._cond:
            return len(self._waiting) + self.occupied_slots

    @property
    def alive(self) -> bool:
        """Scheduler thread running (readiness surface: a dead loop
        must flip /readyz, not hang clients)."""
        return (self._thread is not None and self._thread.is_alive()
                and not self._closed)

    @property
    def spec_acceptance_rate(self) -> float:
        """Accepted / proposed draft tokens over the loop's lifetime
        (0.0 while speculation is off or nothing was proposed yet)."""
        proposed = int(self._m_spec_proposed.value)
        if proposed <= 0:
            return 0.0
        return int(self._m_spec_accepted.value) / proposed

    def kv_pool_bytes(self) -> int:
        return paged_kv_bytes(self.cfg, self.n_pages, self.page_size)

    def decode_step_programs(self) -> int:
        """Compiled-program count for the decode lane — the
        continuous-batching recompile guard. Plain mode: exactly 1
        after warmup, no matter how requests join/leave. Speculative
        mode: decode + widened verify, pinned <= 2 (both fixed-shape;
        membership is traced). -1 when the private jax counter API
        drifted."""
        n = jit_cache_size(self._step)
        if n < 0:
            return n
        if self.spec_k:
            nv = jit_cache_size(self._verify)
            if nv < 0:
                return -1
            n += nv
        return n

    def prefill_programs(self) -> int:
        """Compiled prefill programs — bounded by the prompt bucket
        ladder (one per bucket hit)."""
        return jit_cache_size(self._prefill)

    # ---- warmup plans (docs/WARMUP.md)
    def plan_fragment(self) -> dict:
        """The "decode" fragment of a warmup plan: which of this loop's
        programs existed and at which prefill group shapes. Fixed-shape
        programs (step, verify, copy) are flags — their shapes are
        implied by the loop config; only the prefill groups are
        traffic-dependent."""
        frag = {
            "cache_key": self.cache_key,
            "role": self.role,
            "step": self._plan_step,
            "verify": self._plan_verify,
            "copy": self._plan_copy,
            "prefill": sorted(list(g) for g in self._plan_prefill),
            "prefill_ctx": sorted(list(g)
                                  for g in self._plan_prefill_ctx),
        }
        if (self._drafter is not None
                and getattr(self._drafter, "kind", None) == "model"):
            frag["draft"] = {"rows": self.slots, "k": self.spec_k}
        return frag

    def warm_programs(self, frag: dict) -> int:
        """Replay a recorded plan fragment: AOT load-or-compile every
        listed program via `jax.ShapeDtypeStruct` placeholders, WITHOUT
        executing anything (execution would donate buffers and write
        the page pool). No-op unless this process has the persistent
        cache active (plain jits can't be preloaded) and the fragment
        matches this loop's program identity. Returns the number of
        programs warmed."""
        import jax

        if frag.get("cache_key") != self.cache_key:
            return 0
        if not hasattr(self._step, "warm"):
            return 0

        def sds(a):
            return jax.ShapeDtypeStruct(a.shape, a.dtype)

        def ints(*shape):
            return jax.ShapeDtypeStruct(shape, np.int32)

        params_spec = jax.tree_util.tree_map(sds, self.params)
        pool_spec = jax.tree_util.tree_map(sds, self._pool)
        S, P, ps = self.slots, self._pps, self.page_size
        n = 0
        if frag.get("step"):
            n += self._step.warm(params_spec, ints(S), pool_spec,
                                 ints(S, P), ints(S), ints(S))
        if frag.get("verify") and self.spec_k:
            n += self._verify.warm(params_spec,
                                   ints(S, self.spec_k + 1), pool_spec,
                                   ints(S, P), ints(S), ints(S))
        if frag.get("copy"):
            n += self._copy.warm(pool_spec, ints(), ints())
        for bb, tb in frag.get("prefill", ()):
            n += self._prefill.warm(params_spec, ints(bb, tb), ints(bb),
                                    pool_spec, ints(bb, tb // ps))
        for bb, cb, tb in frag.get("prefill_ctx", ()):
            n += self._prefill_ctx.warm(
                params_spec, ints(bb, tb), ints(bb), pool_spec,
                ints(bb, tb // ps), ints(bb, cb), ints(bb))
        draft = frag.get("draft")
        if (draft and self._drafter is not None
                and hasattr(self._drafter, "warm")):
            n += int(self._drafter.warm(int(draft.get("rows", S)),
                                        int(draft.get("k", self.spec_k))))
        return n

    # ---- fleet KV plane (serving/fleetkv.py, docs/FLEET.md)
    def kv_summary(self) -> Optional[dict]:
        """The affinity summary piggybacked on /readyz: cumulative
        head-chunk fingerprints of every cached trie path (most recent
        first, capped), plus the cache/ship counters the fleet probe
        deltas into router-side series. None while the plane is off —
        the readiness payload then simply omits the key. Only tokens
        the trie RETAINS are fingerprinted; opted-out requests never
        seeded it, so nothing prompt-derived about them leaves this
        process."""
        if self._prefix is None or self.fleet_kv == fleetkv.MODE_OFF:
            return None
        # chaos: a summary-build fault must degrade the replica to
        # "no affinity signal", never fail the health probe
        chaos.hit("fleet.kv_summary")
        with self._cond:
            return {
                "v": 1,
                "mode": self.fleet_kv,
                "role": self.role,
                "page_size": self.page_size,
                "heads": fleetkv.summary_heads(self._prefix,
                                               self.page_size),
                "pages_cached": self.pages_cached,
                "hits": int(self._m_hits.value),
                "misses": int(self._m_misses.value),
                **self._ship_stats,
            }

    def kv_export(self, tokens: Sequence[int],
                  max_chunks: Optional[int] = None) -> Optional[bytes]:
        """Donor half of a page ship: serialize this replica's cached
        pages covering `tokens`' head chunks (crc-framed, no pickle —
        fleetkv.pack_pages). None when shipping is off. The matched
        pages are PINNED (reader refcount) for the duration of the
        read: eviction only takes refcount-zero pages, and any writer
        CoW-forks away from a trie-retained page, so the bytes each
        extract sees are frozen even while the pool keeps serving —
        and even across pool swaps, because a pinned page's content is
        immutable in every pool generation. Runs on the HTTP handler
        thread; only the bookkeeping takes the lock."""
        if self._prefix is None or self.fleet_kv != fleetkv.MODE_ON:
            return None
        with self._cond:
            matched = self._prefix.match(tokens)
            if max_chunks is not None:
                matched = matched[:int(max_chunks)]
            for page in matched:
                self._ref[page] += 1  # pin across the export read
        try:
            # chaos: donor faults mid-ship (a "hang" rule holds the
            # pins open — the export-vs-eviction drills ride this
            # window; "error"/"reset" drill the receiver's fallback)
            chaos.hit("fleet.kv_ship", role="export",
                      chunks=len(matched))
            pool = self._pool
            chunks = [extract_page(pool, page) for page in matched]
        finally:
            with self._cond:
                for page in matched:
                    self._release_page(page)
                self._cond.notify_all()
        meta = {
            "v": 1,
            "cache_key": self.cache_key,
            "page_size": self.page_size,
            "chunks": len(matched),
            "layers": self.cfg.n_layers,
            "shape": [self.cfg.n_heads, self.page_size,
                      self.cfg.d_model // self.cfg.n_heads],
        }
        return fleetkv.pack_pages(meta, chunks)

    def kv_ship(self, donor_url: str, tokens: Sequence[int],
                timeout: Optional[float] = None) -> int:
        """Receiver half: fetch the donor's cached pages for `tokens`'
        head chunks and install whatever this trie is missing. Returns
        the number of pages installed; 0 on ANY failure — shipping is
        an optimization, the caller's admission prefills the same
        tokens regardless. Safe from any thread: the pool scatter is
        routed through the scheduler thread (`_kv_jobs`)."""
        if self._prefix is None or self.fleet_kv != fleetkv.MODE_ON:
            return 0
        n_full = len(tokens) // self.page_size
        if n_full == 0 or not donor_url:
            return 0
        with self._cond:
            covered = len(self._prefix.match(tokens))
        if covered >= n_full:
            return 0  # already warm locally — nothing worth a fetch
        if timeout is None:
            timeout = self.kv_ship_timeout
        try:
            # chaos: receiver-side fetch faults (transport flakes)
            chaos.hit("fleet.kv_ship", role="fetch", donor=donor_url)
            payload = fleetkv.fetch_pages(
                donor_url, tokens[:n_full * self.page_size], timeout,
                max_chunks=n_full)
            header, chunks = fleetkv.unpack_pages(payload)
            if header.get("cache_key") != self.cache_key:
                raise fleetkv.ShipError(
                    "donor/receiver decode identity mismatch — "
                    "refusing pages from a different model, page "
                    "size, kernel lane, or device")
            if not chunks:
                raise fleetkv.ShipError("donor had no cached pages")
            installed = self._kv_install(tokens, chunks, timeout)
        except Exception:
            # ANY failure — transport, framing, crc, identity, pool
            # pressure, chaos — falls back to plain prefill
            with self._cond:
                self._ship_stats["ship_failures"] += 1
            return 0
        if installed:
            with self._cond:
                self._ship_stats["page_ships"] += installed
                self._ship_stats["ship_bytes"] += len(payload)
        return installed

    def _kv_install(self, tokens, chunks, timeout: float) -> int:
        """Hand an install to the scheduler thread and wait: pool
        swaps happen outside the lock on that thread, so a scatter
        from this (handler) thread would race a prefill's swap. With
        no scheduler running (manual/test mode) the caller IS the
        scheduler — apply inline."""
        job = {"tokens": list(tokens), "chunks": chunks,
               "event": threading.Event(), "result": {}}
        self._enqueue_kv_job(job, timeout, "install did not complete "
                                           "within the ship budget")
        err = job["result"].get("error")
        if err is not None:
            raise err
        return int(job["result"].get("installed", 0))

    def _enqueue_kv_job(self, job: dict, timeout: float,
                        expiry_msg: str) -> None:
        """Route one pool-mutating job through the scheduler thread
        (or run it inline in manual/test mode) and wait it out."""
        if self.alive:
            with self._cond:
                if self._closed:
                    job["result"]["error"] = RuntimeError(
                        "decode loop is closed")
                    return
                self._kv_jobs.append(job)
                self._cond.notify_all()
            if not job["event"].wait(timeout=max(1.0, float(timeout))):
                job["result"].setdefault(
                    "error", fleetkv.ShipError(expiry_msg))
        else:
            self._run_kv_job(job)

    # ---- disaggregated prefill (docs/FLEET.md "Disaggregated roles")
    def prefill_only(self, tokens: Sequence[int],
                     timeout: Optional[float] = None) -> dict:
        """Handoff source: compute KV for `tokens`' FULL page-aligned
        head chunks into this replica's own pool and adopt the pages
        into the prefix trie as cached (refcount-zero, trie-retained)
        pages — exactly where `/kv/export` reads from — WITHOUT ever
        starting a stream. This is the whole job of a `prefill`-role
        replica: the router POSTs `/prefill` here, then names this
        replica as the `kv_donor` on the decode replica that owns the
        stream, whose existing `kv_ship` pulls the pages. No decode
        step, verify, or copy program is ever compiled by this path
        (role-scoped warmup plans pin that), and a fully-covered head
        is a cheap no-op — re-prefilling an already-hot prompt costs
        one trie match. Raises on pool pressure / chaos faults; the
        router treats ANY error as a failed handoff and falls back to
        plain unified prefill on the decode replica (bit-identical by
        the same causality argument the prefix cache rests on).
        Returns {"chunks", "covered", "cached", "kv_bytes"}."""
        if self._prefix is None:
            raise ValueError(
                "prefill_only needs the prefix cache: the trie is "
                "where handoff pages live until /kv/export ships them")
        n_full = len(tokens) // self.page_size
        if n_full == 0:
            # sub-page prompts have no trie key — nothing to hand off
            return {"chunks": 0, "covered": 0, "cached": 0,
                    "kv_bytes": 0}
        job = {"kind": "prefill", "tokens": [int(t) for t in tokens],
               "event": threading.Event(), "result": {}}
        if timeout is None:
            timeout = max(30.0, self.kv_ship_timeout)
        self._enqueue_kv_job(job, timeout, "prefill handoff did not "
                                           "complete within its budget")
        err = job["result"].get("error")
        if err is not None:
            raise err
        return job["result"]["report"]

    def _apply_prefill_only(self, tokens) -> dict:
        """Scheduler-thread half of `prefill_only`: pin the already-
        cached head run, allocate pages for the uncovered chunks, run
        the SAME bucketed prefill programs admission uses (bb=1 —
        recorded in the warmup plan like any other group), adopt the
        pages into the trie, release every pin. Mirrors
        `_kv_apply_install`'s pin/alloc/adopt/release discipline so
        the three-way page invariant holds at every exit."""
        import jax.numpy as jnp

        ps = self.page_size
        head = [int(t) for t in tokens[:(len(tokens) // ps) * ps]]
        n_full = len(head) // ps
        # chaos: a handoff fault on the EXPORT side — the router sees
        # the /prefill error, counts a failed handoff, and the stream
        # proceeds with plain prefill on its decode replica
        chaos.hit("disagg.handoff", role="export", chunks=n_full)
        with self._cond:
            matched = self._prefix.match(head)
            covered = len(matched)
            need = n_full - covered
            page_bytes = paged_kv_bytes(self.cfg, 1, self.page_size)
            if need <= 0:
                return {"chunks": n_full, "covered": covered,
                        "cached": 0, "kv_bytes": n_full * page_bytes}
            for page in matched:
                self._ref[page] += 1
            fresh: List[int] = []
            if self._avail_pages() >= need:
                for _ in range(need):
                    page = self._alloc_page()
                    if page is None:  # pragma: no cover — availability
                        break         # was checked above
                    fresh.append(page)
        try:
            if len(fresh) < need:
                raise OverloadedError(
                    f"prefill handoff needs {need} pages but the pool "
                    f"has no headroom "
                    f"({len(self._free)}/{self.n_pages} free)",
                    retry_after_ms=1000)
            cov_tok = covered * ps
            tl = len(head) - cov_tok
            tb = next(b for b in self._buckets if b >= tl)
            padded = np.zeros((1, tb), np.int32)
            padded[0, :tl] = head[cov_tok:]
            lens = np.full((1,), tl, np.int32)
            pids = np.full((1, tb // ps), self._trash, np.int32)
            pids[0, :len(fresh)] = fresh
            if covered == 0:
                self._plan_prefill.add((1, tb))
                _first, self._pool = self._prefill(
                    self.params, jnp.asarray(padded), jnp.asarray(lens),
                    self._pool, jnp.asarray(pids))
            else:
                cb = 1
                while cb < covered:
                    cb *= 2
                cb = min(cb, self._pps)
                ctab = np.full((1, cb), self._trash, np.int32)
                ctab[0, :covered] = matched
                clen = np.full((1,), cov_tok, np.int32)
                self._plan_prefill_ctx.add((1, cb, tb))
                _first, self._pool = self._prefill_ctx(
                    self.params, jnp.asarray(padded), jnp.asarray(lens),
                    self._pool, jnp.asarray(pids), jnp.asarray(ctab),
                    jnp.asarray(clen))
            self._prefill_token_count += tl
            with self._cond:
                adopted = self._prefix.insert(head, matched + fresh)
                self._ship_stats["prefill_handoffs"] = (
                    self._ship_stats.get("prefill_handoffs", 0) + 1)
            return {"chunks": n_full, "covered": covered,
                    "cached": adopted, "kv_bytes": n_full * page_bytes}
        finally:
            with self._cond:
                for page in matched + fresh:
                    self._release_page(page)
                self._cond.notify_all()

    def _service_kv_jobs(self) -> None:
        """Scheduler-thread drain of queued shipped-page installs —
        runs at the top of every tick, before admission, so a ship
        that lands between ticks warms the very next `_admit` match."""
        while True:
            with self._cond:
                if not self._kv_jobs:
                    return
                job = self._kv_jobs.popleft()
            self._run_kv_job(job)

    def _run_kv_job(self, job: dict) -> None:
        try:
            if job.get("kind") == "prefill":
                job["result"]["report"] = self._apply_prefill_only(
                    job["tokens"])
            else:
                job["result"]["installed"] = self._kv_apply_install(
                    job["tokens"], job["chunks"])
        except Exception as e:
            job["result"]["error"] = e
        finally:
            job["event"].set()

    def _drain_kv_jobs(self, exc: BaseException) -> None:
        with self._cond:
            while self._kv_jobs:
                job = self._kv_jobs.popleft()
                job["result"]["error"] = exc
                job["event"].set()

    def _kv_apply_install(self, tokens, chunks) -> int:
        """Install shipped chunk K/V beyond this trie's current
        coverage: pin the existing matched path (an eviction during
        our own allocations must not consume it), allocate fresh pages
        through the normal ladder (free list first, LRU eviction
        second), scatter the bytes, adopt the pages into the trie,
        then drop every pin — adopted pages land in the cached
        (refcount-zero, trie-retained) tier exactly like a retired
        prompt's. Runs on the scheduler thread."""
        ps = self.page_size
        with self._cond:
            matched = self._prefix.match(tokens)
            covered = len(matched)
            depth = min(len(chunks), len(tokens) // ps)
            if depth <= covered:
                return 0
            need = depth - covered
            for page in matched:
                self._ref[page] += 1
            fresh: List[int] = []
            if self._avail_pages() >= need:
                for _ in range(need):
                    page = self._alloc_page()
                    if page is None:  # pragma: no cover — availability
                        break         # was checked above
                    fresh.append(page)
        try:
            if len(fresh) < need:
                raise fleetkv.ShipError(
                    "pool has no headroom for shipped pages")
            pool = self._pool
            for j, page in enumerate(fresh):
                pool = install_page(pool, page, chunks[covered + j])
            self._pool = pool  # scheduler thread: no concurrent swap
            with self._cond:
                adopted = self._prefix.insert(
                    tokens[:depth * ps], matched + fresh)
            return adopted
        finally:
            with self._cond:
                for page in matched + fresh:
                    self._release_page(page)
                self._cond.notify_all()

    def snapshot(self) -> dict:
        with self._cond:
            return {
                "role": self.role,
                "slots": self.slots,
                "occupied_slots": self.occupied_slots,
                "queued": len(self._waiting),
                "page_size": self.page_size,
                "horizon": self.horizon,
                "pages_total": self.n_pages,
                "pages_in_use": self.pages_in_use,
                "peak_pages_in_use": self._peak_pages,
                "pool_bytes": self.kv_pool_bytes(),
                "max_waiting": self.max_waiting,
                "tiers": {
                    "batch_share": self.batch_share,
                    "batch_slot_cap": self._batch_slot_cap,
                    "batch_max_waiting": self.batch_max_waiting,
                    "preemptions": int(self._m_preempt.value),
                    "waiting": dict(self._tier_waiting),
                    "occupied": {
                        t: sum(1 for s in self._slot_state
                               if s is not None and s.stream.tier == t)
                        for t in TIERS},
                    "requests": {
                        t: int(self._m_tier_requests[t].value)
                        for t in TIERS},
                    "shed": {
                        t: int(self._m_tier_shed[t].value)
                        for t in TIERS},
                },
                "requests": int(self._m_requests.value),
                "tokens_streamed": int(self._m_tokens.value),
                "shed": int(self._m_shed.value),
                "deadline_exceeded": int(self._m_deadline.value),
                "cancelled": int(self._m_cancelled.value),
                "admission_waits": int(self._m_waits.value),
                "dispatches": int(self._m_steps.value),
                "decode_kernel": {
                    "requested": self.kernel_requested,
                    "selected": self.decode_kernel,
                    "kv_read_bytes": {
                        "kernel": int(self._m_kv_read["kernel"].value),
                        "gather": int(self._m_kv_read["gather"].value),
                    },
                },
                "decode_step_programs": self.decode_step_programs(),
                "prefill_programs": self.prefill_programs(),
                "prefill_ctx_programs": jit_cache_size(self._prefill_ctx),
                "prefill_tokens": self._prefill_token_count,
                "prefix_cache": {
                    "enabled": self.prefix_cache_enabled,
                    "hits": int(self._m_hits.value),
                    "misses": int(self._m_misses.value),
                    "forks": int(self._m_forks.value),
                    "evictions": int(self._m_evictions.value),
                    "pages_cached": self.pages_cached,
                    "pages_shared": self.pages_shared,
                    "cached_unreferenced": self._cached_unref(),
                    "nodes": (0 if self._prefix is None
                              else len(self._prefix)),
                },
                "fleet_kv": {
                    "mode": self.fleet_kv,
                    **self._ship_stats,
                },
                "speculation": {
                    "enabled": bool(self.spec_k),
                    "k": self.spec_k,
                    "drafter": (None if self._drafter is None
                                else self._drafter.kind),
                    "proposed": int(self._m_spec_proposed.value),
                    "accepted": int(self._m_spec_accepted.value),
                    "rounds": int(self._m_spec_rounds.value),
                    "acceptance_rate": self.spec_acceptance_rate,
                    "draft_programs": (
                        self._drafter.draft_programs()
                        if self._drafter is not None
                        and hasattr(self._drafter, "draft_programs")
                        else 0),
                },
            }

    def close(self, timeout: float = 30.0) -> None:
        """Stop accepting new requests, drain everything queued and in
        flight, stop the scheduler thread."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    def __enter__(self) -> "DecodeLoop":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------ scheduler
    def _run(self) -> None:
        while True:
            with self._cond:
                while (not self._closed and not self._waiting
                       and not self._kv_jobs
                       and self.occupied_slots == 0):
                    self._cond.wait(timeout=0.1)
                if (self._closed and not self._waiting
                        and self.occupied_slots == 0):
                    self._drain_kv_jobs(
                        RuntimeError("decode loop closed"))
                    return
            try:
                self.tick()
            except Exception as e:  # pragma: no cover — defensive: a
                # scheduler crash must fail the in-flight streams loudly
                # instead of hanging every waiting client
                self._fail_all(e)
                return

    def _fail_all(self, exc: BaseException) -> None:
        self._drain_kv_jobs(exc)
        with self._cond:
            self._deferred = []
            for i, slot in enumerate(self._slot_state):
                if slot is not None:
                    for page in slot.pages:
                        self._release_page(page)
                    slot.stream._finish("error", exc)
                    self._slot_state[i] = None
            while self._waiting:
                stream = self._waiting.popleft()
                self._tier_waiting[stream.tier] -= 1
                stream._finish("error", exc)

    def tick(self) -> bool:
        """One scheduler pass: admit what fits, grant boundary pages,
        run one compiled dispatch if any slot can advance, emit tokens,
        retire finished slots. Returns True if a dispatch ran. Public so
        tests (and `start=False` callers) can drive the loop
        deterministically."""
        self._reap()
        # shipped-page installs land before admission so the very next
        # `_admit` match sees them as cached chunks
        self._service_kv_jobs()
        # chaos point: a "delay" rule paces every scheduler pass (the
        # SLO drills use it to pin slot occupancy open long enough for
        # preemption to observably fire); an "error" drills the
        # fail-loudly path in _run
        chaos.hit("decode.step")
        self._admit()
        ran = self._dispatch()
        if not ran:
            # no chunk ran (e.g. every admitted request has
            # max_tokens=1): deferred prefill tokens still must reach
            # their streams
            self._flush_first_tokens()
        if not ran:
            # nothing advanced: either idle, or every occupied slot is
            # starved of pages that can never come — fail those rather
            # than spin forever
            with self._cond:
                stuck = (self.occupied_slots > 0
                         and self._avail_pages() == 0
                         and all(s is None
                                 or self._stop[i] <= self._lengths[i]
                                 for i, s in enumerate(self._slot_state)))
            if stuck:
                self._fail_all(RuntimeError(
                    "KV page pool exhausted with every slot stalled — "
                    "no completion can free a page; size the pool with "
                    "paged_kv_bytes (docs/SERVING.md)"))
        return ran

    def run_until_idle(self, max_ticks: int = 100_000) -> None:
        """Drive the loop inline until nothing is queued or in flight
        (manual mode / tests)."""
        for _ in range(max_ticks):
            with self._cond:
                if not self._waiting and self.occupied_slots == 0:
                    return
            self.tick()
        raise RuntimeError("decode loop did not drain")

    # ---- cancellation / expiry reaping
    def _reap(self) -> None:
        """Retire occupied slots whose stream was cancelled (client
        disconnect, explicit `cancel()`) or whose deadline budget died
        mid-flight: the slot is released and its pages return to the
        pool within THIS scheduler pass — an abandoned stream must not
        keep burning pages (docs/SERVING.md "Cancellation")."""
        with self._cond:
            for i, slot in enumerate(self._slot_state):
                if slot is None:
                    continue
                stream = slot.stream
                if stream.cancelled:
                    self._m_cancelled.inc()
                    self._retire(i, slot, "cancelled")
                elif (stream.deadline is not None
                      and stream.deadline.expired):
                    self._m_deadline.inc()
                    self._retire(i, slot, "deadline_exceeded",
                                 error=DeadlineExceededError(
                                     "deadline exceeded mid-generation",
                                     deadline_ms=stream.deadline.budget_ms,
                                     elapsed_ms=stream.deadline
                                     .elapsed_ms()))

    # ---- admission
    def _preempt_one(self, used: set) -> bool:
        """Evict ONE batch-held slot so a blocked interactive admission
        can proceed. The victim — the batch slot with the FEWEST tokens
        emitted, the cheapest to resume — retires with finish_reason
        "preempted" and error None: every token it already emitted was
        already streamed (and dedupable by absolute `token_index`), its
        pages return to the pool, and its full prompt pages seed the
        prefix cache so the router-side durable-stream resume replays
        the prefix nearly for free. Lossless by construction — the
        router re-admits `prompt + delivered` with the remaining budget
        exactly as a replica-failure resume would (docs/SERVING.md
        "Priority tiers"). Pure host bookkeeping: the retirement path
        is the cancel/deadline one, so `decode_step_programs()` never
        moves. Returns True when a victim was retired. Caller holds the
        lock."""
        victim = None
        for i, slot in enumerate(self._slot_state):
            if slot is None or slot.stream.tier != TIER_BATCH:
                continue
            if (victim is None or slot.emitted
                    < self._slot_state[victim].emitted):
                victim = i
        if victim is None:
            return False
        self._m_preempt.inc()
        self._retire(victim, self._slot_state[victim], "preempted")
        used.discard(victim)
        return True

    def _admit(self) -> None:
        import jax.numpy as jnp

        ps = self.page_size
        # claim everything that fits in one lock pass
        admitted = []  # (slot_idx, stream, pages, plen, covered)
        with self._cond:
            used = {i for i, s in enumerate(self._slot_state)
                    if s is not None}
            batch_held = sum(1 for s in self._slot_state
                             if s is not None
                             and s.stream.tier == TIER_BATCH)
            inter_held = len(used) - batch_held
            while self._waiting:
                # tier-priority scan: every interactive arrival goes
                # ahead of every batch one (FIFO within a tier) — a
                # head-of-line bulk prompt must never make the user who
                # is watching wait
                stream = next((s for s in self._waiting
                               if s.tier == TIER_INTERACTIVE),
                              self._waiting[0])
                interactive = stream.tier == TIER_INTERACTIVE
                # queue-expired or cancelled work is shed here, BEFORE
                # any prefill compute (the dispatch counters pin it)
                if stream.cancelled:
                    self._waiting.remove(stream)
                    self._tier_waiting[stream.tier] -= 1
                    self._m_cancelled.inc()
                    stream._finish("cancelled")
                    continue
                if (stream.deadline is not None
                        and stream.deadline.expired):
                    self._waiting.remove(stream)
                    self._tier_waiting[stream.tier] -= 1
                    self._m_deadline.inc()
                    stream._finish(
                        "deadline_exceeded", DeadlineExceededError(
                            "deadline exceeded while queued for a "
                            "decode slot",
                            deadline_ms=stream.deadline.budget_ms,
                            elapsed_ms=stream.deadline.elapsed_ms()))
                    continue
                if (not interactive
                        and batch_held >= self._batch_slot_cap
                        and inter_held > 0):
                    # weighted-fair share: while interactive work is
                    # live on the machine, batch holds at most its
                    # share of the slots — it soaks ALL idle capacity
                    # only when no user-facing work wants it
                    self._m_waits.inc()
                    break
                plen = len(stream.prompt)
                idx = next((i for i in range(self.slots)
                            if i not in used), None)
                while (idx is None and interactive
                       and self._preempt_one(used)):
                    batch_held -= 1
                    idx = next((i for i in range(self.slots)
                                if i not in used), None)
                if idx is None:
                    self._m_waits.inc()
                    break
                # longest cached prefix of FULL page-aligned chunks:
                # those pool pages are mapped by reference, only the
                # uncovered tail is prefilled
                use_cache = (self._prefix is not None
                             and stream.prefix_cache)
                matched = (self._prefix.match(stream.prompt)
                           if use_cache else [])
                covered = len(matched) * ps
                # reference the cached run FIRST, so the availability
                # check and any eviction below can never consume the
                # very pages this request is about to read
                for page in matched:
                    self._ref[page] += 1
                # uncovered prompt pages + room for the first decode
                # write (when fully covered, that is the CoW fork's
                # headroom) — the check that replaces the contiguous
                # path's whole-max_len reservation
                need = pages_for_tokens(plen + 1, ps) - len(matched)
                while (self._avail_pages() < need and interactive
                       and self._preempt_one(used)):
                    batch_held -= 1
                if self._avail_pages() < need:
                    for page in matched:
                        self._release_page(page)
                    self._m_waits.inc()
                    break
                self._waiting.remove(stream)
                self._tier_waiting[stream.tier] -= 1
                if interactive:
                    inter_held += 1
                else:
                    batch_held += 1
                used.add(idx)
                alloc = pages_for_tokens(plen, ps) - len(matched)
                pages = list(matched)
                for _ in range(alloc):
                    page = self._alloc_page()
                    if page is None:  # pragma: no cover — availability
                        raise AssertionError(  # was checked above
                            "page allocation failed after availability "
                            "check")
                    pages.append(page)
                if use_cache:
                    (self._m_hits if matched else self._m_misses).inc()
                admitted.append((idx, stream, pages, plen, covered))
            if admitted:
                self._peak_pages = max(self._peak_pages,
                                       self.pages_in_use)
        if not admitted:
            return
        cold = [a for a in admitted if a[4] == 0]
        warm = [a for a in admitted if 0 < a[4] < a[3]]
        full = [a for a in admitted if a[4] >= a[3]]
        # fully-covered prompts skip prefill entirely: the slot starts
        # ONE position early with its last prompt token pending, so the
        # first compiled decode dispatch recomputes position plen-1 —
        # its K/V write re-enters the last shared page, which the CoW
        # guard forks before the dispatch — and emits the first token.
        for idx, stream, pages, plen, covered in full:
            slot = _Slot(stream, pages,
                         stop_len=plen + stream.max_tokens - 1)
            slot.awaiting_first = False
            with self._cond:
                self._slot_state[idx] = slot
                self._table[idx, :len(pages)] = pages
                self._lengths[idx] = plen - 1
                self._pending[idx] = stream.prompt[-1]
                self._stop[idx] = 0  # set by _grant_pages
                self._dirty = True
        # one compiled prefill per (prompt-bucket, batch-bucket) group:
        # an admission burst costs O(groups) dispatches, not O(streams).
        # The prefill is dispatched but NOT synced — first tokens stay
        # on device until the next flush, so back-to-back groups queue
        # without a host round trip between them.
        by_bucket: dict = {}
        for item in cold:
            tb = next(b for b in self._buckets if b >= item[3])
            by_bucket.setdefault(tb, []).append(item)
        for tb, group in by_bucket.items():
            bb = 1
            while bb < len(group):
                bb *= 2
            n_pids = tb // ps
            padded = np.zeros((bb, tb), np.int32)
            lens = np.ones((bb,), np.int32)  # pad rows: true_len 1
            pids = np.full((bb, n_pids), self._trash, np.int32)
            for row, (idx, stream, pages, plen, _cov) in enumerate(group):
                padded[row, :plen] = stream.prompt
                lens[row] = plen
                pids[row, :len(pages)] = pages
                self._prefill_token_count += plen
            self._plan_prefill.add((bb, tb))
            first, self._pool = self._prefill(
                self.params, jnp.asarray(padded), jnp.asarray(lens),
                self._pool, jnp.asarray(pids))
            self._install_prefilled(group, first)
        # warm tails ride the ctx-aware prefill, bucketed by (cached
        # pages, tail length) — tails start on a page boundary by
        # construction (only FULL chunks match)
        by_ctx: dict = {}
        for item in warm:
            idx, stream, pages, plen, covered = item
            cb = 1
            while cb < covered // ps:
                cb *= 2
            cb = min(cb, self._pps)
            tb = next(b for b in self._buckets if b >= plen - covered)
            by_ctx.setdefault((cb, tb), []).append(item)
        for (cb, tb), group in by_ctx.items():
            bb = 1
            while bb < len(group):
                bb *= 2
            n_pids = tb // ps
            padded = np.zeros((bb, tb), np.int32)
            lens = np.ones((bb,), np.int32)
            pids = np.full((bb, n_pids), self._trash, np.int32)
            ctab = np.full((bb, cb), self._trash, np.int32)
            clen = np.zeros((bb,), np.int32)
            for row, (idx, stream, pages, plen, cov) in enumerate(group):
                cp = cov // ps
                tl = plen - cov
                padded[row, :tl] = stream.prompt[cov:]
                lens[row] = tl
                pids[row, :len(pages) - cp] = pages[cp:]
                ctab[row, :cp] = pages[:cp]
                clen[row] = cov
                self._prefill_token_count += tl
            self._plan_prefill_ctx.add((bb, cb, tb))
            first, self._pool = self._prefill_ctx(
                self.params, jnp.asarray(padded), jnp.asarray(lens),
                self._pool, jnp.asarray(pids), jnp.asarray(ctab),
                jnp.asarray(clen))
            self._install_prefilled(group, first)

    def _install_prefilled(self, group, first) -> None:
        """Install slots for one prefill group; first tokens stay on
        device until the next flush (`self._deferred`)."""
        members = []
        for row, (idx, stream, pages, plen, _cov) in enumerate(group):
            slot = _Slot(stream, pages,
                         stop_len=plen + stream.max_tokens - 1)
            members.append((row, idx))
            with self._cond:
                self._slot_state[idx] = slot
                self._table[idx, :len(pages)] = pages
                self._lengths[idx] = plen
                self._pending[idx] = 0  # real value still on device
                self._stop[idx] = 0  # set by _grant_pages
                self._dirty = True
        self._deferred.append((first, members))

    # ---- page granting
    def _grant_pages(self) -> None:
        """Before a dispatch: give every occupied slot pages covering
        its next advance-window positions (`horizon` plain steps, or
        the `spec_k`-draft + 1 verify width in speculative mode, capped
        at its token budget) and set its device `stop` bound to the
        granted frontier — a slot the pool cannot extend simply stops
        advancing there. Because the CoW guard fences the WHOLE
        [length, stop) window, every position a speculative verify may
        write — including draft tokens that get rejected — lands in
        private pages: rollback is just the host cursor not moving."""
        adv = (self.spec_k + 1) if self.spec_k else self.horizon
        with self._cond:
            for i, slot in enumerate(self._slot_state):
                if slot is None:
                    continue
                length = int(self._lengths[i])
                target = min(length + adv, slot.stop_len)
                want = pages_for_tokens(target, self.page_size)
                granted = False
                while len(slot.pages) < want:
                    page = self._alloc_page()
                    if page is None:
                        break
                    self._table[i, len(slot.pages)] = page
                    slot.pages.append(page)
                    granted = True
                if granted:
                    self._peak_pages = max(self._peak_pages,
                                           self.pages_in_use)
                alloc_end = len(slot.pages) * self.page_size
                stop = min(slot.stop_len, alloc_end)
                if stop > length:
                    stop = self._cow_guard(i, slot, length, stop)
                if stop <= length and slot.stop_len > length:
                    self._m_waits.inc()  # page-starved this pass
                if stop != self._stop[i]:
                    self._stop[i] = stop
                    self._dirty = True

    def _cow_guard(self, i: int, slot: _Slot, length: int,
                   stop: int) -> int:
        """Copy-on-write fence, run before every dispatch: positions
        [length, stop) are about to be WRITTEN, so any page in that
        range that is still shared — mapped by another slot, or
        retained by the prefix index — is forked into a private copy
        first (`copy_page` duplicates the exact bytes, so outputs are
        unchanged). When no page can be obtained for the fork, the
        slot's stop bound clamps to the shared frontier: the same
        stall-until-a-retirement-frees-pages backpressure as page
        granting. Chaos point `decode.fork` fires inside the fork so
        drills can prove a mid-fork fault leaves page accounting
        balanced. Caller holds the lock."""
        import jax.numpy as jnp

        ps = self.page_size
        for j in range(length // ps, (stop - 1) // ps + 1):
            page = slot.pages[j]
            if not self._is_shared(page):
                continue
            new = self._alloc_page()
            if new is None:
                # fork-under-pressure: hold just before the shared page
                return max(length, j * ps)
            try:
                chaos.hit("decode.fork")
                self._plan_copy = True
                self._pool = self._copy(
                    self._pool, jnp.asarray(page, jnp.int32),
                    jnp.asarray(new, jnp.int32))
            except BaseException:
                # balance the books before propagating: the fresh page
                # goes straight back (nothing was mapped into it), the
                # shared page keeps all its readers
                self._release_page(new)
                raise
            slot.pages[j] = new
            self._table[i, j] = new
            slot.no_cache.add(new)
            self._release_page(page)
            self._m_forks.inc()
            self._dirty = True
        return stop

    # ---- one compiled dispatch
    def _dispatch(self) -> bool:
        """Route one dispatch round: draft-and-verify when speculation
        is on, the horizon chain otherwise."""
        if self.spec_k:
            return self._dispatch_spec()
        return self._dispatch_plain()

    # ---- plain dispatch (horizon token steps)
    def _dispatch_plain(self) -> bool:
        import jax.numpy as jnp

        self._grant_pages()
        with self._cond:
            runnable = [i for i, s in enumerate(self._slot_state)
                        if s is not None
                        and self._stop[i] > self._lengths[i]]
            if not runnable:
                return False
            before = self._lengths.copy()
            if self._dirty or self._d_tokens is None:
                self._d_tokens = jnp.asarray(self._pending)
                self._d_table = jnp.asarray(self._table)
                self._d_lengths = jnp.asarray(self._lengths)
                self._d_stop = jnp.asarray(self._stop)
                self._dirty = False
            # overlay deferred prefill tokens (still device-resident)
            # into the feedback array — ONE scatter per prefill group,
            # no sync
            for arr, members in self._deferred:
                rows = jnp.asarray([r for r, _ in members])
                idxs = jnp.asarray([i for _, i in members])
                self._d_tokens = self._d_tokens.at[idxs].set(arr[rows])
        t0 = time.perf_counter()
        self._plan_step = True
        toks, t_out, l_out, self._pool = self._step(
            self.params, self._d_tokens, self._pool, self._d_table,
            self._d_lengths, self._d_stop)
        self._m_steps.inc()
        # the (K, S) token D2H is the sync the streams need anyway
        toks = np.asarray(toks)
        self._m_step_s.observe(time.perf_counter() - t0)
        self._d_tokens, self._d_lengths = t_out, l_out
        # per-token-step KV read accounting, host math mirroring the
        # device chain: inner step k runs at cursor before+k, clamped
        # at each slot's stop bound (stalled/idle slots hold still).
        # Both figures are recorded each dispatch — the selected lane
        # is in snapshot()["decode_kernel"]
        advance = np.maximum(self._stop - before, 0)
        ideal = dense = 0
        for k in range(self.horizon):
            cur = before + np.minimum(k, advance)
            ideal += decode_read_bytes(self._pool, cur, self._pps)
            dense += decode_read_bytes(self._pool, cur, self._pps,
                                       dense=True)
        self._m_kv_read["kernel"].inc(ideal)
        self._m_kv_read["gather"].inc(dense)
        self._flush_first_tokens()  # emit firsts BEFORE chunk tokens
        for i in runnable:
            slot = self._slot_state[i]
            if slot is None:  # retired at flush (eos on first token)
                continue
            consumed = min(self.horizon, int(self._stop[i] - before[i]))
            with self._cond:
                self._lengths[i] = before[i] + consumed
            for j in range(consumed):
                tok = int(toks[j, i])
                self._pending[i] = tok
                slot.emitted += 1
                self._emit_and_maybe_finish(i, slot, tok)
                if self._slot_state[i] is None:
                    break  # retired: discard speculative overshoot
        return True

    # ---- speculative dispatch (draft k on the host, verify k+1 wide)
    def _dispatch_spec(self) -> bool:
        """One draft-and-verify round. Per runnable slot the drafter
        proposes up to k continuation tokens; ONE widened verify step
        feeds `[pending, d_1..d_k]` at cursors `length..length+k` and
        returns the target model's argmax after every prefix. The
        accepted run is the longest m with `d_j == argmax_{j-1}`, and
        the emitted tokens are `argmax_0..argmax_m` — the first
        disagreement (or the tail when all agree) is the verify step's
        OWN next token, so each round delivers m+1 tokens and the
        stream is bit-identical to plain decode by induction. Rollback
        of rejected positions is pure host bookkeeping: the cursor just
        doesn't advance past m, and the garbage K/V beyond it sits in
        CoW-private pages (see `_grant_pages`), masked by `key_pos <=
        query_pos`, and overwritten by the next round before any query
        can see it."""
        import jax.numpy as jnp

        # drafting extends each slot's last token on the HOST, so any
        # deferred prefill firsts flush (one D2H per group) and emit
        # now — same firsts-before-chunk order as the plain lane
        if self._deferred:
            self._flush_first_tokens()
            self._dirty = True  # firsts never reached the device carry
        self._grant_pages()
        with self._cond:
            runnable = [i for i, s in enumerate(self._slot_state)
                        if s is not None
                        and self._stop[i] > self._lengths[i]]
            if not runnable:
                return False
            before = self._lengths.copy()
        W = self.spec_k + 1
        tokens = np.zeros((self.slots, W), np.int32)
        widths = np.zeros((self.slots,), np.int32)
        proposals = {}
        model_rows = []
        for i in runnable:
            slot = self._slot_state[i]
            tokens[i, 0] = self._pending[i]
            widths[i] = 1
            # room for length-advance this round; >= 2 means at least
            # one draft position fits under the granted/budget frontier
            room = int(self._stop[i] - before[i])
            if room < 2 or not slot.stream.speculation:
                continue
            if self._drafter.kind == "model":
                model_rows.append(i)
            else:
                history = slot.stream.prompt + slot.stream._generated
                prop = self._drafter.propose(
                    history, min(self.spec_k, room - 1))
                if prop:
                    proposals[i] = [int(t) for t in prop]
        if model_rows:
            # one fixed-shape (S, window) batch through the draft
            # program — idle rows ride along and are ignored
            win = self._drafter.window
            windows = np.zeros((self.slots, win), np.int32)
            for i in model_rows:
                slot = self._slot_state[i]
                hist = (slot.stream.prompt
                        + slot.stream._generated)[-win:]
                windows[i, win - len(hist):] = hist
            drafted = self._drafter.propose_all(windows, self.spec_k)
            for i in model_rows:
                room = int(self._stop[i] - before[i])
                prop = [int(t) for t in
                        drafted[i, :min(self.spec_k, room - 1)]]
                if prop:
                    proposals[i] = prop
        if not proposals:
            # nothing drafted — run the plain width-1 chain instead so
            # an idle/unluckly round costs exactly what it always did
            # and the plain program stays warm
            return self._dispatch_plain()
        for i, prop in proposals.items():
            n = len(prop)
            tokens[i, 1:1 + n] = prop
            widths[i] = 1 + n
            self._m_spec_proposed.inc(n)
        t0 = time.perf_counter()
        self._plan_verify = True
        out, self._pool = self._verify(
            self.params, jnp.asarray(tokens), self._pool,
            jnp.asarray(self._table), jnp.asarray(before),
            jnp.asarray(widths))
        self._m_steps.inc()
        self._m_spec_rounds.inc()
        out = np.asarray(out)  # (S, W) argmax — the sync streams need
        self._m_step_s.observe(time.perf_counter() - t0)
        # KV read accounting mirrors the widened step: column j of slot
        # i attends at cursor before+j (clamped to its real width)
        for j in range(int(widths.max())):
            cur = before + np.minimum(j, np.maximum(widths - 1, 0))
            self._m_kv_read["kernel"].inc(
                decode_read_bytes(self._pool, cur, self._pps))
            self._m_kv_read["gather"].inc(
                decode_read_bytes(self._pool, cur, self._pps,
                                  dense=True))
        for i in runnable:
            slot = self._slot_state[i]
            if slot is None:
                continue
            prop = proposals.get(i, [])
            m = 0
            while m < len(prop) and prop[m] == int(out[i, m]):
                m += 1
            with self._cond:
                self._lengths[i] = before[i] + m + 1
            if prop:
                self._m_spec_accepted.inc(m)
            for j in range(m + 1):
                tok = int(out[i, j])
                self._pending[i] = tok
                slot.emitted += 1
                self._emit_and_maybe_finish(i, slot, tok)
                if self._slot_state[i] is None:
                    break  # retired (eos/budget): overshoot discarded
        # host cursors moved without touching the plain device carry —
        # any later plain-lane dispatch must re-upload
        self._dirty = True
        return True

    def _flush_first_tokens(self) -> None:
        """Read deferred prefill tokens (one D2H per prefill group —
        the compute is long finished) and emit them."""
        deferred, self._deferred = self._deferred, []
        for arr, members in deferred:
            host = np.asarray(arr)
            for row, i in members:
                slot = self._slot_state[i]
                if slot is None or not slot.awaiting_first:
                    continue  # failed/cleared meanwhile
                tok = int(host[row])
                slot.awaiting_first = False
                self._pending[i] = tok
                slot.emitted += 1
                self._emit_and_maybe_finish(i, slot, tok)

    # ---- emission / retirement
    def _emit_and_maybe_finish(self, idx: int, slot: _Slot,
                               token: int) -> None:
        stream = slot.stream
        stream._emit(token)
        self._m_tokens.inc()
        if (stream.eos_id is not None and token == stream.eos_id):
            self._retire(idx, slot, "eos")
        elif slot.emitted >= stream.max_tokens:
            self._retire(idx, slot, "max_tokens")

    def _retire(self, idx: int, slot: _Slot, reason: str,
                error: Optional[BaseException] = None) -> None:
        with self._cond:
            self._slot_state[idx] = None
            self._table[idx, :] = self._trash
            self._lengths[idx] = 0
            self._stop[idx] = 0
            self._pending[idx] = 0
            if (self._prefix is not None and slot.stream.prefix_cache
                    and reason in ("eos", "max_tokens", "preempted")):
                # seed the cache with the FULL prompt pages only —
                # decode pages hold this request's continuation, and a
                # partial prompt page would be rewritten by the next
                # reader's cursor. Forked pages never seed (no_cache):
                # their bytes diverged from the pure token sequence.
                # "preempted" seeds too: the durable-stream resume
                # re-sends this prompt as a prefix, and the cache is
                # what makes that replay near-free.
                n_full = len(slot.stream.prompt) // self.page_size
                self._prefix.insert(slot.stream.prompt,
                                    slot.pages[:n_full],
                                    skip=slot.no_cache)
            for page in slot.pages:
                self._release_page(page)
            self._dirty = True
            self._cond.notify_all()  # admissions may proceed
        slot.stream._finish(reason, error)
