"""Thin stdlib HTTP JSON front end over engines/replicas/batcher.

Same embedded-server pattern as plot/render_server.py (and the shared
lifecycle helper in utils/httpd.py): ThreadingHTTPServer on a daemon
thread, port-0 auto-assign, graceful close. Endpoints:

- ``POST /predict``  {"inputs": [[...], ...]} ->
  {"outputs": [[...]...], "classes": [...]} — rows go through the
  shared micro-batcher (coalescing concurrent clients) onto the
  round-robin replica set.
- ``POST /generate`` {"prompt": [[...tokens]], "n_tokens": N} ->
  {"tokens": [[...]]} — KV-cached decode (requires a transformer
  engine; 404 otherwise).
- ``POST /reload``   {"path": "<checkpoint dir or .ckpt>", "step": N?}
  — hot-swap every replica's weights from a checkpoint
  (docs/CHECKPOINTS.md) WITHOUT dropping in-flight requests: each
  engine validates shapes, stages the new params on its device, then
  swaps by a single reference assignment.
- ``GET /healthz``   liveness + replica count.
- ``GET /stats``     replica + batcher (queue depth, per-bucket forward
  counts) + uptime counters + last reload.
- ``GET /metrics``   Prometheus text exposition of the process-global
  telemetry registry (train/serve/guardian/device series —
  docs/OBSERVABILITY.md); ``GET /snapshot`` is the JSON twin.

This front end is deliberately minimal (stdlib only, JSON in/out, one
process): production fronting (TLS, auth, load shedding) belongs in the
infra layer; the contract that matters here is that everything behind
the socket is already batched, bucketed, and compiled once per shape.
"""

from __future__ import annotations

import json
import time
from concurrent.futures import Future
from http.server import BaseHTTPRequestHandler
from typing import Optional

import numpy as np

from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.serving.engine import InferenceEngine
from deeplearning4j_tpu.serving.replicas import ReplicaSet
from deeplearning4j_tpu.telemetry import exposition
from deeplearning4j_tpu.utils.httpd import ServerHandle, start_http_server

__all__ = ["ServingHandle", "serve_network"]

_M_RELOADS = telemetry.counter(
    "dl4j_serve_reloads", "hot checkpoint reloads applied to the replicas")

#: per-request wait on the batcher future — generous; the batcher bounds
#: queueing at max_delay_ms, so hitting this means the engine died
_RESULT_TIMEOUT_S = 120.0


class ServingHandle:
    """A running serving endpoint: http handle + batcher + replicas.

    Constructed (and handed to the request handler) BEFORE the socket
    opens — `http` is attached right after bind — so /stats is safe from
    the first accepted connection; stats() never touches `http`.
    """

    def __init__(self, replicas: ReplicaSet, batcher,
                 generate_engine: Optional[InferenceEngine],
                 http: Optional[ServerHandle] = None):
        self.http = http
        self.replicas = replicas
        self.batcher = batcher
        self.generate_engine = generate_engine
        self.started_at = time.time()
        self.last_reload: Optional[dict] = None

    @property
    def url(self) -> str:
        return self.http.url

    @property
    def port(self) -> int:
        return self.http.port

    def close(self) -> None:
        """Stop accepting requests, flush the batcher, release the
        socket."""
        self.http.close()
        if self.batcher is not None:
            self.batcher.close()

    def __enter__(self) -> "ServingHandle":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def stats(self) -> dict:
        out = {"uptime_s": round(time.time() - self.started_at, 3),
               "replicas": self.replicas.snapshot()}
        if self.batcher is not None:
            out["batcher"] = self.batcher.snapshot()
        if self.generate_engine is not None:
            out["generate"] = self.generate_engine.snapshot()
        if self.last_reload is not None:
            out["last_reload"] = self.last_reload
        return out

    def load_checkpoint(self, path: str, step: Optional[int] = None) -> dict:
        """Hot-swap replica weights from a checkpoint path (sharded dir
        or legacy npz) without dropping in-flight requests; records the
        reload in /stats. The HTTP `/reload` route calls this."""
        info = self.replicas.load_checkpoint(path, step=step)
        self.last_reload = {
            "path": path,
            "step": info.get("step"),
            "iterator_position": info.get("iterator_position"),
            "at": time.time(),
        }
        _M_RELOADS.inc()
        return info


def serve_network(net=None, *, replicas: Optional[ReplicaSet] = None,
                  generate_engine: Optional[InferenceEngine] = None,
                  n_replicas: Optional[int] = None,
                  max_batch_size: int = 64, max_delay_ms: float = 2.0,
                  host: str = "127.0.0.1", port: int = 0,
                  warmup_shape=None) -> ServingHandle:
    """Serve a MultiLayerNetwork (or a prebuilt ReplicaSet) over HTTP.

    Pass `net` for the common case — a replica set is built across
    local devices (capped by `n_replicas`) with `max_batch_size` as the
    top of each engine's bucket ladder — or pass `replicas=` directly
    for custom engines. `generate_engine` (an
    InferenceEngine.for_transformer) enables /generate.
    `warmup_shape` (one example's feature shape) precompiles every
    bucket before the socket opens.
    """
    if replicas is None:
        if net is None:
            raise ValueError("serve_network needs net= or replicas=")
        replicas = ReplicaSet.for_network(net, n_replicas=n_replicas,
                                          max_batch_size=max_batch_size)
    if warmup_shape is not None:
        replicas.warmup(tuple(warmup_shape))
    batcher = replicas.batcher(max_batch_size=max_batch_size,
                               max_delay_ms=max_delay_ms)
    handle = ServingHandle(replicas, batcher, generate_engine)

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):  # quiet
            pass

        def _reply(self, code: int, payload: dict) -> None:
            self._reply_raw(code, "application/json",
                            json.dumps(payload).encode())

        def _reply_raw(self, code: int, ctype: str, body: bytes) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _read_json(self) -> dict:
            length = int(self.headers.get("Content-Length") or 0)
            if length <= 0:
                raise ValueError("missing request body")
            data = json.loads(self.rfile.read(length))
            if not isinstance(data, dict):
                raise ValueError("request body must be a JSON object")
            return data

        # ------------------------------------------------------- routes
        def do_GET(self):
            try:
                if self.path.startswith("/healthz"):
                    self._reply(200, {"ok": True,
                                      "replicas": len(replicas.engines)})
                elif self.path.startswith("/stats"):
                    self._reply(200, handle.stats())
                elif (hit := exposition.handle_metrics_get(
                        self.path)) is not None:
                    self._reply_raw(*hit)
                else:
                    self._reply(404, {"error": f"no route {self.path}"})
            except Exception as e:  # always answer with a status line
                self._reply(500, {"error": f"{type(e).__name__}: {e}"})

        def do_POST(self):
            try:
                if self.path.startswith("/predict"):
                    self._predict()
                elif self.path.startswith("/generate"):
                    self._generate()
                elif self.path.startswith("/reload"):
                    self._reload()
                else:
                    self._reply(404, {"error": f"no route {self.path}"})
            except FileNotFoundError as e:
                self._reply(404, {"error": str(e)})
            except (ValueError, KeyError, TypeError) as e:
                self._reply(400, {"error": str(e)})
            except Exception as e:  # engine-side failure
                self._reply(500, {"error": f"{type(e).__name__}: {e}"})

        def _predict(self):
            data = self._read_json()
            inputs = np.asarray(data["inputs"], np.float32)
            fut: Future = batcher.submit(inputs)
            out = fut.result(timeout=_RESULT_TIMEOUT_S)
            self._reply(200, {
                "outputs": np.asarray(out).tolist(),
                "classes": np.argmax(out, axis=-1).astype(int).tolist(),
            })

        def _reload(self):
            data = self._read_json()
            path = data.get("path")
            if not path:
                raise ValueError("reload needs {'path': <checkpoint>}")
            step = data.get("step")
            info = handle.load_checkpoint(
                str(path), step=None if step is None else int(step))
            self._reply(200, {
                "reloaded": True,
                "step": info.get("step"),
                "iterator_position": info.get("iterator_position"),
                "replicas": len(replicas.engines),
            })

        def _generate(self):
            if generate_engine is None:
                self._reply(404, {"error": "no generate engine configured"})
                return
            data = self._read_json()
            prompt = np.asarray(data["prompt"], np.int64)
            n_tokens = int(data.get("n_tokens", 16))
            out = generate_engine.generate(prompt, n_tokens)
            self._reply(200, {"tokens": out.astype(int).tolist()})

    handle.http = start_http_server(Handler, host=host, port=port)
    return handle
