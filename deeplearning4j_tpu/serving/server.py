"""Thin stdlib HTTP JSON front end over engines/replicas/batcher.

Same embedded-server pattern as plot/render_server.py (and the shared
lifecycle helper in utils/httpd.py): ThreadingHTTPServer on a daemon
thread, port-0 auto-assign, graceful close. Endpoints:

- ``POST /predict``  {"inputs": [[...], ...]} ->
  {"outputs": [[...]...], "classes": [...]} — rows go through the
  shared micro-batcher (coalescing concurrent clients) onto the
  round-robin replica set.
- ``POST /generate`` {"prompt": [[...tokens]], "max_tokens": N,
  "eos_id": E?, "stream": bool?} -> {"tokens": [[...]],
  "finish_reasons": [...]} — continuous-batching decode: each prompt
  row joins the slot scheduler (serving/decode_loop.py) and terminates
  independently on EOS or its own max_tokens ("n_tokens" is accepted as
  a legacy alias; the non-streaming response shape is unchanged).
  ``"stream": true`` switches the response to chunked transfer with one
  NDJSON line per emitted token ({"row": r, "token": t,
  "token_index": i} — `token_index` is the token's absolute per-row
  position, the fleet router's failover dedupe key) and a final
  {"done": true, ...} summary line — clients see tokens as slots emit
  them. `max_tokens` and `token_index_base` accept a per-row list
  (failover continuations). Requires a transformer engine; 404
  otherwise.
- ``POST /reload``   {"path": "<checkpoint dir or .ckpt>", "step": N?}
  — hot-swap every replica's weights from a checkpoint
  (docs/CHECKPOINTS.md) WITHOUT dropping in-flight requests: each
  engine validates shapes, stages the new params on its device, then
  swaps by a single reference assignment.
- ``GET /healthz``   liveness + replica count. Liveness ONLY — a
  process that is up but still compiling answers 200 here.
- ``GET /readyz``    readiness: 503 until the warmup precompile has
  finished (sync warmup is done before the socket opens; with
  ``warmup_async=True`` the socket opens immediately and this flips
  when the background warmup lands) and, when a decode loop runs, its
  scheduler thread is alive. The fleet router (serving/fleet.py) and
  any external LB gate admission on this, never on /healthz.
- ``GET /stats``     replica + batcher (queue depth, per-bucket forward
  counts) + uptime counters + last reload.
- ``GET /metrics``   Prometheus text exposition of the process-global
  telemetry registry (train/serve/guardian/device series —
  docs/OBSERVABILITY.md); ``GET /snapshot`` is the JSON twin.

Overload is machine-actionable end to end: a full batcher queue
(`max_queue=`) or a saturated decode admission queue (`max_waiting=`)
answers ``503`` with a ``Retry-After`` header and
``{"error": "overloaded", "retry_after_ms": N}`` — the shape the fleet
router's shedding also speaks (serving/errors.py, docs/FLEET.md).

This front end is deliberately minimal (stdlib only, JSON in/out, one
process): production fronting (TLS, auth, fleet-level routing/shedding)
belongs to the router tier (serving/fleet.py) or external infra; the
contract that matters here is that everything behind the socket is
already batched, bucketed, and compiled once per shape.
"""

from __future__ import annotations

import json
import threading
import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutureTimeoutError
from http.server import BaseHTTPRequestHandler
from typing import Optional

import numpy as np

from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.serving.engine import InferenceEngine
from deeplearning4j_tpu.serving.errors import (Deadline,
                                               DeadlineExceededError,
                                               OverloadedError,
                                               deadline_body,
                                               overload_body,
                                               parse_tier)
from deeplearning4j_tpu.serving.replicas import ReplicaSet
from deeplearning4j_tpu.telemetry import exposition
from deeplearning4j_tpu.testing import chaos
from deeplearning4j_tpu.utils.httpd import ServerHandle, start_http_server

__all__ = ["ServingHandle", "serve_network"]

_M_RELOADS = telemetry.counter(
    "dl4j_serve_reloads", "hot checkpoint reloads applied to the replicas")
_M_DEADLINE = telemetry.counter(
    "dl4j_serve_deadline_exceeded",
    "requests answered 504 because their deadline budget was spent")
_M_DISCONNECTS = telemetry.counter(
    "dl4j_serve_client_disconnects",
    "streaming clients that hung up mid-/generate (their slots were "
    "cancelled and their KV pages freed)")
_M_WARMUP_S = telemetry.gauge(
    "dl4j_compile_warmup_seconds",
    "wall seconds the serving warmup took (plan replay + bucket "
    "precompile) — the cold-vs-warm spin-up number docs/WARMUP.md "
    "tracks")

#: per-request wait on the batcher future — generous; the batcher bounds
#: queueing at max_delay_ms, so hitting this means the engine died.
#: Requests carrying a deadline derive their wait from the REMAINING
#: budget instead (docs/SERVING.md "Deadlines").
_RESULT_TIMEOUT_S = 120.0


class ServingHandle:
    """A running serving endpoint: http handle + batcher + replicas.

    Constructed (and handed to the request handler) BEFORE the socket
    opens — `http` is attached right after bind — so /stats is safe from
    the first accepted connection; stats() never touches `http`.
    """

    def __init__(self, replicas: ReplicaSet, batcher,
                 generate_engine: Optional[InferenceEngine],
                 http: Optional[ServerHandle] = None,
                 warmup_pending: bool = False,
                 role: str = "unified",
                 model_id: Optional[str] = None):
        self.http = http
        self.replicas = replicas
        self.batcher = batcher
        self.generate_engine = generate_engine
        # disaggregated-serving identity (docs/FLEET.md "Disaggregated
        # roles"): announced in /readyz so the fleet's role/model
        # registry reads placement identity off the probe that gates
        # admission — never from config drift
        self.role = role
        self.model_id = model_id
        self.started_at = time.time()
        self.last_reload: Optional[dict] = None
        # readiness state: pre-set unless an async warmup is in flight
        self._warmed = threading.Event()
        self.warmup_error: Optional[str] = None
        if not warmup_pending:
            self._warmed.set()
        # AOT warm-start state (docs/WARMUP.md): the plan loaded at
        # boot (None = cold), where to record this process's own
        # program set, and the post-warmup baselines that define
        # `recompiled_after_warmup`
        self.warmup_plan: Optional[dict] = None
        self.warmup_plan_path: Optional[str] = None
        self.warmup_seconds: Optional[float] = None
        self.plan_replay: Optional[dict] = None
        self._baseline_misses: Optional[int] = None
        self._baseline_programs: Optional[int] = None

    @property
    def url(self) -> str:
        return self.http.url

    @property
    def port(self) -> int:
        return self.http.port

    def close(self) -> None:
        """Stop accepting requests, flush the batcher, drain the decode
        loop, release the socket. Re-records the warmup plan on the way
        out — the plan now includes every program TRAFFIC compiled
        (escape buckets, prefill groups), so the next boot warms the
        real working set, not just what warmup touched."""
        self.http.close()
        if self.batcher is not None:
            self.batcher.close()
        if self.generate_engine is not None:
            self.generate_engine.close()  # drains the decode loop
        self.record_plan()

    # ----------------------------------------------- warmup plans
    def build_plan(self) -> dict:
        """The warmup plan describing this process's compiled program
        set (docs/WARMUP.md): one fragment per predict engine (matched
        at replay by cache_key — replicas pin different devices) plus
        the decode loop's."""
        plan: dict = {"engines": [], "decode": None}
        for eng in self.replicas.engines:
            # getattr: engine-shaped wrappers without a plan surface
            frag = getattr(eng, "plan_fragment", lambda: None)()
            if frag is not None:
                plan["engines"].append(frag)
        ge = self.generate_engine
        if ge is not None and ge.decode_loop is not None:
            plan["decode"] = ge.decode_loop.plan_fragment()
        return plan

    def record_plan(self) -> bool:
        """Write the current program set to `warmup_plan_path`
        (crash-atomic; no-op without a path)."""
        from deeplearning4j_tpu.compilecache import warmup as _warmup

        if self.warmup_plan_path is None:
            return False
        return _warmup.save_plan(self.warmup_plan_path,
                                 self.build_plan())

    def _program_total(self) -> int:
        """Every compiled-program counter this process exposes, summed
        — the cache-less definition of `recompiled_after_warmup`."""
        total = 0
        for eng in self.replicas.engines:
            total += max(0, eng.program_cache_size())
        ge = self.generate_engine
        if ge is not None:
            total += max(0, ge.program_cache_size())
            loop = ge.decode_loop
            if loop is not None:
                snap_keys = (loop.decode_step_programs(),
                             loop.prefill_programs())
                total += sum(max(0, n) for n in snap_keys)
        return total

    def recompiled_after_warmup(self) -> Optional[int]:
        """Programs compiled AFTER warmup finished: store misses since
        the post-warmup baseline when the persistent cache is active
        (a miss is exactly a compile), program-count growth otherwise.
        None until warmup has run. Zero on a warm boot is the whole
        point of the subsystem — bench.py warmup gates on it."""
        from deeplearning4j_tpu import compilecache

        comp = compilecache.active_compiler()
        if comp is not None and self._baseline_misses is not None:
            return int(comp.store.stats()["misses"]
                       - self._baseline_misses)
        if self._baseline_programs is not None:
            return max(0, self._program_total()
                       - self._baseline_programs)
        return None

    def __enter__(self) -> "ServingHandle":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ----------------------------------------------------- readiness
    def _run_warmup(self, feature_shape) -> None:
        """Warmup (sync before the socket opens, or on a background
        thread with `warmup_async=True` — /healthz answers, /readyz
        gates admission until this lands). With a loaded warmup plan,
        each engine/decode-loop replays its recorded fragment (AOT
        load from the persistent cache, no execution); engines without
        a matching fragment — and every engine on a cold boot — run
        the standard execute-every-bucket warmup. Afterwards the
        post-warmup baselines are pinned (recompiled_after_warmup
        counts from here) and the plan is recorded for the next
        boot."""
        from deeplearning4j_tpu import compilecache

        start = time.perf_counter()
        try:
            plan = self.warmup_plan
            frags = {f.get("cache_key"): f
                     for f in (plan or {}).get("engines", [])}
            replayed = {"engines": 0, "decode": 0}
            for eng in self.replicas.engines:
                frag = frags.get(getattr(eng, "cache_key", None))
                if frag is not None:
                    eng.warmup_from_plan(frag)
                    replayed["engines"] += 1
                elif feature_shape is not None:
                    eng.warmup(tuple(feature_shape))
            loop = (self.generate_engine.decode_loop
                    if self.generate_engine is not None else None)
            dfrag = (plan or {}).get("decode")
            if loop is not None and dfrag:
                replayed["decode"] = loop.warm_programs(dfrag)
            if plan is not None:
                self.plan_replay = replayed
        except Exception as e:  # surface via /readyz, don't die silent
            self.warmup_error = f"{type(e).__name__}: {e}"
        finally:
            self.warmup_seconds = time.perf_counter() - start
            _M_WARMUP_S.set(self.warmup_seconds)
            comp = compilecache.active_compiler()
            if comp is not None:
                self._baseline_misses = int(
                    comp.store.stats()["misses"])
            self._baseline_programs = self._program_total()
            self._warmed.set()
        self.record_plan()

    def readiness(self) -> dict:
        """Readiness probe payload: ready iff warmup precompile is done
        (and didn't fail) and, if a decode loop runs, its scheduler
        thread is alive. `/readyz` (and the fleet router) keys on
        this; liveness stays on /healthz."""
        reasons = []
        if not self._warmed.is_set():
            reasons.append("warmup in progress")
        elif self.warmup_error is not None:
            reasons.append(f"warmup failed: {self.warmup_error}")
        loop = (self.generate_engine.decode_loop
                if self.generate_engine is not None else None)
        if loop is not None and not loop.alive:
            reasons.append("decode loop not running")
        if self.batcher is not None and not self.batcher._worker.is_alive():
            reasons.append("batcher worker not running")
        out = {"ready": not reasons,
               "warmup_done": self._warmed.is_set(),
               "replicas": len(self.replicas.engines),
               # checkpoint identity ({path, step} or None): the fleet
               # journal and the deployment controller's convergence
               # check read WHAT this replica serves from the same
               # probe that gates admission (docs/PIPELINE.md)
               "checkpoint": self.replicas.checkpoint,
               # (role, model_id): the disaggregated fleet's placement
               # identity (docs/FLEET.md "Disaggregated roles")
               "role": self.role,
               "model_id": self.model_id}
        if loop is not None:
            out["decode_loop_alive"] = loop.alive
            # fleet KV plane: the affinity summary rides the SAME
            # probe that gates admission, so the router's placement
            # view refreshes exactly as fast as its health view. A
            # summary fault (chaos fleet.kv_summary) degrades this
            # replica to "no affinity signal" — it must never turn a
            # healthy replica unready
            try:
                summary = loop.kv_summary()
            except Exception:
                summary = None
            if summary is not None:
                out["kv_summary"] = summary
        if self.warmup_seconds is not None:
            out["warmup_seconds"] = round(self.warmup_seconds, 4)
        if reasons:
            out["reason"] = "; ".join(reasons)
        return out

    def stats(self) -> dict:
        from deeplearning4j_tpu import compilecache

        out = {"uptime_s": round(time.time() - self.started_at, 3),
               "checkpoint": self.replicas.checkpoint,
               "role": self.role,
               "model_id": self.model_id,
               "replicas": self.replicas.snapshot()}
        if self.batcher is not None:
            out["batcher"] = self.batcher.snapshot()
        if self.generate_engine is not None:
            out["generate"] = self.generate_engine.snapshot()
        if self.last_reload is not None:
            out["last_reload"] = self.last_reload
        if self.warmup_seconds is not None:
            out["warmup"] = {
                "seconds": round(self.warmup_seconds, 4),
                "plan_replayed": self.plan_replay,
                "recompiled_after_warmup":
                    self.recompiled_after_warmup(),
            }
        cache_stats = compilecache.stats()
        if cache_stats is not None:
            out["compile_cache"] = cache_stats
        return out

    def load_checkpoint(self, path: str, step: Optional[int] = None) -> dict:
        """Hot-swap replica weights from a checkpoint path (sharded dir
        or legacy npz) without dropping in-flight requests; records the
        reload in /stats. The HTTP `/reload` route calls this."""
        info = self.replicas.load_checkpoint(path, step=step)
        self.last_reload = {
            "path": path,
            "step": info.get("step"),
            "iterator_position": info.get("iterator_position"),
            "at": time.time(),
        }
        _M_RELOADS.inc()
        return info

    def load_draft_checkpoint(self, path: str,
                              step: Optional[int] = None) -> dict:
        """Hot-swap the speculative DRAFT model's weights from a
        checkpoint (sharded dir with a `params` payload) — the
        `/reload {"target": "draft"}` canary path. Serving weights and
        their checkpoint identity are untouched; a draft swap can only
        move acceptance rate, never output bits."""
        import os

        from deeplearning4j_tpu.checkpoint.restore import \
            load_payload_tree

        if self.generate_engine is None:
            raise ValueError("no generate engine configured")
        payload, manifest = load_payload_tree(path, step)
        params = (payload["params"]
                  if isinstance(payload, dict) and "params" in payload
                  else payload)
        info = {"path": os.path.abspath(path),
                "step": manifest.get("step", step)}
        self.generate_engine.load_draft_params(params, checkpoint=info)
        self.last_reload = {
            "path": path,
            "step": info["step"],
            "target": "draft",
            "at": time.time(),
        }
        _M_RELOADS.inc()
        return info


def serve_network(net=None, *, replicas: Optional[ReplicaSet] = None,
                  generate_engine: Optional[InferenceEngine] = None,
                  n_replicas: Optional[int] = None,
                  max_batch_size: int = 64, max_delay_ms: float = 2.0,
                  max_queue: Optional[int] = None,
                  slots: int = 8, page_size: int = 16,
                  kv_pages: Optional[int] = None,
                  max_waiting: Optional[int] = None,
                  prefix_cache: bool = True,
                  fleet_kv: str = "on",
                  kv_ship_timeout: float = 2.0,
                  decode_kernel: str = "auto",
                  horizon: int = 1,
                  speculation: int = 0,
                  drafter: str = "ngram",
                  draft_params=None, draft_cfg=None,
                  draft_window: int = 32,
                  batch_share: float = 0.5,
                  role: str = "unified",
                  model_id: Optional[str] = None,
                  host: str = "127.0.0.1", port: int = 0,
                  warmup_shape=None,
                  warmup_async: bool = False,
                  checkpoint: Optional[dict] = None,
                  compile_cache: Optional[str] = None,
                  warmup_plan: Optional[str] = "auto") -> ServingHandle:
    """Serve a MultiLayerNetwork (or a prebuilt ReplicaSet) over HTTP.

    Pass `net` for the common case — a replica set is built across
    local devices (capped by `n_replicas`) with `max_batch_size` as the
    top of each engine's bucket ladder — or pass `replicas=` directly
    for custom engines. `generate_engine` (an
    InferenceEngine.for_transformer) enables /generate; its requests
    ride the continuous-batching decode loop (`slots` concurrent
    streams over a paged KV pool of `kv_pages` pages of `page_size`
    tokens — docs/SERVING.md tuning notes). `warmup_shape` (one
    example's feature shape) precompiles every bucket before the socket
    opens; `warmup_async=True` opens the socket first and runs the
    warmup on a background thread, with `/readyz` answering 503 until
    it lands (how a fleet replica hides its spin-up cost behind the
    router, docs/FLEET.md). `max_queue` bounds the /predict coalescing
    queue and `max_waiting` the /generate admission queue — past
    either, requests shed with 503 + Retry-After. `prefix_cache=False`
    disables cross-request KV prefix sharing in the decode loop;
    individual requests opt out with `"prefix_cache": false` in the
    /generate body. `fleet_kv` tunes the fleet KV plane
    ("on"|"affinity-only"|"off", docs/FLEET.md "Fleet KV plane"): the
    affinity summary piggybacked on /readyz and the `POST /kv/export`
    peer page-shipping endpoint the router's donor hints point at.
    `decode_kernel` picks the decode attention lane
    ("auto" = Pallas paged kernel on TPU, dense gather elsewhere;
    docs/SERVING.md "Decode kernel"). `horizon > 1` chains K decode
    steps per dispatch; `speculation = k > 0` turns on draft-and-verify
    speculative decoding instead (`drafter` "ngram" or "model" with
    `draft_params`/`draft_cfg`; requests opt out with
    `"speculation": false` in the /generate body and the reload route
    accepts `{"target": "draft"}` to canary new draft weights —
    docs/SERVING.md "Speculative decoding"). `checkpoint` ({path, step})
    stamps the initial checkpoint identity on the replicas when the
    served model came from a checkpoint — /readyz, /stats, and the
    fleet journal report it (docs/PIPELINE.md). Requests carry an SLO
    tier (`X-Priority` header or `"priority"` body field, interactive
    default): batch-tier work rides the bulk lane — shed first at
    lower water marks, admitted behind interactive, preemptible —
    and `batch_share` tunes its weighted-fair slice of the decode
    slots (docs/SERVING.md "Priority tiers"). `role` declares this
    replica's place in a disaggregated fleet (docs/FLEET.md
    "Disaggregated roles"): "unified" (default) serves everything;
    "prefill" computes prompt KV for handoff (`POST /prefill` +
    /kv/export, /generate rejected); "decode" owns the streams. A
    prefill role requires `prefix_cache=True` and `fleet_kv="on"`.
    `model_id` names the served model for the router's multi-model
    registry; both ride the /readyz payload.

    AOT warm-start (docs/WARMUP.md): `compile_cache=DIR` activates the
    persistent program cache for this process (pass engines built
    AFTER activation — or activate via `compilecache.activate` /
    `DL4J_TPU_COMPILE_CACHE` before constructing them — so their jits
    are cache-wrapped). `warmup_plan` replays a recorded program set
    at warmup time: "auto" (default) looks for a plan co-located in
    the active cache dir and is a silent no-op when there is none or
    no cache is active; "off" disables replay; any other value is a
    plan file path. The handle re-records the plan after warmup and at
    close, so a replica's next boot warms exactly the program set this
    one actually used.
    """
    from deeplearning4j_tpu import compilecache
    from deeplearning4j_tpu.compilecache import warmup as _warmup_mod

    if compile_cache:
        compilecache.activate(compile_cache)
    if replicas is None:
        if net is None:
            raise ValueError("serve_network needs net= or replicas=")
        replicas = ReplicaSet.for_network(net, n_replicas=n_replicas,
                                          max_batch_size=max_batch_size)
    if checkpoint:
        # initial checkpoint identity (the model was constructed FROM a
        # checkpoint rather than reloaded onto a live server): stamp it
        # on every engine so /readyz reports it from the first probe
        for _e in replicas.engines:
            _e.checkpoint = dict(checkpoint)
    warm = tuple(warmup_shape) if warmup_shape is not None else None
    # slots=0 opts out of continuous batching: /generate falls back to
    # the per-request compiled-scan path (no streaming/EOS)
    if (generate_engine is not None and slots
            and generate_engine.decode_loop is None):
        generate_engine.start_decode_loop(slots=slots, page_size=page_size,
                                          n_pages=kv_pages,
                                          max_waiting=max_waiting,
                                          prefix_cache=prefix_cache,
                                          fleet_kv=fleet_kv,
                                          kv_ship_timeout=kv_ship_timeout,
                                          kernel=decode_kernel,
                                          horizon=horizon,
                                          speculation=speculation,
                                          drafter=drafter,
                                          draft_params=draft_params,
                                          draft_cfg=draft_cfg,
                                          draft_window=draft_window,
                                          batch_share=batch_share,
                                          role=role)
    batcher = replicas.batcher(max_batch_size=max_batch_size,
                               max_delay_ms=max_delay_ms,
                               max_queue=max_queue)
    # resolve the warmup plan (docs/WARMUP.md): "auto" keys the plan
    # off the first cache-identified engine, inside the active cache
    # dir — record and replay coordinate through the directory alone
    plan_path = plan_doc = None
    if warmup_plan and warmup_plan != "off":
        if warmup_plan == "auto":
            cache_dir = compilecache.active_dir()
            # getattr: callers may hand in engine-shaped wrappers
            # (test fixtures, gating shims) without a cache identity
            identity = next(
                (getattr(e, "cache_key", None)
                 for e in ([generate_engine] if generate_engine else [])
                 + list(replicas.engines)
                 if getattr(e, "cache_key", None) is not None), None)
            if cache_dir and identity:
                # role-scoped plans (docs/WARMUP.md): a prefill
                # replica's plan must never warm the decode ladder
                # (and vice versa), so the role is part of the key
                plan_path = _warmup_mod.auto_plan_path(cache_dir,
                                                       identity,
                                                       role=role)
        else:
            plan_path = warmup_plan
        if plan_path:
            plan_doc = _warmup_mod.load_plan(plan_path)
    run_warmup = (warm is not None or plan_doc is not None)
    handle = ServingHandle(replicas, batcher, generate_engine,
                           warmup_pending=(run_warmup and warmup_async),
                           role=role, model_id=model_id)
    handle.warmup_plan = plan_doc
    handle.warmup_plan_path = plan_path
    if run_warmup and not warmup_async:
        handle._run_warmup(warm)

    class Handler(BaseHTTPRequestHandler):
        # chunked transfer (the streaming /generate response) needs
        # HTTP/1.1; every non-streaming reply carries Content-Length so
        # keep-alive connections frame correctly
        protocol_version = "HTTP/1.1"

        def log_message(self, *args):  # quiet
            pass

        def _reply(self, code: int, payload: dict) -> None:
            self._reply_raw(code, "application/json",
                            json.dumps(payload).encode())

        def _reply_raw(self, code: int, ctype: str, body: bytes) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _read_json(self) -> dict:
            if self._body is None:
                raise ValueError("missing request body")
            data = json.loads(self._body)
            if not isinstance(data, dict):
                raise ValueError("request body must be a JSON object")
            return data

        # ------------------------------------------------------- routes
        def do_GET(self):
            try:
                if self.path.startswith("/healthz"):
                    self._reply(200, {"ok": True,
                                      "replicas": len(replicas.engines)})
                elif self.path.startswith("/readyz"):
                    ready = handle.readiness()
                    self._reply(200 if ready["ready"] else 503, ready)
                elif self.path.startswith("/stats"):
                    self._reply(200, handle.stats())
                elif (hit := exposition.handle_metrics_get(
                        self.path)) is not None:
                    self._reply_raw(*hit)
                else:
                    self._reply(404, {"error": f"no route {self.path}"})
            except Exception as e:  # always answer with a status line
                self._reply(500, {"error": f"{type(e).__name__}: {e}"})

        def _reset_connection(self) -> None:
            """Abort the client connection with an RST (SO_LINGER 0),
            not a clean FIN — the injected "reset" socket fault."""
            import socket as _socket
            import struct as _struct

            try:
                self.connection.setsockopt(
                    _socket.SOL_SOCKET, _socket.SO_LINGER,
                    _struct.pack("ii", 1, 0))
            except OSError:
                pass
            self.close_connection = True
            try:
                self.connection.close()
            except OSError:
                pass

        def do_POST(self):
            try:
                # accept-then-hang / pre-read faults: the request is
                # accepted but the handler goes dark before reading or
                # answering anything (chaos "hang"/"delay"/"reset")
                chaos.hit("server.accept", path=self.path)
            except chaos.ChaosReset:
                self._reset_connection()
                return
            # slurp the body up front, before ANY reply: under
            # HTTP/1.1 keep-alive an unread body would desync the
            # connection — the leftover bytes parse as the client's
            # next request line (404-before-read was exactly that bug)
            length = int(self.headers.get("Content-Length") or 0)
            self._body = self.rfile.read(length) if length > 0 else None
            try:
                # slow-loris-shaped handler stall: body read, reply
                # withheld (chaos "delay"; errors surface as 500s)
                chaos.hit("server.read", path=self.path)
                if self.path.startswith("/predict"):
                    self._predict()
                elif self.path.startswith("/generate"):
                    self._generate()
                elif self.path.startswith("/reload"):
                    self._reload()
                elif self.path.startswith("/kv/export"):
                    self._kv_export()
                elif self.path.startswith("/prefill"):
                    self._prefill()
                else:
                    self._reply(404, {"error": f"no route {self.path}"})
            except chaos.ChaosReset:
                self._reset_connection()
            except FileNotFoundError as e:
                self._reply(404, {"error": str(e)})
            except OverloadedError as e:
                # machine-actionable shedding: 503 + Retry-After +
                # JSON body, same shape as the fleet router's shed
                self.send_response(503)
                self.send_header("Retry-After", str(e.retry_after_s))
                body = json.dumps(overload_body(e)).encode()
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            except DeadlineExceededError as e:
                # the machine-readable twin for spent time budgets:
                # 504 + {"error": "deadline_exceeded", ...}
                _M_DEADLINE.inc()
                self._reply(504, deadline_body(e))
            except (ValueError, KeyError, TypeError) as e:
                self._reply(400, {"error": str(e)})
            except Exception as e:  # engine-side failure
                self._reply(500, {"error": f"{type(e).__name__}: {e}"})

        def _predict(self):
            data = self._read_json()
            deadline = Deadline.from_request(self.headers, data)
            # SLO tier: X-Priority header wins, else "priority" body
            # field (interactive default; unknown values 400)
            tier = parse_tier(self.headers, data)
            chaos.hit("server.predict")
            inputs = np.asarray(data["inputs"], np.float32)
            # batcher.submit sheds an already-expired budget before
            # enqueueing, and re-checks at dispatch; batch-tier
            # requests shed first at the lower water mark
            fut: Future = batcher.submit(inputs, deadline=deadline,
                                         tier=tier)
            wait_s = (_RESULT_TIMEOUT_S if deadline is None
                      else deadline.timeout(_RESULT_TIMEOUT_S))
            try:
                out = fut.result(timeout=wait_s)
            except (FutureTimeoutError, TimeoutError):
                # abandon the future: if it is still queued, the
                # batcher drops it at dispatch instead of computing
                # an answer nobody is waiting for. Only a genuinely
                # SPENT budget becomes a 504 — a wait that hit the
                # engine-death backstop with budget remaining is an
                # engine failure (500), not the client's fault
                fut.cancel()
                if deadline is not None and deadline.expired:
                    raise DeadlineExceededError(
                        "deadline exceeded waiting for the batcher",
                        deadline_ms=deadline.budget_ms,
                        elapsed_ms=deadline.elapsed_ms()) from None
                raise
            self._reply(200, {
                "outputs": np.asarray(out).tolist(),
                "classes": np.argmax(out, axis=-1).astype(int).tolist(),
            })

        def _reload(self):
            data = self._read_json()
            path = data.get("path")
            if not path:
                raise ValueError("reload needs {'path': <checkpoint>}")
            step = data.get("step")
            step = None if step is None else int(step)
            target = data.get("target", "serving")
            if target == "draft":
                # canary path for the speculative draft model: swap
                # ONLY the drafter's weights; serving weights and
                # checkpoint identity are untouched
                info = handle.load_draft_checkpoint(str(path), step=step)
                self._reply(200, {
                    "reloaded": True,
                    "target": "draft",
                    "step": info.get("step"),
                    "replicas": len(replicas.engines),
                    "checkpoint": replicas.checkpoint,
                })
                return
            if target != "serving":
                raise ValueError(
                    f"reload target must be 'serving' or 'draft', "
                    f"got {target!r}")
            info = handle.load_checkpoint(str(path), step=step)
            self._reply(200, {
                "reloaded": True,
                "step": info.get("step"),
                "iterator_position": info.get("iterator_position"),
                "replicas": len(replicas.engines),
                "checkpoint": replicas.checkpoint,
            })

        def _kv_export(self):
            """Donor side of a fleet KV page ship (serving/fleetkv.py):
            serialize this replica's cached prefix pages for the
            requested head tokens. 404 while the plane is off —
            receivers treat any non-200 as "no donor", fall back to
            plain prefill, and move on."""
            loop = (generate_engine.decode_loop
                    if generate_engine is not None else None)
            if loop is None:
                self._reply(404, {"error": "no decode loop"})
                return
            data = self._read_json()
            tokens = data.get("tokens")
            if not isinstance(tokens, list) or not tokens:
                raise ValueError(
                    "kv export needs {'tokens': [head token ids]}")
            max_chunks = data.get("max_chunks")
            max_chunks = None if max_chunks is None else int(max_chunks)
            payload = loop.kv_export([int(t) for t in tokens],
                                     max_chunks=max_chunks)
            if payload is None:
                self._reply(404, {"error": "fleet KV shipping is off "
                                           "on this replica"})
                return
            self._reply_raw(200, "application/octet-stream", payload)

        def _prefill(self):
            """Prefill leg of a disaggregated handoff (docs/FLEET.md
            "Disaggregated roles"): run the prompt's full-page prefill
            on THIS replica and park the pages in its prefix trie —
            the router then sets the decode replica's `kv_donor` hint
            to this replica's URL so admission pulls the pages over
            /kv/export. Any role can donate (a unified replica's trie
            works the same way); a prefill-role replica serves ONLY
            this and /kv/export. Per row: `chunks` full pages in the
            prompt, `covered` already cached here, `cached` newly
            adopted, `kv_bytes` the page payload the handoff makes
            shippable."""
            loop = (generate_engine.decode_loop
                    if generate_engine is not None else None)
            if loop is None:
                self._reply(404, {"error": "no decode loop"})
                return
            data = self._read_json()
            deadline = Deadline.from_request(self.headers, data)
            raw = data.get("prompt", data.get("tokens"))
            if not isinstance(raw, list) or not raw:
                raise ValueError("prefill needs {'prompt': [token "
                                 "ids]} (flat row or list of rows)")
            if not isinstance(raw[0], list):
                raw = [raw]
            if deadline is not None:
                deadline.check("prefill")  # 504 before compute
            timeout = (None if deadline is None
                       else max(0.05, deadline.remaining_s()))
            reports = []
            for row in raw:
                tokens = [int(t) for t in row]
                if not tokens:
                    raise ValueError("prefill rows must be non-empty")
                reports.append(loop.prefill_only(tokens,
                                                 timeout=timeout))
            self._reply(200, {
                "chunks": sum(r["chunks"] for r in reports),
                "covered": sum(r["covered"] for r in reports),
                "cached": sum(r["cached"] for r in reports),
                "kv_bytes": sum(r["kv_bytes"] for r in reports),
                "rows": reports,
            })

        def _generate(self):
            if generate_engine is None:
                self._reply(404, {"error": "no generate engine configured"})
                return
            data = self._read_json()
            deadline = Deadline.from_request(self.headers, data)
            # SLO tier: X-Priority header wins, else "priority" body
            # field (interactive default; unknown values 400). Batch
            # rides the weighted-fair bulk lane and may be PREEMPTED —
            # the stream then finishes with reason "preempted" and its
            # already-emitted tokens still relay (the fleet router
            # turns that into a lossless durable-stream resume)
            tier = parse_tier(self.headers, data)
            chaos.hit("server.generate")
            raw = data["prompt"]
            if not isinstance(raw, list) or not raw:
                raise ValueError("prompt must be a non-empty token list "
                                 "or list of token lists")
            if not isinstance(raw[0], list):
                raw = [raw]  # single flat row
            # rows may be RAGGED — each slot decodes independently, so
            # unlike /predict there is no rectangularity requirement
            prompt = [np.asarray(row, np.int64).ravel() for row in raw]
            if any(row.size < 1 for row in prompt):
                raise ValueError("prompt rows must be non-empty")
            # "max_tokens" is the contract; "n_tokens" stays as the
            # legacy alias so pre-continuous-batching clients keep
            # working unchanged. A list gives each row its OWN budget
            # (failover continuations re-admit rows interrupted at
            # different depths as one group — docs/FLEET.md)
            max_tokens = data.get("max_tokens", data.get("n_tokens", 16))
            max_tokens = ([int(m) for m in max_tokens]
                          if isinstance(max_tokens, list)
                          else int(max_tokens))
            # absolute-index offset for streamed `token_index` chunks:
            # a resumed request's replayed tokens ride in as prompt, so
            # its first NEW token is not index 0 (scalar or per-row)
            base = data.get("token_index_base", 0)
            base = ([int(b) for b in base] if isinstance(base, list)
                    else int(base))
            eos_id = data.get("eos_id")
            eos_id = None if eos_id is None else int(eos_id)
            streaming = bool(data.get("stream", False))
            # per-request opt-out: a secret-bearing prompt must neither
            # read from nor seed the shared prefix cache
            use_prefix = bool(data.get("prefix_cache", True))
            # per-request speculation opt-out (no-op on loops without
            # speculation; output is bit-identical either way)
            use_spec = bool(data.get("speculation", True))
            loop = generate_engine.decode_loop
            if loop is None:
                # legacy per-request compiled-scan path (no slot
                # scheduler): fixed n_tokens, no EOS, no streaming
                if eos_id is not None or streaming:
                    raise ValueError(
                        "eos_id/stream need the continuous-batching "
                        "decode loop (serve with slots >= 1)")
                if isinstance(max_tokens, list):
                    raise ValueError(
                        "per-row max_tokens needs the continuous-"
                        "batching decode loop (serve with slots >= 1)")
                if deadline is not None:
                    deadline.check("generate")  # 504 before compute
                out = generate_engine.generate(np.asarray(prompt),
                                               max_tokens)
                self._reply(200, {"tokens": out.astype(int).tolist()})
                return
            # fleet KV plane donor hint (serving/fleetkv.py): the
            # router knows a peer holds this prompt's prefix hot —
            # fetch + install those pages BEFORE admission so the
            # match below sees them as cached chunks. Budget-derived
            # timeout; ANY failure just means plain prefill. Gated on
            # the same prefix_cache opt-out as the cache itself.
            donor = data.get("kv_donor")
            if donor and use_prefix:
                try:
                    # disagg handoff, install leg: a chaos fault here
                    # models the wire tearing at the worst moment —
                    # the pull is skipped and the request falls
                    # through to plain prefill, bit-identically
                    # (docs/FLEET.md "Disaggregated roles")
                    chaos.hit("disagg.handoff", role="install",
                              donor=str(donor))
                    ship_timeout = loop.kv_ship_timeout
                    if deadline is not None:
                        ship_timeout = max(
                            0.05, min(ship_timeout,
                                      0.5 * deadline.remaining_s()))
                    shipped = set()
                    for row in prompt:
                        head = tuple(
                            row[:(row.size // loop.page_size)
                                * loop.page_size].tolist())
                        if head and head not in shipped:
                            shipped.add(head)
                            loop.kv_ship(str(donor), list(head),
                                         timeout=ship_timeout)
                except Exception:
                    pass  # ANY handoff failure degrades to prefill
            # all-or-nothing admission: a malformed row 400s and an
            # admission shed 503s WITHOUT orphaning row-mates' streams
            # in running slots (submit_many validates every row, then
            # enqueues the whole group under one lock); an expired
            # deadline 504s at submit, and again at slot admission
            streams = loop.submit_many(prompt, max_tokens, eos_id,
                                       deadline=deadline,
                                       prefix_cache=use_prefix,
                                       token_index_base=base,
                                       speculation=use_spec,
                                       tier=tier)
            if streaming:
                self._stream_tokens(streams, deadline)
                return
            wait_s = (_RESULT_TIMEOUT_S if deadline is None
                      else deadline.timeout(_RESULT_TIMEOUT_S))
            try:
                rows = [s.full_sequence(wait_s) for s in streams]
            except BaseException as e:
                # deadline/timeout/error on any row: retire the whole
                # group's slots so no abandoned stream burns pages
                for s in streams:
                    s.cancel()
                if (deadline is not None and deadline.expired
                        and isinstance(e, TimeoutError)):
                    # the wall wait and the loop's own reap race; the
                    # client-visible verdict is the same either way
                    raise DeadlineExceededError(
                        "deadline exceeded waiting for generation",
                        deadline_ms=deadline.budget_ms,
                        elapsed_ms=deadline.elapsed_ms()) from None
                raise
            self._reply(200, {
                "tokens": rows,
                "finish_reasons": [s.finish_reason for s in streams],
            })

        def _stream_tokens(self, streams, deadline=None):
            """Chunked NDJSON: one line per emitted token, as the slots
            emit them, then a final summary line. The client sees
            first-token latency, not last-token latency.

            Every abnormal exit CANCELS the request's streams — a
            disconnected (or reset, or timed-out) client must not leave
            slots decoding into the void: cancellation retires them and
            frees their KV pages within one scheduler dispatch."""
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

            def chunk(obj) -> None:
                chaos.hit("generate.midstream")
                body = (json.dumps(obj) + "\n").encode()
                self.wfile.write(f"{len(body):x}\r\n".encode()
                                 + body + b"\r\n")
                self.wfile.flush()

            try:
                self._relay_streams(streams, chunk, deadline)
            except chaos.ChaosReset:
                for s in streams:
                    s.cancel()
                self._reset_connection()
                return
            except DeadlineExceededError as e:
                # the decode loop's reap retired the slot on a spent
                # budget (the PRIMARY mid-stream enforcement): keep
                # the machine-readable wire shape in-band
                _M_DEADLINE.inc()
                for s in streams:
                    s.cancel()
                try:
                    chunk(deadline_body(e))
                except Exception:
                    self.close_connection = True
                    return
            except TimeoutError as e:
                # a stalled wait, NOT a disconnect (TimeoutError IS-A
                # OSError, so this arm must come first): the client is
                # still connected — cancel the slots and say why
                # in-band, with the machine-readable deadline shape
                # when a budget ran out
                for s in streams:
                    s.cancel()
                if deadline is not None and deadline.expired:
                    _M_DEADLINE.inc()
                    err = deadline_body(DeadlineExceededError(
                        "deadline exceeded mid-stream",
                        deadline_ms=deadline.budget_ms,
                        elapsed_ms=deadline.elapsed_ms()))
                else:
                    err = {"error": f"TimeoutError: {e}"}
                try:
                    chunk(err)
                except Exception:
                    self.close_connection = True
                    return
            except OSError:
                # the client hung up mid-stream: nothing left to tell
                # it — just stop burning its slots
                _M_DISCONNECTS.inc()
                for s in streams:
                    s.cancel()
                self.close_connection = True
                return
            except Exception as e:  # headers are gone — report in-band
                for s in streams:
                    s.cancel()
                try:
                    chunk({"error": f"{type(e).__name__}: {e}"})
                except Exception:
                    self.close_connection = True
                    return
            try:
                self.wfile.write(b"0\r\n\r\n")
            except OSError:
                pass
            self.close_connection = True

        def _relay_streams(self, streams, chunk, deadline=None) -> None:
            # the per-wait backstop: budget-carrying requests bound
            # every wait by their REMAINING budget (the decode loop's
            # reap is the primary enforcement; this covers a stalled
            # scheduler), budget-less ones keep the legacy constant
            def wait_s() -> float:
                return (_RESULT_TIMEOUT_S if deadline is None
                        else deadline.timeout(_RESULT_TIMEOUT_S))

            # every token line carries its ABSOLUTE per-row index
            # (token_index_base + emit ordinal): the fleet router's
            # failover dedupe key — exactly-once across replica hops
            # (clients that ignore it see the same stream as before)
            if len(streams) == 1:  # common case: emit inline
                for idx, tok in streams[0].indexed_tokens(
                        timeout=wait_s()):
                    chunk({"row": 0, "token": int(tok),
                           "token_index": int(idx)})
            else:  # merge rows as they emit, one relay thread per slot
                import queue as _queue
                import threading as _threading

                merged: "_queue.Queue" = _queue.Queue()

                def relay(r, s):
                    try:
                        for idx, tok in s.indexed_tokens(
                                timeout=wait_s()):
                            merged.put((r, int(idx), int(tok)))
                    except Exception:
                        pass  # surfaced via finish_reason below
                    finally:
                        merged.put((r, None, None))

                workers = [_threading.Thread(target=relay, args=(r, s),
                                             daemon=True)
                           for r, s in enumerate(streams)]
                for w in workers:
                    w.start()
                live = len(streams)
                while live:
                    r, idx, tok = merged.get()
                    if tok is None:
                        live -= 1
                    else:
                        chunk({"row": r, "token": tok,
                               "token_index": idx})
            chunk({"done": True,
                   "tokens": [s.prompt + s.result(wait_s())
                              if s.error is None else None
                              for s in streams],
                   "finish_reasons": [s.finish_reason for s in streams]})

    handle.http = start_http_server(Handler, host=host, port=port)
    if run_warmup and warmup_async:
        threading.Thread(target=handle._run_warmup, args=(warm,),
                         daemon=True, name="serve-warmup").start()
    return handle
