"""Fleet KV plane: prefix-affinity routing + peer-to-peer page shipping.

PR 11's prefix cache is per-replica: the router's least-outstanding
dispatch scatters a shared system prompt across all N replicas, so at
fleet scale the hit rate divides by N while every replica burns pages
caching the same prefix. This module is the host-side plumbing that
makes the fleet behave like ONE cache, in two independent halves:

1. **Prefix-affinity routing.** Each replica summarizes its trie as a
   compact set of fingerprints — one cumulative hash per page-aligned
   head-chunk path, the trie's own key unit (`PrefixIndex._chunks`) —
   piggybacked on the `/readyz` payload the fleet's health probe
   already fetches every heartbeat. The router hashes an incoming
   prompt's head chunks the same way and prefers the READY replica
   whose summary matches the longest run. Cold prompts (no match
   anywhere) fall back to a consistent-hash ring over the READY set,
   so repeats of a brand-new prefix keep landing on the same replica
   (the second request is the hit) and membership churn only remaps
   the keys the departed replica owned. Affinity is a PREFERENCE, not
   a mandate: shed pressure, SUSPECT state, and tier shedding all
   still win (`Fleet.select` honors the hint only inside a bounded
   load slack).

2. **Peer-to-peer page shipping.** When affinity cannot land the
   request on the replica that owns the prefix (slack exceeded,
   resume excludes it, replica mid-drain), the router names that
   replica as a DONOR hint instead. The chosen replica fetches the
   donor's hot pages over `POST /kv/export` — serialized with the
   checkpoint format's dtype-name/byte-view idiom (crc-framed raw
   array bytes, no pickle) — and installs them into its own pool +
   trie through the existing refcount machinery, so the subsequent
   admission sees a warm `paged_prefill_ctx` hit. Shipping is an
   optimization, never a correctness dependency: ANY failure (donor
   dead, timeout, crc mismatch, model identity mismatch, pool full)
   falls back to plain prefill of the same tokens.

Wire format (`pack_pages`/`unpack_pages`)::

    b"DL4JKV1\\n"
    <u32 header_len> <header json: page_size/chunks/layers/dtype/...>
    then chunk-major, layer-minor, K before V:
    <u32 frame_len> <u32 crc32> <raw array bytes>

The header carries the donor's decode `cache_key` — it pins model
config digest, page size, kernel lane and device, so a receiver can
reject bytes from a replica that reloaded onto a different checkpoint
shape mid-flight. Extension dtypes (bfloat16) round-trip exactly like
checkpoint shards: logical dtype name in the header, raw bytes viewed
back through `np.dtype` (ml_dtypes registers the names).

Everything here is host-side bookkeeping plus one eager per-page
scatter at install; the decode step programs never change.
"""

from __future__ import annotations

import json
import struct
import zlib
from typing import Dict, List, Optional, Sequence, Tuple
from urllib import error as _urlerror
from urllib import request as _urlrequest

import numpy as np

from deeplearning4j_tpu.checkpoint.format import (_dtype_name,
                                                  _resolve_dtype)

__all__ = [
    "MODE_ON", "MODE_AFFINITY", "MODE_OFF", "MODES",
    "ShipError", "hash_chunks", "HashRing", "pack_pages",
    "unpack_pages", "fetch_pages", "summary_heads", "match_summary",
    "RouterAffinity", "Placement",
]

#: full plane: affinity routing + donor hints + page shipping
MODE_ON = "on"
#: routing only — summaries and placement, no /kv/export traffic
MODE_AFFINITY = "affinity-only"
#: feature off: no summaries, no hashing, no shipping
MODE_OFF = "off"
MODES = (MODE_ON, MODE_AFFINITY, MODE_OFF)

#: per-path fingerprint depth: affinity only needs to discriminate the
#: HEAD of a prompt (system prompt + few-shot template); deeper chunks
#: add summary bytes without adding routing signal
MAX_HEAD_CHUNKS = 16
#: per-replica summary bound — most-recently-touched paths first, so
#: under pressure the summary degrades to "what is hot", never "what
#: happens to sort first"
MAX_SUMMARY_HASHES = 512
#: `Fleet.select` honors an affinity preference only while the target
#: is within this many outstanding requests of the least-loaded READY
#: replica — affinity must never stack a convoy on one box
PLACEMENT_SLACK = 4
#: consistent-hash ring virtual nodes per replica (higher = smoother
#: cold-placement spread, linearly more hashing on membership change)
RING_VNODES = 64

_MAGIC = b"DL4JKV1\n"
_FRAME = struct.Struct("<II")  # (byte length, crc32)
_U32 = struct.Struct("<I")


class ShipError(RuntimeError):
    """A page-shipping exchange failed (transport, framing, crc, or
    identity mismatch). Always recoverable: the receiver falls back to
    plain prefill of the exact same tokens."""


# --------------------------------------------------------------- hashing
def hash_chunks(tokens: Sequence[int], page_size: int,
                limit: Optional[int] = MAX_HEAD_CHUNKS) -> List[int]:
    """Cumulative fingerprint per FULL page-aligned head chunk of
    `tokens` — chunk j's hash covers chunks 0..j, so one value
    identifies a whole root-to-depth-j trie path. Mirrors
    `PrefixIndex._chunks` exactly (full chunks only, int token ids);
    a partial trailing page contributes nothing, same as the trie."""
    ps = int(page_size)
    n = len(tokens) // ps
    if limit is not None:
        n = min(n, int(limit))
    out: List[int] = []
    h = 0
    for j in range(n):
        chunk = tokens[j * ps:(j + 1) * ps]
        h = zlib.crc32(
            struct.pack(f"<{ps}q", *(int(t) for t in chunk)), h)
        out.append(h)
    return out


class HashRing:
    """Consistent-hash ring over replica ids: cold prompts with no
    summary match anywhere still get STABLE placement (the repeat
    request is the cache hit), and adding/removing a replica only
    remaps the keys it owned."""

    def __init__(self, ids: Sequence[str], vnodes: int = RING_VNODES):
        points: List[Tuple[int, str]] = []
        for rid in ids:
            for v in range(vnodes):
                points.append(
                    (zlib.crc32(f"{rid}#{v}".encode()), rid))
        points.sort()
        self._points = points

    def lookup(self, key: int) -> Optional[str]:
        """Owner of `key`: first ring point clockwise of the key."""
        points = self._points
        if not points:
            return None
        lo, hi = 0, len(points)
        while lo < hi:
            mid = (lo + hi) // 2
            if points[mid][0] < key:
                lo = mid + 1
            else:
                hi = mid
        return points[lo % len(points)][1]


# --------------------------------------------------------- wire format
def pack_pages(meta: dict, chunks: Sequence[Sequence[Tuple]]) -> bytes:
    """Serialize shipped pages: `chunks[j][l] = (k, v)` host arrays for
    chunk j, layer l. crc-framed raw bytes, no pickle — the checkpoint
    shard discipline (checkpoint/format.py) applied to KV pages."""
    dtype = None
    parts = [_MAGIC]
    frames: List[bytes] = []
    for chunk in chunks:
        for k, v in chunk:
            for arr in (k, v):
                a = np.ascontiguousarray(arr)
                if dtype is None:
                    dtype = _dtype_name(a.dtype)
                raw = a.tobytes()
                frames.append(
                    _FRAME.pack(len(raw), zlib.crc32(raw)) + raw)
    header = dict(meta)
    header["dtype"] = dtype
    head = json.dumps(header, sort_keys=True).encode()
    parts.append(_U32.pack(len(head)))
    parts.append(head)
    parts.extend(frames)
    return b"".join(parts)


def unpack_pages(payload: bytes) -> Tuple[dict, List[List[Tuple]]]:
    """Inverse of `pack_pages`: returns (header, chunks) with every
    frame crc-verified and every array rebuilt via the logical-dtype
    byte view. Raises ShipError on ANY framing defect — a truncated or
    corrupted ship must fall back, never install garbage K/V."""
    if not payload.startswith(_MAGIC):
        raise ShipError("kv ship payload: bad magic")
    off = len(_MAGIC)
    try:
        (hlen,) = _U32.unpack_from(payload, off)
        off += _U32.size
        header = json.loads(payload[off:off + hlen].decode())
        off += hlen
    except (struct.error, ValueError) as e:
        raise ShipError(f"kv ship payload: bad header ({e})") from None
    n_chunks = int(header.get("chunks", 0))
    n_layers = int(header.get("layers", 0))
    shape = tuple(header.get("shape", ()))
    if n_chunks == 0:
        return header, []
    if n_layers < 1 or len(shape) != 3:
        raise ShipError("kv ship payload: bad geometry header")
    try:
        dtype = _resolve_dtype(header["dtype"])
    except Exception as e:
        raise ShipError(
            f"kv ship payload: unknown dtype ({e})") from None
    expect = int(np.prod(shape)) * dtype.itemsize
    chunks: List[List[Tuple]] = []
    for _ in range(n_chunks):
        layers: List[Tuple] = []
        for _ in range(n_layers):
            pair = []
            for _ in range(2):  # K then V
                try:
                    ln, crc = _FRAME.unpack_from(payload, off)
                except struct.error:
                    raise ShipError(
                        "kv ship payload: truncated frame") from None
                off += _FRAME.size
                raw = payload[off:off + ln]
                off += ln
                if len(raw) != ln or ln != expect:
                    raise ShipError(
                        "kv ship payload: short frame")
                if zlib.crc32(raw) != crc:
                    raise ShipError(
                        "kv ship payload: frame failed its crc32 "
                        "check — refusing to install corrupt K/V")
                pair.append(np.frombuffer(raw, np.uint8)
                            .view(dtype).reshape(shape))
            layers.append((pair[0], pair[1]))
        chunks.append(layers)
    return header, chunks


def fetch_pages(donor_url: str, tokens: Sequence[int],
                timeout: float,
                max_chunks: Optional[int] = None) -> bytes:
    """POST the donor's `/kv/export` and return the raw framed payload.
    Transport failures of every flavor surface as ShipError — the
    caller's fallback path does not care which flavor."""
    body = {"tokens": [int(t) for t in tokens]}
    if max_chunks is not None:
        body["max_chunks"] = int(max_chunks)
    req = _urlrequest.Request(
        donor_url.rstrip("/") + "/kv/export",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST")
    try:
        with _urlrequest.urlopen(req, timeout=timeout) as resp:
            if resp.status != 200:
                raise ShipError(
                    f"donor replied {resp.status}")
            return resp.read()
    except ShipError:
        raise
    except (_urlerror.URLError, OSError, TimeoutError) as e:
        raise ShipError(f"kv export fetch failed: {e}") from None


# ------------------------------------------------------- summary/match
def summary_heads(index, page_size: int,
                  max_hashes: int = MAX_SUMMARY_HASHES,
                  max_chunks: int = MAX_HEAD_CHUNKS) -> List[int]:
    """Fingerprint a replica's trie for the /readyz summary: one
    cumulative hash per cached head-chunk path, most recently touched
    paths first, deduplicated, capped at `max_hashes`. Only tokens the
    trie RETAINS are hashed — requests that opted out of the prefix
    cache never seeded the trie, so their prompt bytes can never leak
    into a summary (the opt-out satellite's replica half)."""
    heads: List[int] = []
    seen = set()
    for seq in index.head_paths():
        for h in hash_chunks(seq, page_size, limit=max_chunks):
            if h not in seen:
                seen.add(h)
                heads.append(h)
        if len(heads) >= max_hashes:
            break
    return heads[:max_hashes]


def match_summary(summary: Optional[dict],
                  hashes: Sequence[int]) -> int:
    """Longest head-chunk run of `hashes` present in one replica's
    summary (0 = no overlap / no summary / page-size mismatch)."""
    if not summary or not hashes:
        return 0
    heads = summary.get("heads")
    if not heads:
        return 0
    head_set = heads if isinstance(heads, (set, frozenset)) \
        else frozenset(heads)
    depth = 0
    for j, h in enumerate(hashes):
        if h not in head_set:
            break
        depth = j + 1
    return depth


class Placement:
    """One routing decision: `prefer` is the replica id `Fleet.select`
    should lean toward; `donor`/`donor_url` name the replica whose
    pages are worth shipping when the request lands elsewhere; `depth`
    is the matched head-chunk run (0 = ring-placed cold prompt)."""

    __slots__ = ("prefer", "depth", "donor", "donor_url")

    def __init__(self, prefer: Optional[str], depth: int,
                 donor: Optional[str], donor_url: Optional[str]):
        self.prefer = prefer
        self.depth = depth
        self.donor = donor
        self.donor_url = donor_url


class RouterAffinity:
    """Router-side half of the plane: turns (prompt, fleet summaries)
    into a Placement. Stateless apart from a per-membership HashRing
    cache. Summary head-sets are frozen PER CALL, never cached by
    payload identity: each heartbeat probe parses a fresh summary
    dict and frees the old one, so CPython readily recycles the
    address — an `id()`-keyed cache would serve the PREVIOUS
    payload's head-set (typically the pre-warm empty one) and
    silently turn every deep match into a ring placement. Freezing
    <= MAX_SUMMARY_HASHES ints per candidate is noise next to the
    generate request being routed."""

    def __init__(self, mode: str = MODE_ON):
        if mode not in MODES:
            raise ValueError(
                f"fleet-kv mode must be one of {MODES}, got {mode!r}")
        self.mode = mode
        self._rings: Dict[Tuple[str, ...], HashRing] = {}

    @property
    def enabled(self) -> bool:
        return self.mode != MODE_OFF

    @property
    def shipping(self) -> bool:
        return self.mode == MODE_ON

    def plan(self, prompt: Sequence[int],
             summaries: Dict[str, Tuple[dict, str]]
             ) -> Optional[Placement]:
        """Place one prompt. `summaries` maps READY replica id ->
        (kv_summary payload, replica url). Returns None when affinity
        has nothing to say (mode off, no candidates, or the prompt is
        shorter than one page — sub-page prompts have no trie key, so
        hashing them would be pure noise). The CALLER gates on the
        request's prefix_cache opt-out: an opted-out prompt must never
        reach this method (its hashes must not leave the router's
        request handler — the opt-out satellite's router half)."""
        if self.mode == MODE_OFF or not summaries:
            return None
        # role filter (docs/FLEET.md "Disaggregated roles"): a
        # prefill-role replica never runs a generate stream, so it
        # must never become a prefer target, a donor hint, or a ring
        # owner here — its pages reach the decode side through the
        # explicit /prefill handoff, not through affinity placement
        summaries = {rid: sv for rid, sv in summaries.items()
                     if ((sv[0] or {}).get("role") or "unified")
                     != "prefill"}
        if not summaries:
            return None
        page_sizes = {int((s or {}).get("page_size", 0))
                      for s, _url in summaries.values()}
        page_sizes.discard(0)
        if len(page_sizes) != 1:
            return None  # mid-rollout heterogeneity: sit out
        ps = page_sizes.pop()
        hashes = hash_chunks(prompt, ps)
        if not hashes:
            return None
        best_id, best_depth = None, 0
        for rid in sorted(summaries):
            summary, _url = summaries[rid]
            depth = match_summary(
                {"heads": frozenset((summary or {}).get("heads")
                                    or ())}, hashes)
            if depth > best_depth:
                best_id, best_depth = rid, depth
        if best_id is not None:
            return Placement(best_id, best_depth, best_id,
                             summaries[best_id][1])
        ids = tuple(sorted(summaries))
        ring = self._rings.get(ids)
        if ring is None:
            ring = self._rings[ids] = HashRing(ids)
            if len(self._rings) > 64:  # membership churn bound
                self._rings = {ids: ring}
        return Placement(ring.lookup(hashes[0]), 0, None, None)
