"""InferenceEngine: compile-once-per-bucket forward for serving.

The training side already learned this lesson (datasets/device_feed.py):
a jitted program re-specializes per input shape, so ragged traffic must
be padded onto a small bucket ladder. An engine owns ONE jitted apply
function and the bucket ladder for its model; every request pads up to
the smallest bucket that holds it and slices the padding back off the
result. Since the forward is per-row independent (no cross-example
reductions at inference), padded rows never touch real outputs — no
mask needed, unlike the training loss.

The request input buffer is donated to the jitted call (it is freshly
device_put per request, so XLA reuses its HBM for the activations);
params are NOT donated — they serve every request.

Observability is first-class (`EngineStats`): requests, rows, batch
occupancy, p50/p99 wall latency (each timed window ends with the D2H
read of the result — the honest protocol from BASELINE.md), and the
program-cache counter that pins "ragged stream compiles <= one program
per bucket" in tests and bench.
"""

from __future__ import annotations

import itertools
import time
from typing import Callable, Optional, Sequence

import numpy as np

from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.datasets.device_feed import (DEFAULT_MIN_BUCKET,
                                                     bucket_for,
                                                     pow2_buckets)
from deeplearning4j_tpu.telemetry.trace import span
from deeplearning4j_tpu.utils.jitcache import jit_cache_size

__all__ = ["EngineStats", "InferenceEngine"]

_engine_seq = itertools.count()


class EngineStats:
    """Per-engine serving stats as a VIEW over the telemetry registry.

    Historically this class kept its own lock-and-dict counters in
    parallel with everything else's; now each engine owns a labeled set
    of registry series (`dl4j_serve_*{engine=...}`) and this object is
    just the typed accessor — the same numbers appear in `/metrics`, in
    `/stats`, and here, with no second code path. Latency percentiles
    come from the histogram's bounded reservoir; each timed window ends
    with the D2H read of the result (the honest protocol from
    BASELINE.md). Note `telemetry.set_enabled(False)` blanks recording
    here too — the registry IS the storage.
    """

    def __init__(self, window: int = 2048, label: Optional[str] = None,
                 registry=None):
        reg = registry if registry is not None else telemetry.get_registry()
        self.label = label if label is not None else f"e{next(_engine_seq)}"
        lab = {"engine": self.label}
        self._requests = reg.counter(
            "dl4j_serve_requests", "inference requests served").labels(**lab)
        self._rows = reg.counter(
            "dl4j_serve_rows", "real request rows served").labels(**lab)
        self._padded = reg.counter(
            "dl4j_serve_padded_rows",
            "bucket-padding rows shipped alongside real rows").labels(**lab)
        self._errors = reg.counter(
            "dl4j_serve_errors", "failed inference requests").labels(**lab)
        self._latency = reg.histogram(
            "dl4j_serve_latency_seconds",
            "per-request wall latency incl. the result D2H read",
            window=window).labels(**lab)
        self._bucket_fam = reg.counter(
            "dl4j_serve_bucket_forwards",
            "compiled-bucket forwards by bucket size")
        # memoized per-bucket children: labels() takes the family lock
        # shared across ALL engines — not a per-request cost
        self._bucket_children: dict = {}

    # typed accessors (the historical attribute surface)
    @property
    def requests(self) -> int:
        return int(self._requests.value)

    @property
    def rows(self) -> int:
        return int(self._rows.value)

    @property
    def padded_rows(self) -> int:
        return int(self._padded.value)

    @property
    def errors(self) -> int:
        return int(self._errors.value)

    def record(self, rows: int, bucket: int, seconds: float) -> None:
        self._requests.inc()
        self._rows.inc(rows)
        self._padded.inc(bucket - rows)
        self._latency.observe(seconds)
        child = self._bucket_children.get(bucket)
        if child is None:  # benign race: labels() is get-or-create
            child = self._bucket_fam.labels(engine=self.label,
                                            bucket=str(bucket))
            self._bucket_children[bucket] = child
        child.inc()

    def record_error(self) -> None:
        self._errors.inc()

    def bucket_forwards(self) -> dict:
        """{bucket_size: forward_count} for this engine."""
        out = {}
        for labels, child in self._bucket_fam.children():
            if labels.get("engine") == self.label:
                out[int(labels["bucket"])] = int(child.value)
        return out

    def snapshot(self) -> dict:
        rows, padded = self.rows, self.padded_rows
        shipped = rows + padded
        return {
            "requests": self.requests,
            "rows": rows,
            "padded_rows": padded,
            "errors": self.errors,
            # fraction of shipped rows that were real work
            "occupancy": (rows / shipped) if shipped else 0.0,
            "latency_p50_ms": round(self._latency.percentile(0.50) * 1e3, 3),
            "latency_p99_ms": round(self._latency.percentile(0.99) * 1e3, 3),
            "bucket_forwards": self.bucket_forwards(),
        }


class InferenceEngine:
    """A jitted, bucket-padded forward for one model on one device.

    `apply_fn(params, x)` must be a pure per-row forward; `x`'s leading
    dim is the batch. Construct via the classmethods for the stock
    model families, or directly for anything functional.
    """

    def __init__(self, apply_fn: Callable, params, *,
                 max_batch_size: int = 64,
                 buckets: Optional[Sequence[int]] = None,
                 min_bucket: int = DEFAULT_MIN_BUCKET,
                 device=None,
                 generate_fn: Optional[Callable] = None,
                 cache_key: Optional[str] = None):
        import jax

        from deeplearning4j_tpu import compilecache

        if buckets is None:
            buckets = pow2_buckets(max_batch_size, min_bucket=min_bucket)
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError(f"buckets must be positive, got {buckets}")
        self.max_batch_size = int(max_batch_size)
        self.device = device
        self._params = (jax.device_put(params, device)
                        if device is not None else params)
        # donate the request buffer (engine-owned: infer stages through
        # host + device_put, never the caller's array) so its HBM is
        # reused for activations; CPU ignores donation with a warning,
        # so gate it off there
        donate = () if jax.default_backend() == "cpu" else (1,)
        #: model identity for the persistent compile cache
        #: (docs/WARMUP.md); the full program key also pins the device,
        #: because serialized executables are device-bound — replica 3
        #: on cpu:3 must not load replica 0's programs
        dev = device if device is not None else jax.devices()[0]
        self.cache_key = (f"{cache_key}|dev={dev}"
                          if cache_key is not None else None)
        self._jit = compilecache.maybe_wrap(
            jax.jit(apply_fn, donate_argnums=donate), self.cache_key)
        self._generate_fn = generate_fn
        #: continuous-batching slot scheduler (transformer engines;
        #: start_decode_loop) — None until started
        self.decode_loop = None
        self._tf_cfg = None
        #: True once warmup() precompiled every bucket — the readiness
        #: surface (/readyz, docs/FLEET.md) reads it
        self.warmed_up = False
        #: wall seconds the last warmup()/warmup_from_plan() took (the
        #: cold-vs-warm spin-up number /stats and bench.py warmup pin)
        self.warmup_seconds: Optional[float] = None
        #: feature shape + dtype the engine warms with, captured from
        #: warmup() or the first infer() — what plan_fragment() records
        self._warm_shape: Optional[tuple] = None
        #: checkpoint identity this engine serves ({path, step} or None
        #: for constructor-installed params) — recorded by load_params,
        #: surfaced through /readyz and /stats so the deployment
        #: controller can verify a promotion landed (docs/PIPELINE.md)
        self.checkpoint: Optional[dict] = None
        #: identity of the speculative draft model's checkpoint, when
        #: one was hot-loaded via load_draft_params (None otherwise)
        self.draft_checkpoint: Optional[dict] = None
        self.stats = EngineStats()
        from deeplearning4j_tpu.telemetry import device as _tdev
        _tdev.watch_jit_cache("serving_engine", self.program_cache_size)

    # ----------------------------------------------------- constructors
    @classmethod
    def for_network(cls, net, **kw) -> "InferenceEngine":
        """Wrap a MultiLayerNetwork: apply = output-layer activations
        (the bucketed twin of `net.output`)."""
        from deeplearning4j_tpu.compilecache import config_digest

        kw.setdefault("cache_key",
                      "serve.net:" + config_digest(net.to_json()))
        return cls(lambda p, x: net.feed_forward_fn(p, x)[-1],
                   net.param_table, **kw)

    @classmethod
    def for_transformer(cls, params, cfg, *, decode_slots: int = 0,
                        page_size: int = 16,
                        kv_pages: Optional[int] = None,
                        max_waiting: Optional[int] = None,
                        prefix_cache: bool = True,
                        decode_kernel: str = "auto",
                        horizon: int = 1,
                        speculation: int = 0,
                        drafter: str = "ngram",
                        draft_params=None, draft_cfg=None,
                        draft_window: int = 32,
                        batch_share: float = 0.5,
                        batch_max_waiting: Optional[int] = None,
                        **kw) -> "InferenceEngine":
        """Wrap a transformer LM: apply = full logits (B, T, vocab);
        `generate()` runs the per-request KV-cached compiled scan.
        `decode_slots > 0` additionally starts the continuous-batching
        `DecodeLoop` (paged KV pool, `generate_stream()`); pass
        `page_size`/`kv_pages` to size the pool, `max_waiting` to
        bound its admission queue, `prefix_cache=False` to disable
        cross-request KV prefix sharing, and `decode_kernel` to pick
        the decode attention lane ("auto" = the Pallas paged kernel on
        TPU, dense gather elsewhere — docs/SERVING.md). `horizon > 1`
        chains K decode steps per dispatch; `speculation = k > 0`
        instead turns on draft-and-verify speculative decoding with
        the chosen `drafter` flavor ("ngram", or "model" with
        `draft_params`/`draft_cfg` — docs/SERVING.md "Speculative
        decoding")."""
        from deeplearning4j_tpu.compilecache import config_digest
        from deeplearning4j_tpu.models.transformer import transformer_logits
        from deeplearning4j_tpu.serving.kv_cache import generate_cached

        kw.setdefault("cache_key", "serve.tf:" + config_digest(cfg))
        eng = cls(lambda p, tok: transformer_logits(p, tok, cfg), params,
                  generate_fn=lambda p, prompt, n: generate_cached(
                      p, prompt, cfg, n),
                  **kw)
        eng._tf_cfg = cfg
        if decode_slots:
            eng.start_decode_loop(slots=decode_slots, page_size=page_size,
                                  n_pages=kv_pages,
                                  max_waiting=max_waiting,
                                  prefix_cache=prefix_cache,
                                  kernel=decode_kernel,
                                  horizon=horizon,
                                  speculation=speculation,
                                  drafter=drafter,
                                  draft_params=draft_params,
                                  draft_cfg=draft_cfg,
                                  draft_window=draft_window,
                                  batch_share=batch_share,
                                  batch_max_waiting=batch_max_waiting)
        return eng

    @classmethod
    def for_lstm(cls, layer, params, **kw) -> "InferenceEngine":
        """Wrap an LSTM layer: apply = per-timestep decoded outputs over
        (B, T, n_in) input."""
        return cls(lambda p, x: layer.activate(p, x), params, **kw)

    # ------------------------------------------------------------ serve
    def infer(self, x) -> np.ndarray:
        """One request: (n, ...) -> np.ndarray of the first n output
        rows. Pads n up to the bucket ladder (requests beyond the top
        bucket take the pow2 escape ladder, still bounding program
        count), runs the compiled forward, slices the padding off. The
        returned array is host-resident — the D2H read is inside the
        latency window.

        Input is staged through the host (np.asarray + device_put), so
        the device buffer handed to the donated jit arg is always
        engine-owned — a caller's device array is never invalidated."""
        import jax

        x = np.asarray(x)
        if x.ndim < 2:
            raise ValueError(
                f"infer expects a (n, ...) batch, got shape {x.shape}")
        n = int(x.shape[0])
        if n == 0:
            raise ValueError("empty request")
        if self._warm_shape is None:
            self._warm_shape = (tuple(int(d) for d in x.shape[1:]),
                                x.dtype.str)
        start = time.perf_counter()
        try:
            with span("engine_infer", rows=n):
                b = bucket_for(n, self.buckets)
                if b != n:  # pad on host — the H2D copy ships once
                    x = np.concatenate(
                        [x, np.zeros((b - n, *x.shape[1:]), x.dtype)])
                xb = jax.device_put(x, self.device)
                out = np.asarray(self._jit(self._params, xb)[:n])
        except Exception:
            self.stats.record_error()
            raise
        self.stats.record(n, b, time.perf_counter() - start)
        return out

    def generate(self, prompt, n_tokens: int) -> np.ndarray:
        """KV-cached greedy decode (transformer engines only):
        prompt (B, T0) int tokens -> (B, T0 + n_tokens)."""
        import jax.numpy as jnp

        if self._generate_fn is None:
            raise ValueError(
                "this engine has no generate path (construct it with "
                "InferenceEngine.for_transformer)")
        prompt = jnp.asarray(prompt, jnp.int32)
        if prompt.ndim != 2:
            raise ValueError(
                f"prompt must be (B, T0) tokens, got shape {prompt.shape}")
        start = time.perf_counter()
        try:
            out = np.asarray(
                self._generate_fn(self._params, prompt, int(n_tokens)))
        except Exception:
            self.stats.record_error()
            raise
        self.stats.record(int(prompt.shape[0]), int(prompt.shape[0]),
                          time.perf_counter() - start)
        return out

    # ------------------------------------------- continuous batching
    def start_decode_loop(self, slots: int = 8, page_size: int = 16,
                          n_pages: Optional[int] = None,
                          horizon: int = 1,
                          max_waiting: Optional[int] = None,
                          prefix_cache: bool = True,
                          fleet_kv: str = "on",
                          kv_ship_timeout: float = 2.0,
                          kernel: str = "auto",
                          speculation: int = 0,
                          drafter: str = "ngram",
                          draft_params=None, draft_cfg=None,
                          draft_window: int = 32,
                          batch_share: float = 0.5,
                          batch_max_waiting: Optional[int] = None,
                          role: str = "unified"):
        """Start the continuous-batching slot scheduler
        (serving/decode_loop.py) for this transformer engine: S slots
        over a paged KV pool riding ONE compiled decode step. `/generate`
        traffic routes here instead of the per-request compiled-scan
        path — requests join/leave at token boundaries and KV memory
        scales with written tokens. `kernel` picks the decode attention
        lane ("auto"|"pallas"|"gather", docs/SERVING.md);
        `speculation = k` turns on draft-and-verify with the chosen
        `drafter` ("ngram"|"model"). `batch_share`/`batch_max_waiting`
        tune the batch SLO tier's weighted-fair slot share and its
        (lower) admission-queue bound (docs/SERVING.md "Priority
        tiers")."""
        from deeplearning4j_tpu.serving.decode_loop import DecodeLoop

        if self._tf_cfg is None:
            raise ValueError(
                "decode loop needs a transformer engine (construct it "
                "with InferenceEngine.for_transformer)")
        if self.decode_loop is not None:
            raise RuntimeError("decode loop already started")
        self.decode_loop = DecodeLoop(self._params, self._tf_cfg,
                                      slots=slots, page_size=page_size,
                                      n_pages=n_pages, horizon=horizon,
                                      max_waiting=max_waiting,
                                      prefix_cache=prefix_cache,
                                      fleet_kv=fleet_kv,
                                      kv_ship_timeout=kv_ship_timeout,
                                      kernel=kernel,
                                      speculation=speculation,
                                      drafter=drafter,
                                      draft_params=draft_params,
                                      draft_cfg=draft_cfg,
                                      draft_window=draft_window,
                                      batch_share=batch_share,
                                      batch_max_waiting=batch_max_waiting,
                                      role=role)
        return self.decode_loop

    def generate_stream(self, prompt, max_tokens: int,
                        eos_id: Optional[int] = None,
                        speculation: bool = True):
        """Submit one prompt (1-D token sequence) to the slot scheduler;
        returns a `GenerationStream` emitting tokens as they come off
        the chip, terminated by EOS or `max_tokens`. Requires
        `start_decode_loop` (or `decode_slots=` at construction).
        `speculation=False` opts this request out of speculative
        drafting (output is bit-identical either way)."""
        if self.decode_loop is None:
            raise ValueError(
                "this engine has no decode loop (pass decode_slots= to "
                "for_transformer or call start_decode_loop)")
        return self.decode_loop.submit(prompt, max_tokens, eos_id,
                                       speculation=speculation)

    def close(self) -> None:
        """Drain and stop the decode loop (no-op without one)."""
        if self.decode_loop is not None:
            self.decode_loop.close()

    # ------------------------------------------------------- hot reload
    def load_params(self, params, *,
                    checkpoint: Optional[dict] = None) -> None:
        """Swap this engine's weights in place — zero-downtime reload.

        Validates the new tree leaf-for-leaf (structure + shapes, error
        naming the first mismatched leaf) and device_puts it onto the
        engine's device BEFORE the swap, so the visible transition is a
        single reference assignment: requests in flight keep the old
        params they already closed over, later requests see the new ones
        — nothing is dropped and no lock sits on the request path. The
        compiled bucket programs are reused as-is (params are a traced
        argument, so same shapes = same program).

        `checkpoint` records the identity of what was just installed
        ({path, step}); it becomes visible only after the swap, so a
        reader never sees a new identity paired with old weights."""
        import jax

        from deeplearning4j_tpu.checkpoint.restore import validate_like

        validate_like(params, self._params, context="engine reload")
        if self.device is not None:
            params = jax.device_put(params, self.device)
        else:
            import jax.numpy as jnp

            params = jax.tree_util.tree_map(jnp.asarray, params)
        self._params = params  # atomic swap
        if self.decode_loop is not None:
            # same single-reference swap: in-flight decode steps keep
            # the params they closed over, the next step sees new ones
            self.decode_loop.params = params
        self.checkpoint = dict(checkpoint) if checkpoint else None

    def load_draft_params(self, params, *,
                          checkpoint: Optional[dict] = None) -> None:
        """Swap the speculative DRAFT model's weights in place — the
        `/reload {"target": "draft"}` path the deployment pipeline uses
        to canary a new draft model without touching serving weights.
        Requires a decode loop running a model drafter. Same contract
        as `load_params`: leaf-for-leaf validation against the current
        draft tree, then one reference assignment. A bad draft model
        can only cost acceptance rate, never correctness — the target
        verify step still decides every emitted token."""
        from deeplearning4j_tpu.checkpoint.restore import validate_like

        drafter = (None if self.decode_loop is None
                   else self.decode_loop._drafter)
        if drafter is None or drafter.kind != "model":
            raise ValueError(
                "no draft model to reload: the decode loop must be "
                "running with speculation > 0 and drafter='model'")
        validate_like(params, drafter.params, context="draft reload")
        drafter.load_params(params)
        self.draft_checkpoint = dict(checkpoint) if checkpoint else None

    # ---------------------------------------------------- observability
    def warmup(self, feature_shape: Sequence[int],
               dtype=np.float32) -> None:
        """Compile every bucket program up front so the first real
        requests don't pay compile latency. `feature_shape` is one
        example's shape (without the batch dim). Bypasses EngineStats —
        warmup compiles must not pollute the serving p50/p99/occupancy
        the bench and /stats report.

        With a persistent compile cache active, each bucket's program is
        loaded from disk instead of compiled when a prior run left it
        there (the execute below then just runs the loaded program on
        zeros)."""
        import jax

        start = time.perf_counter()
        for b in self.buckets:
            xb = jax.device_put(np.zeros((b, *feature_shape), dtype),
                                self.device)
            np.asarray(self._jit(self._params, xb))
        self.warmup_seconds = time.perf_counter() - start
        self._warm_shape = (tuple(int(d) for d in feature_shape),
                            np.dtype(dtype).str)
        self.warmed_up = True

    # ------------------------------------------------- warmup plans
    def plan_fragment(self) -> Optional[dict]:
        """The "engine" fragment of a warmup plan (docs/WARMUP.md):
        the buckets this engine compiled — ladder plus any pow2 escape
        buckets traffic actually forwarded — and the feature shape to
        build them with. None until a shape is known (no warmup and no
        traffic yet) or when the engine has no cache identity."""
        if self.cache_key is None or self._warm_shape is None:
            return None
        shape, dtype = self._warm_shape
        buckets = set(self.buckets) | set(self.stats.bucket_forwards())
        return {"cache_key": self.cache_key,
                "buckets": sorted(int(b) for b in buckets),
                "feature_shape": list(shape),
                "dtype": dtype}

    def warmup_from_plan(self, frag: dict) -> None:
        """Replay a recorded plan fragment: AOT load-or-compile every
        bucket program listed, WITHOUT executing anything (pure
        `lower().compile()` / deserialize via the persistent cache).
        Falls back to the standard execute-zeros warmup when the engine
        is not cache-wrapped or the fragment was recorded for a
        different model identity."""
        import jax

        shape = tuple(int(d) for d in frag.get("feature_shape", ()))
        dtype = np.dtype(frag.get("dtype", "float32"))
        if (frag.get("cache_key") != self.cache_key
                or not hasattr(self._jit, "warm")):
            self.warmup(shape, dtype)
            return
        start = time.perf_counter()
        sds = lambda a: jax.ShapeDtypeStruct(  # noqa: E731
            a.shape, a.dtype)
        params_spec = jax.tree_util.tree_map(sds, self._params)
        for b in frag.get("buckets", self.buckets):
            self._jit.warm(params_spec,
                           jax.ShapeDtypeStruct((int(b), *shape), dtype))
        self.warmup_seconds = time.perf_counter() - start
        self._warm_shape = (shape, dtype.str)
        self.warmed_up = True

    def program_cache_size(self) -> int:
        """Compiled-program count for the jitted forward — the serving
        twin of MultiLayerNetwork.train_step_cache_size(). With bucket
        padding this stays <= len(buckets) hit (+ escape buckets);
        -1 when the private jax counter API drifted."""
        return jit_cache_size(self._jit)

    def snapshot(self) -> dict:
        snap = self.stats.snapshot()
        snap["buckets"] = list(self.buckets)
        snap["compiled_programs"] = self.program_cache_size()
        if self.warmup_seconds is not None:
            snap["warmup_seconds"] = round(self.warmup_seconds, 4)
        snap["checkpoint"] = self.checkpoint
        if self.draft_checkpoint is not None:
            snap["draft_checkpoint"] = self.draft_checkpoint
        if self.device is not None:
            snap["device"] = str(self.device)
        if self.decode_loop is not None:
            snap["decode"] = self.decode_loop.snapshot()
        return snap
