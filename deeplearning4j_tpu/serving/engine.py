"""InferenceEngine: compile-once-per-bucket forward for serving.

The training side already learned this lesson (datasets/device_feed.py):
a jitted program re-specializes per input shape, so ragged traffic must
be padded onto a small bucket ladder. An engine owns ONE jitted apply
function and the bucket ladder for its model; every request pads up to
the smallest bucket that holds it and slices the padding back off the
result. Since the forward is per-row independent (no cross-example
reductions at inference), padded rows never touch real outputs — no
mask needed, unlike the training loss.

The request input buffer is donated to the jitted call (it is freshly
device_put per request, so XLA reuses its HBM for the activations);
params are NOT donated — they serve every request.

Observability is first-class (`EngineStats`): requests, rows, batch
occupancy, p50/p99 wall latency (each timed window ends with the D2H
read of the result — the honest protocol from BASELINE.md), and the
program-cache counter that pins "ragged stream compiles <= one program
per bucket" in tests and bench.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.datasets.device_feed import (DEFAULT_MIN_BUCKET,
                                                     bucket_for,
                                                     pow2_buckets)
from deeplearning4j_tpu.utils.jitcache import jit_cache_size

__all__ = ["EngineStats", "InferenceEngine"]


def _percentile(sorted_vals, q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


class EngineStats:
    """Thread-safe per-engine counters + a bounded latency reservoir."""

    def __init__(self, window: int = 2048):
        self._lock = threading.Lock()
        self.requests = 0
        self.rows = 0
        self.padded_rows = 0
        self.errors = 0
        self._latencies = deque(maxlen=window)

    def record(self, rows: int, bucket: int, seconds: float) -> None:
        with self._lock:
            self.requests += 1
            self.rows += rows
            self.padded_rows += bucket - rows
            self._latencies.append(seconds)

    def record_error(self) -> None:
        with self._lock:
            self.errors += 1

    def snapshot(self) -> dict:
        with self._lock:
            lat = sorted(self._latencies)
            shipped = self.rows + self.padded_rows
            return {
                "requests": self.requests,
                "rows": self.rows,
                "padded_rows": self.padded_rows,
                "errors": self.errors,
                # fraction of shipped rows that were real work
                "occupancy": (self.rows / shipped) if shipped else 0.0,
                "latency_p50_ms": round(_percentile(lat, 0.50) * 1e3, 3),
                "latency_p99_ms": round(_percentile(lat, 0.99) * 1e3, 3),
            }


class InferenceEngine:
    """A jitted, bucket-padded forward for one model on one device.

    `apply_fn(params, x)` must be a pure per-row forward; `x`'s leading
    dim is the batch. Construct via the classmethods for the stock
    model families, or directly for anything functional.
    """

    def __init__(self, apply_fn: Callable, params, *,
                 max_batch_size: int = 64,
                 buckets: Optional[Sequence[int]] = None,
                 min_bucket: int = DEFAULT_MIN_BUCKET,
                 device=None,
                 generate_fn: Optional[Callable] = None):
        import jax

        if buckets is None:
            buckets = pow2_buckets(max_batch_size, min_bucket=min_bucket)
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError(f"buckets must be positive, got {buckets}")
        self.max_batch_size = int(max_batch_size)
        self.device = device
        self._params = (jax.device_put(params, device)
                        if device is not None else params)
        # donate the request buffer (engine-owned: infer stages through
        # host + device_put, never the caller's array) so its HBM is
        # reused for activations; CPU ignores donation with a warning,
        # so gate it off there
        donate = () if jax.default_backend() == "cpu" else (1,)
        self._jit = jax.jit(apply_fn, donate_argnums=donate)
        self._generate_fn = generate_fn
        self.stats = EngineStats()

    # ----------------------------------------------------- constructors
    @classmethod
    def for_network(cls, net, **kw) -> "InferenceEngine":
        """Wrap a MultiLayerNetwork: apply = output-layer activations
        (the bucketed twin of `net.output`)."""
        return cls(lambda p, x: net.feed_forward_fn(p, x)[-1],
                   net.param_table, **kw)

    @classmethod
    def for_transformer(cls, params, cfg, **kw) -> "InferenceEngine":
        """Wrap a transformer LM: apply = full logits (B, T, vocab);
        `generate()` runs the KV-cached decode loop."""
        from deeplearning4j_tpu.models.transformer import transformer_logits
        from deeplearning4j_tpu.serving.kv_cache import generate_cached

        return cls(lambda p, tok: transformer_logits(p, tok, cfg), params,
                   generate_fn=lambda p, prompt, n: generate_cached(
                       p, prompt, cfg, n),
                   **kw)

    @classmethod
    def for_lstm(cls, layer, params, **kw) -> "InferenceEngine":
        """Wrap an LSTM layer: apply = per-timestep decoded outputs over
        (B, T, n_in) input."""
        return cls(lambda p, x: layer.activate(p, x), params, **kw)

    # ------------------------------------------------------------ serve
    def infer(self, x) -> np.ndarray:
        """One request: (n, ...) -> np.ndarray of the first n output
        rows. Pads n up to the bucket ladder (requests beyond the top
        bucket take the pow2 escape ladder, still bounding program
        count), runs the compiled forward, slices the padding off. The
        returned array is host-resident — the D2H read is inside the
        latency window.

        Input is staged through the host (np.asarray + device_put), so
        the device buffer handed to the donated jit arg is always
        engine-owned — a caller's device array is never invalidated."""
        import jax

        x = np.asarray(x)
        if x.ndim < 2:
            raise ValueError(
                f"infer expects a (n, ...) batch, got shape {x.shape}")
        n = int(x.shape[0])
        if n == 0:
            raise ValueError("empty request")
        start = time.perf_counter()
        try:
            b = bucket_for(n, self.buckets)
            if b != n:  # pad on host — the H2D copy ships once
                x = np.concatenate(
                    [x, np.zeros((b - n, *x.shape[1:]), x.dtype)])
            xb = jax.device_put(x, self.device)
            out = np.asarray(self._jit(self._params, xb)[:n])
        except Exception:
            self.stats.record_error()
            raise
        self.stats.record(n, b, time.perf_counter() - start)
        return out

    def generate(self, prompt, n_tokens: int) -> np.ndarray:
        """KV-cached greedy decode (transformer engines only):
        prompt (B, T0) int tokens -> (B, T0 + n_tokens)."""
        import jax.numpy as jnp

        if self._generate_fn is None:
            raise ValueError(
                "this engine has no generate path (construct it with "
                "InferenceEngine.for_transformer)")
        prompt = jnp.asarray(prompt, jnp.int32)
        if prompt.ndim != 2:
            raise ValueError(
                f"prompt must be (B, T0) tokens, got shape {prompt.shape}")
        start = time.perf_counter()
        try:
            out = np.asarray(
                self._generate_fn(self._params, prompt, int(n_tokens)))
        except Exception:
            self.stats.record_error()
            raise
        self.stats.record(int(prompt.shape[0]), int(prompt.shape[0]),
                          time.perf_counter() - start)
        return out

    # ---------------------------------------------------- observability
    def warmup(self, feature_shape: Sequence[int],
               dtype=np.float32) -> None:
        """Compile every bucket program up front so the first real
        requests don't pay compile latency. `feature_shape` is one
        example's shape (without the batch dim). Bypasses EngineStats —
        warmup compiles must not pollute the serving p50/p99/occupancy
        the bench and /stats report."""
        import jax

        for b in self.buckets:
            xb = jax.device_put(np.zeros((b, *feature_shape), dtype),
                                self.device)
            np.asarray(self._jit(self._params, xb))

    def program_cache_size(self) -> int:
        """Compiled-program count for the jitted forward — the serving
        twin of MultiLayerNetwork.train_step_cache_size(). With bucket
        padding this stays <= len(buckets) hit (+ escape buckets);
        -1 when the private jax counter API drifted."""
        return jit_cache_size(self._jit)

    def snapshot(self) -> dict:
        snap = self.stats.snapshot()
        snap["buckets"] = list(self.buckets)
        snap["compiled_programs"] = self.program_cache_size()
        if self.device is not None:
            snap["device"] = str(self.device)
        return snap
