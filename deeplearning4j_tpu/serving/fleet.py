"""Elastic serving fleet: health-tracked replicas behind a router tier.

`serving/replicas.py` scales one process across local chips; this
module scales across PROCESSES (and hosts): a `Fleet` owns N replica
endpoints — each a full `serve_network` server, spawned locally by a
`ReplicaSpawner` or attached by URL — and the router
(`serving/router.py`) dispatches over them. The design deliberately
reuses the scaleout control-plane idioms (ROADMAP "Elastic serving
fleet"): replica health IS worker health, so the fleet rides the same
`InMemoryStateTracker` the distributed runtime uses —
`tracker.heartbeat()` on every successful liveness probe (which
re-registers an evicted member, the tracker's elasticity contract),
`tracker.stale_workers()` to find the dead, the `runtime._evict_stale`
shape for eviction. The whole-program-compilation framing of
arXiv:1810.09868 motivates the readiness split: a replica is a
compiled-once program whose spin-up (warmup precompile) is hidden
behind the router — `/healthz` up but `/readyz` 503 means "alive,
still compiling", and the router admits it only when readiness lands.

Replica lifecycle:

```
 attach()/spawn()          readyz ok                 heartbeat stale /
      │                       │                      conn refused
      ▼                       ▼                            │
  STARTING ───────────────► READY ◄────────────────┐       ▼
                              │     readyz ok      │   EVICTED ◄──┐
                              │  (readmission)     └───────┤      │
                     drain for reload/retire               │ probes keep
                              ▼                            │ running: a
                          DRAINING ──► READY / retired     │ rejoining
                                                           └─ replica is
                                                              readmitted
```

Routing is least-outstanding-requests over READY replicas (round-robin
tiebreak — the same policy `ReplicaSet` applies intra-process), with:

- **retries**: idempotent `/predict` replays on a healthy peer after a
  connection failure, request timeout, or replica 5xx — under an
  explicit `retry_budget`, with each hop's socket timeout derived from
  the request's remaining `deadline_ms` budget (docs/SERVING.md
  "Deadlines") so a hung replica costs a slice of the budget, not the
  fixed 30s client timeout; a connection-level failure also evicts the
  replica immediately (faster than the heartbeat timeout — the monitor
  readmits it when it answers `/readyz` again).
- **hung-replica defense**: a request TIMEOUT marks the replica
  SUSPECT (deprioritized, still probed) and feeds its per-replica
  circuit breaker — closed → open after `breaker_threshold`
  consecutive timeouts (the replica is EVICTED: hung-but-TCP-alive
  members, e.g. SIGSTOP'd or with a wedged handler pool, answer
  health probes the heartbeat path trusts) → half-open after
  `breaker_reset_s` (one `/readyz` probe) → closed on success
  (readmission). One pathological request still cannot evict a
  replica; N consecutive ones can (docs/FLEET.md "Chaos runbook").
- **load shedding**: total in-flight past `shed_high_water` answers
  503 + `Retry-After` + `{"error": "overloaded", ...}` before any
  replica is touched — PER TIER: the batch lane has its own lower
  `batch_high_water` (default half the global mark) so bulk work sheds
  while interactive admission still has headroom, and every shed reply
  names the shed tier and derives Retry-After from THAT tier's backlog
  (docs/FLEET.md "Per-tier shedding & autoscaling").
- **rolling/canary reload** (`rolling_reload`): drain -> per-replica
  `POST /reload` -> `/readyz` probe (-> optional `/predict` validation
  probe) -> readmit, one replica at a time; the first replica is the
  canary — if it fails, replicas already on the new checkpoint roll
  back to the previous one automatically and the fleet stays
  consistent. A replica whose `/reload` itself failed kept its old
  weights (the engine's validated atomic swap), so only
  probe-stage failures need a rollback of the failed member.
- **autoscaling hook** (`Autoscaler` + a spawner): queue-depth
  (outstanding-per-replica) signals spawn or retire replicas between
  `min_replicas`/`max_replicas` with a cooldown; `scale_to(n)` is the
  manual twin (router `POST /scale`).
- **crash-safe control plane** (`state_dir=`): losing the router no
  longer strands (or worse, recompiles) the warm fleet. Every
  membership transition journals replica endpoints, states, and spawn
  fingerprints (pid + /proc start time) through a `utils/statefile.py`
  StateFile (`fleet.journal`, the checkpoint layer's atomic-rename
  commit idiom), and a restarted incarnation re-adopts the journaled
  world instead of respawning it: attached URLs re-attach, spawned
  replicas whose fingerprints verify become `AdoptedProc` members
  (released from the previous incarnation's atexit sweep via
  `procs.release_spawned` on a handoff close — and simply surviving a
  SIGKILL, which runs no sweep at all), and the ordinary `/readyz`
  probe readmits each one WARM — zero replica respawns, zero engine
  recompiles. Dead or recycled pids are skipped (the
  spawner/autoscaler replaces them); a torn journal degrades to a
  fresh spawn, never a crash. `cli watchdog` supervises the router
  itself (docs/FLEET.md "Router restart runbook").

Telemetry (`dl4j_fleet_*` + `dl4j_controlplane_*`,
docs/OBSERVABILITY.md): `dl4j_fleet_replicas{state=}` gauges,
request/retry/shed/eviction/readmission/reload counters, per-route
latency histograms, `dl4j_fleet_outstanding`; control-plane restarts,
adoptions by kind, journal write/commit histograms, incarnation gauge.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import subprocess
import sys
import threading
import time
import weakref
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.scaleout.statetracker import InMemoryStateTracker
from deeplearning4j_tpu.utils import procs
from deeplearning4j_tpu.utils.statefile import StateFile
from deeplearning4j_tpu.serving.errors import (DEADLINE_HEADER,
                                               PRIORITY_HEADER,
                                               TIER_BATCH, TIER_INTERACTIVE,
                                               TIERS, Deadline,
                                               OverloadedError,
                                               backlog_retry_ms)
from deeplearning4j_tpu.serving.router import ReplicaClient

__all__ = ["Fleet", "FleetReplica", "ReplicaSpawner", "Autoscaler",
           "CircuitBreaker", "NoReadyReplicas",
           "STARTING", "READY", "SUSPECT", "DRAINING", "EVICTED"]

log = logging.getLogger(__name__)

STARTING = "starting"
READY = "ready"
#: READY member with recent request timeouts: still alive by every
#: probe, deprioritized for routing, one breaker trip from EVICTED
SUSPECT = "suspect"
DRAINING = "draining"
EVICTED = "evicted"
STATES = (STARTING, READY, SUSPECT, DRAINING, EVICTED)

_fleet_seq = itertools.count()

#: rough per-request drain estimate feeding tier-aware Retry-After at
#: the fleet's shed sites: an interactive request is a short decode, a
#: batch request is a bulk stream — a shed bulk client should back off
#: proportionally longer (serving/errors.backlog_retry_ms)
_TIER_ITEM_MS = {TIER_INTERACTIVE: 50.0, TIER_BATCH: 250.0}


class NoReadyReplicas(RuntimeError):
    """No replica is in the READY state (the router answers 503)."""


class CircuitBreaker:
    """Per-replica request-timeout breaker (mutations happen under the
    owning fleet's lock).

    closed --(threshold consecutive timeouts)--> open
    open   --(reset_s elapsed, one /readyz probe)--> half_open
    half_open --(probe ok)--> closed | --(probe fails)--> open

    The heartbeat monitor sees liveness; THIS sees request progress —
    a SIGSTOP'd replica (the kernel keeps accepting into the listen
    backlog) or a wedged handler pool passes every health probe and
    only the breaker evicts it. Any success fully closes the breaker;
    one success is what a half-open trial is for."""

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(self, threshold: int = 3, reset_s: float = 2.0):
        if threshold < 1:
            raise ValueError(f"threshold must be >= 1, got {threshold}")
        self.threshold = int(threshold)
        self.reset_s = float(reset_s)
        #: a "consecutive" streak whose previous timeout is older than
        #: this is no streak at all — without the horizon, 2-of-3
        #: timeouts from a transient blip would arm the breaker
        #: forever, and ONE slow request hours later would evict a
        #: healthy replica (a suspect's probing trickle fires well
        #: inside this window, so real hangs still accumulate)
        self.streak_ttl_s = max(30.0, 10.0 * self.reset_s)
        self.state = self.CLOSED
        self.consecutive_timeouts = 0
        self.opened_at: Optional[float] = None
        self.last_timeout_at: Optional[float] = None
        self.opens = 0  # lifetime closed/half_open -> open transitions

    def record_timeout(self) -> bool:
        """Count one request timeout; returns True when this one OPENS
        the breaker (the caller evicts)."""
        now = time.monotonic()
        if (self.state == self.CLOSED
                and self.last_timeout_at is not None
                and now - self.last_timeout_at > self.streak_ttl_s):
            self.consecutive_timeouts = 0  # ancient streak: start over
        self.consecutive_timeouts += 1
        self.last_timeout_at = now
        trip = (self.state == self.HALF_OPEN
                or self.consecutive_timeouts >= self.threshold)
        if trip and self.state != self.OPEN:
            self.state = self.OPEN
            self.opened_at = time.monotonic()
            self.opens += 1
            return True
        if trip:
            self.opened_at = time.monotonic()  # re-arm the reset clock
        return False

    def record_success(self) -> None:
        self.consecutive_timeouts = 0
        self.state = self.CLOSED
        self.opened_at = None

    def allow_probe(self) -> bool:
        """True when a half-open `/readyz` probe may run: open breakers
        wait out `reset_s` first (and transition to half_open here)."""
        if self.state == self.OPEN:
            if (self.opened_at is not None
                    and time.monotonic() - self.opened_at >= self.reset_s):
                self.state = self.HALF_OPEN
                return True
            return False
        return True  # closed / half_open: probing is always fine

    def reopen(self) -> None:
        """A half-open probe failed: back to open, clock re-armed."""
        self.state = self.OPEN
        self.opened_at = time.monotonic()

    def snapshot(self) -> dict:
        return {"state": self.state,
                "consecutive_timeouts": self.consecutive_timeouts,
                "opens": self.opens,
                "threshold": self.threshold,
                "reset_s": self.reset_s}


class FleetReplica:
    """Router-side record of one replica endpoint. Mutable fields
    (`state`, `outstanding`, `failures`) are guarded by the owning
    fleet's lock."""

    def __init__(self, replica_id: str, client: ReplicaClient,
                 proc: Optional[subprocess.Popen] = None,
                 spawned: bool = False,
                 breaker: Optional[CircuitBreaker] = None,
                 adopted: bool = False):
        self.id = replica_id
        self.client = client
        self.proc = proc
        self.spawned = spawned
        self.adopted = adopted  # re-adopted from a prior incarnation
        #: /proc start-time fingerprint journaled next to the pid so a
        #: restarted router never adopts (or kills) a recycled pid
        self.start_time = (getattr(proc, "start_time", None)
                           or (procs.proc_start_time(proc.pid)
                               if proc is not None else None))
        self.state = STARTING
        self.outstanding = 0
        self.failures = 0          # consecutive request-path failures
        self.breaker = breaker if breaker is not None else CircuitBreaker()
        self.last_ready: Optional[dict] = None
        #: last cumulative ship stats folded into the fleet counters
        #: (the /readyz kv_summary reports lifetime figures; the probe
        #: deltas them — see Fleet._fold_kv_summary)
        self.kv_seen: Optional[dict] = None
        self.admitted_at: Optional[float] = None
        self.evicted_at: Optional[float] = None
        self.eviction_reason: Optional[str] = None
        #: (model_id, role) pool this replica was spawned INTO —
        #: pool-scoped autoscaling attributes a STARTING member (no
        #: /readyz payload yet, so no announced identity) to the pool
        #: that spawned it instead of the default pool
        self.pool: Optional[Tuple[str, str]] = None

    @property
    def role(self) -> str:
        """Replica role announced in its last /readyz payload
        (docs/FLEET.md "Disaggregated roles"). "unified" until the
        first probe — a never-probed replica routes the legacy way."""
        return (self.last_ready or {}).get("role") or "unified"

    @property
    def model_id(self) -> Optional[str]:
        """Model this replica announced (None = single-model legacy;
        consumers normalize None to "default")."""
        return (self.last_ready or {}).get("model_id")

    def snapshot(self, now: Optional[float] = None) -> dict:
        now = now if now is not None else time.time()
        out = {"url": self.client.url, "state": self.state,
               "outstanding": self.outstanding,
               "failures": self.failures, "spawned": self.spawned,
               # what the replica itself says it serves ({path, step}
               # or None), from its last /readyz payload — the per-
               # replica identity the torn-promotion check aggregates
               "checkpoint": (self.last_ready or {}).get("checkpoint"),
               # disaggregated placement identity, from the same probe
               "role": self.role,
               "model_id": self.model_id,
               "breaker": self.breaker.snapshot()}
        if self.adopted:
            out["adopted"] = True
        if self.proc is not None:
            out["pid"] = self.proc.pid
            out["proc_alive"] = self.proc.poll() is None
        if self.admitted_at is not None:
            out["admitted_age_s"] = round(now - self.admitted_at, 3)
        if self.state == EVICTED and self.evicted_at is not None:
            out["evicted_age_s"] = round(now - self.evicted_at, 3)
            out["eviction_reason"] = self.eviction_reason
        return out


# spawned replica processes still alive, reaped at interpreter exit: a
# router that dies without close() must not leak live replica servers
# holding ports. Each replica runs in its OWN session/process group
# (start_new_session); the registry, atexit sweep, and group-kill
# discipline are shared with the training supervisor's WorkerSpawner
# (utils/procs.py holds the pid/pgid-recycling rationale). The module
# aliases keep the historical names on fleet's surface.
_SPAWNED_PROCS = procs.SPAWNED_PROCS
_register_spawned = procs.register_spawned
_unregister_spawned = procs.unregister_spawned
_kill_spawned_orphans = procs.kill_spawned_orphans


class ReplicaSpawner:
    """Spawns local replica server processes (`cli serve` with async
    warmup) and reads each one's announce line for its URL.

    This is the single-host spawner (the autoscaling hook's local
    backend and the test/bench harness); a multi-host deployment
    attaches remote replicas by URL instead and brings its own process
    manager. Every spawn lands in its own process group and a
    module-level atexit sweep SIGKILLs whatever `stop()` never reaped —
    a router crash-exit cannot orphan replica servers on live ports."""

    def __init__(self, model_path: str, *, host: str = "127.0.0.1",
                 serve_args: Sequence[str] = (),
                 env: Optional[dict] = None,
                 python: Optional[str] = None,
                 announce_timeout: float = 180.0):
        self.model_path = str(model_path)
        self.host = host
        self.serve_args = list(serve_args)
        self.env = dict(env) if env is not None else dict(os.environ)
        # replicas inherit the parent's AOT program cache so respawns
        # and autoscale spin-ups boot warm (docs/WARMUP.md)
        from deeplearning4j_tpu import compilecache
        compilecache.export_env(self.env)
        self.python = python or sys.executable
        self.announce_timeout = float(announce_timeout)

    def command(self, port: int = 0) -> List[str]:
        return ([self.python, "-m", "deeplearning4j_tpu.cli", "serve",
                 "-m", self.model_path, "--host", self.host,
                 "--port", str(port), "--warmup-async"]
                + self.serve_args)

    def spawn(self, port: int = 0
              ) -> Tuple[subprocess.Popen, str]:
        """Launch one replica process; returns (proc, url). The
        replica announces fast (async warmup) — readiness is gated by
        its /readyz, not by this call. The process gets its own
        session/group and is registered for atexit orphan cleanup."""
        proc = subprocess.Popen(
            self.command(port), env=self.env, text=True,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            start_new_session=True)
        _register_spawned(proc)
        try:
            url = self._read_announce(proc)
        except BaseException:
            _unregister_spawned(proc)
            raise
        return proc, url

    def _read_announce(self, proc: subprocess.Popen) -> str:
        """First stdout line is the serve announce JSON; a stdout drain
        thread keeps running afterwards so the child never blocks on a
        full pipe (its tail is kept for post-mortem errors)."""
        tail: deque = deque(maxlen=50)
        found: List[str] = []
        got = threading.Event()

        def drain():
            for line in proc.stdout:
                tail.append(line.rstrip())
                if not found and line.lstrip().startswith("{"):
                    try:
                        if "serving" in json.loads(line):
                            found.append(line)
                            got.set()
                    except ValueError:
                        pass
            got.set()  # EOF

        t = threading.Thread(target=drain, daemon=True,
                             name="replica-announce")
        t.start()
        if not got.wait(self.announce_timeout) or not found:
            proc.kill()
            raise RuntimeError(
                "replica process produced no announce line within "
                f"{self.announce_timeout}s; output tail:\n"
                + "\n".join(tail))
        return json.loads(found[0])["serving"]

    @staticmethod
    def stop(proc: subprocess.Popen, timeout: float = 10.0) -> None:
        """Terminate a spawned replica and its whole process group —
        TERM the group (leader un-reaped: raceless), give it the
        graceful window, KILL stragglers. Ordering rationale lives in
        utils/procs.stop_process_group."""
        procs.stop_process_group(proc, timeout=timeout)


class Autoscaler:
    """Queue-depth-driven scaling policy: spawn when mean outstanding
    per ready replica crosses `scale_up_at`, retire when it falls under
    `scale_down_at`, bounded by [min_replicas, max_replicas] with a
    cooldown between actions. Pure policy — the Fleet applies the
    decision (`Fleet.autoscale_tick`), so tests drive it with synthetic
    load and a fake spawner.

    The BATCH tier feeds a second, backlog-shaped signal
    (docs/FLEET.md "Per-tier shedding & autoscaling"): bulk streams
    queue patiently behind replica admission instead of inflating
    instantaneous queue depth the way an interactive burst does, so
    batch scales up on `batch_backlog >= batch_backlog_up_at` (how much
    bulk work is parked, not how fast it arrives) and the fleet never
    scales DOWN while any batch backlog exists — idle capacity is
    exactly what the bulk lane is there to soak."""

    def __init__(self, min_replicas: int = 1, max_replicas: int = 4,
                 scale_up_at: float = 4.0, scale_down_at: float = 0.5,
                 cooldown_s: float = 10.0,
                 batch_backlog_up_at: int = 8):
        if not 1 <= min_replicas <= max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{min_replicas}..{max_replicas}")
        if batch_backlog_up_at < 1:
            raise ValueError(
                f"batch_backlog_up_at must be >= 1, got "
                f"{batch_backlog_up_at}")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.scale_up_at = float(scale_up_at)
        self.scale_down_at = float(scale_down_at)
        self.cooldown_s = float(cooldown_s)
        self.batch_backlog_up_at = int(batch_backlog_up_at)
        self._last_action = 0.0

    def decide(self, n_replicas: int, outstanding: int,
               batch_backlog: int = 0) -> int:
        """-1 / 0 / +1 given live replica count, total in-flight, and
        the batch tier's parked backlog."""
        if n_replicas < self.min_replicas:
            return 1  # below floor: act regardless of cooldown
        if time.monotonic() - self._last_action < self.cooldown_s:
            return 0
        per = outstanding / max(1, n_replicas)
        if per >= self.scale_up_at and n_replicas < self.max_replicas:
            return 1
        if (batch_backlog >= self.batch_backlog_up_at
                and n_replicas < self.max_replicas):
            return 1
        if (per <= self.scale_down_at and n_replicas > self.min_replicas
                and batch_backlog == 0):
            return -1
        return 0

    def note_action(self) -> None:
        self._last_action = time.monotonic()


class Fleet:
    """N replica endpoints + health tracking + dispatch policy."""

    def __init__(self, *, spawner: Optional[ReplicaSpawner] = None,
                 heartbeat_interval: float = 0.5,
                 heartbeat_timeout: float = 3.0,
                 shed_high_water: Optional[int] = None,
                 batch_high_water: Optional[int] = None,
                 probe_timeout: float = 2.0,
                 request_timeout: float = 60.0,
                 generate_timeout: float = 300.0,
                 retry_budget: int = 2,
                 stream_resume_attempts: int = 2,
                 breaker_threshold: int = 3,
                 breaker_reset_s: Optional[float] = None,
                 autoscaler: Optional[Autoscaler] = None,
                 initial_checkpoint: Optional[str] = None,
                 name: Optional[str] = None,
                 state_dir: Optional[str] = None,
                 start: bool = True):
        self.spawner = spawner
        self.autoscaler = autoscaler
        self.heartbeat_interval = float(heartbeat_interval)
        self.shed_high_water = shed_high_water
        #: the BATCH tier's own (lower) high-water mark: bulk work
        #: sheds while interactive admission is still wide open, so an
        #: interactive burst always finds headroom. Default: half the
        #: global mark. Per-tier in-flight is tracked fleet-side
        #: (`_tier_inflight`, select/release twins).
        if batch_high_water is not None:
            if batch_high_water < 1:
                raise ValueError(
                    f"batch_high_water must be >= 1, got "
                    f"{batch_high_water}")
            self.batch_high_water: Optional[int] = int(batch_high_water)
        elif shed_high_water is not None:
            self.batch_high_water = max(1, int(shed_high_water) // 2)
        else:
            self.batch_high_water = None
        self._tier_inflight = {t: 0 for t in TIERS}
        #: monitor probes use this short dedicated timeout, never the
        #: ReplicaClient default — and the sweep probes replicas
        #: CONCURRENTLY, so one hung replica costs the sweep one probe
        #: timeout instead of stalling every later probe past the
        #: heartbeat window
        self.probe_timeout = float(probe_timeout)
        self.request_timeout = float(request_timeout)
        self.generate_timeout = float(generate_timeout)
        #: retries (attempts after the first) forward_predict may spend
        #: on peers after a failure; deadline budgets are split across
        #: the attempts this allows
        self.retry_budget = max(0, int(retry_budget))
        #: mid-stream /generate failovers the router may attempt per
        #: client request: each resume re-admits the interrupted rows
        #: on a surviving replica with `prompt + delivered tokens` as
        #: the continuation context (docs/FLEET.md "Stream failover");
        #: 0 restores the pre-failover fail-fast behavior
        self.stream_resume_attempts = max(0, int(stream_resume_attempts))
        self.breaker_threshold = int(breaker_threshold)
        #: open -> half_open wait; default: a few monitor passes
        self.breaker_reset_s = (float(breaker_reset_s)
                                if breaker_reset_s is not None
                                else 4.0 * self.heartbeat_interval)
        #: checkpoint the fleet currently serves — the implicit
        #: rollback target of a failed canary (rolling_reload updates
        #: it; None until a reload or an explicit initial_checkpoint)
        self.current_checkpoint = initial_checkpoint
        #: step of current_checkpoint once a rolling_reload pinned one.
        #: While set, every replica ADMITTED into rotation (capacity-gap
        #: spawn, readmission, adoption) is first converged onto exactly
        #: this checkpoint@step — a promotion can never end up torn by
        #: later capacity repair. None = never promoted: boot-time
        #: heterogeneity is the operator's business, not ours.
        self.current_step: Optional[int] = None
        #: multi-model twin of current_checkpoint/current_step:
        #: model_id -> (path, step) pinned by a model-scoped
        #: rolling_reload. Newcomers announcing that model converge
        #: onto THIS identity before admission (docs/FLEET.md
        #: "Disaggregated roles" — one router, N models)
        self.model_checkpoints: Dict[str, Tuple[str, Optional[int]]] = {}
        #: (model_id, role) -> {"spawner", "autoscaler"} replica pools
        #: for pool-scoped autoscaling (add_pool); empty = the legacy
        #: single-pool fleet-level autoscaler signal
        self._pools: Dict[Tuple[str, str], dict] = {}
        #: (role, model) gauge children registered so far — roles and
        #: models are DISCOVERED from /readyz payloads, so the
        #: dl4j_fleet_role_replicas series appear at first sight
        self._role_gauge_keys: set = set()
        # the scaleout control-plane tracker IS the health store:
        # heartbeat() on probe success (re-registers evicted members),
        # stale_workers() drives eviction — runtime._evict_stale's idiom
        self.tracker = InMemoryStateTracker(
            heartbeat_timeout=heartbeat_timeout)
        self._replicas: Dict[str, FleetReplica] = {}  # insertion order
        self._lock = threading.RLock()
        self._rr = 0
        self._rid_seq = itertools.count()
        self._reload_lock = threading.Lock()
        self._reload_active = False
        self._closed = threading.Event()
        self._monitor: Optional[threading.Thread] = None

        # ------------------------------------ crash-safe control plane
        self.state_dir = state_dir
        self.journal: Optional[StateFile] = None
        self.incarnation = 0
        self.adoption_events: List[dict] = []
        self._journal_io_lock = threading.Lock()
        #: journal writes are suppressed while _adopt_prior runs: each
        #: attach() inside it would otherwise commit a journal naming
        #: only the already-adopted SUBSET — a crash mid-adoption would
        #: then permanently leak the rest of the warm world. One commit
        #: lands after adoption completes.
        self._adopting = False
        self._prior_journal = None
        if state_dir is not None:
            self.journal = StateFile(
                os.path.join(state_dir, "fleet.journal"),
                point="fleet.journal")
            self._prior_journal = self.journal.read()
            if self._prior_journal is not None:
                self.incarnation = int(
                    self._prior_journal.get("incarnation", 0)) + 1
            elif self.journal.torn:
                self.incarnation = 1  # prior world unknown: fresh spawn

        # telemetry ----------------------------------------------------
        reg = telemetry.get_registry()
        self.label = name if name is not None else f"f{next(_fleet_seq)}"
        lab = {"fleet": self.label}
        self._m_requests = {
            route: reg.counter(
                "dl4j_fleet_requests",
                "requests routed by the fleet tier").labels(
                    route=route, **lab)
            for route in ("predict", "generate")}
        self._m_latency = {
            route: reg.histogram(
                "dl4j_fleet_request_latency_seconds",
                "router-side request wall latency (incl. retries)"
            ).labels(route=route, **lab)
            for route in ("predict", "generate")}
        self._m_shed = {
            route: reg.counter(
                "dl4j_fleet_shed",
                "requests shed at the router's high-water mark").labels(
                    route=route, **lab)
            for route in ("predict", "generate")}
        self._m_retries = reg.counter(
            "dl4j_fleet_retries",
            "predict retries on a healthy peer after a replica "
            "failure").labels(**lab)
        self._m_deadline = {
            route: reg.counter(
                "dl4j_fleet_deadline_exceeded",
                "requests shed at the router because their deadline "
                "budget was already spent").labels(route=route, **lab)
            for route in ("predict", "generate")}
        self._m_stream_resumes = reg.counter(
            "dl4j_fleet_stream_resumes",
            "mid-stream /generate failovers re-admitted on a "
            "surviving replica (prompt + delivered tokens replayed "
            "as the continuation context)").labels(**lab)
        self._m_stream_resume_failures = reg.counter(
            "dl4j_fleet_stream_resume_failures",
            "generate streams the router could NOT resume (attempts "
            "or deadline budget exhausted, or no surviving replica) "
            "— the client saw the in-band retryable error").labels(
                **lab)
        self._m_stream_tokens_replayed = reg.counter(
            "dl4j_fleet_stream_tokens_replayed",
            "context tokens (prompt + already-delivered) re-submitted "
            "as prefill during stream failover — the prefix cache "
            "turns these into page-reference hits on the "
            "survivor").labels(**lab)
        self._m_stream_tokens_deduped = reg.counter(
            "dl4j_fleet_stream_tokens_deduped",
            "replayed tokens the router suppressed by absolute "
            "token_index so the client stream stays exactly-once "
            "across failover").labels(**lab)
        self._m_disagg_handoffs = reg.counter(
            "dl4j_disagg_handoffs",
            "prefill->decode handoffs dispatched: the router drove "
            "/prefill on a prefill-role replica and named it as the "
            "kv_donor of the decode placement").labels(**lab)
        self._m_disagg_handoff_bytes = reg.counter(
            "dl4j_disagg_handoff_bytes",
            "KV page bytes made shippable by prefill handoffs (as "
            "reported by the prefill replica's /prefill reply)").labels(
                **lab)
        self._m_disagg_handoff_failures = reg.counter(
            "dl4j_disagg_handoff_failures",
            "prefill handoff dispatches that errored (dead prefill "
            "replica, shed, chaos) — each one degrades the stream to "
            "plain unified prefill, never to a failed request").labels(
                **lab)
        self._m_disagg_fallbacks = reg.counter(
            "dl4j_disagg_fallbacks",
            "streams that proceeded with plain prefill after a failed "
            "or skipped handoff on a fleet that HAS prefill "
            "capacity").labels(**lab)
        tscope = {"scope": f"fleet:{self.label}"}
        self._m_tier_requests = {
            t: reg.counter(
                "dl4j_tier_requests",
                "requests admitted per SLO tier").labels(tier=t, **tscope)
            for t in TIERS}
        self._m_tier_shed = {
            t: reg.counter(
                "dl4j_tier_shed",
                "requests shed per SLO tier (batch sheds at its own, "
                "lower high-water mark)").labels(tier=t, **tscope)
            for t in TIERS}
        self._m_tier_latency = {
            t: reg.histogram(
                "dl4j_tier_request_latency_seconds",
                "router-side request wall latency per SLO tier").labels(
                    tier=t, **tscope)
            for t in TIERS}
        self._m_preempt_resumes = reg.counter(
            "dl4j_tier_preempt_resumes",
            "batch rows re-admitted after an interactive arrival "
            "preempted their decode slot — the lossless durable-stream "
            "resume path, distinct from failover resumes").labels(
                tier=TIER_BATCH, **tscope)
        self._m_timeouts = reg.counter(
            "dl4j_fleet_request_timeouts",
            "request-path timeouts (the circuit breaker's input — a "
            "hung-but-TCP-alive replica shows up here first)").labels(
                **lab)
        self._m_breaker_opens = reg.counter(
            "dl4j_fleet_breaker_opens",
            "circuit breakers tripped open (the replica is evicted "
            "until a half-open /readyz probe passes)").labels(**lab)
        self._m_evictions = reg.counter(
            "dl4j_fleet_evictions",
            "replicas evicted (stale heartbeat, lost readiness, or "
            "connection failure)").labels(**lab)
        self._m_readmissions = reg.counter(
            "dl4j_fleet_readmissions",
            "evicted replicas readmitted after passing /readyz").labels(
                **lab)
        self._m_reloads = {
            outcome: reg.counter(
                "dl4j_fleet_reloads",
                "rolling checkpoint reloads by outcome").labels(
                    outcome=outcome, **lab)
            for outcome in ("ok", "rolled_back", "failed")}
        self._m_spawned = reg.counter(
            "dl4j_fleet_spawned", "replicas spawned").labels(**lab)
        self._m_retired = reg.counter(
            "dl4j_fleet_retired", "replicas retired").labels(**lab)
        # fleet KV plane (serving/fleetkv.py, docs/FLEET.md): affinity
        # placement counted router-side at select; ship counters are
        # DELTAS of the cumulative per-replica figures each /readyz
        # summary carries, folded in by the health probe — the router
        # never sits on the ship path, yet its /metrics still tells
        # the fleet-wide story
        self._m_affinity_hits = reg.counter(
            "dl4j_fleet_prefix_affinity_hits",
            "generate requests routed to the replica whose KV summary "
            "matched >= 1 head chunk of the prompt (the fleet-level "
            "prefix hit)").labels(**lab)
        self._m_affinity_misses = reg.counter(
            "dl4j_fleet_prefix_affinity_misses",
            "affinity-eligible generate requests with no summary "
            "match anywhere, or whose preferred replica lost to load "
            "slack / shed / exclusion").labels(**lab)
        self._m_page_ships = reg.counter(
            "dl4j_fleet_prefix_page_ships",
            "KV pages installed via peer-to-peer shipping across the "
            "fleet (replica-reported, probe-aggregated)").labels(**lab)
        self._m_ship_bytes = reg.counter(
            "dl4j_fleet_prefix_ship_bytes",
            "serialized bytes fetched by successful page ships "
            "(replica-reported, probe-aggregated)").labels(**lab)
        self._m_ship_failures = reg.counter(
            "dl4j_fleet_prefix_ship_failures",
            "page-ship attempts that fell back to plain prefill "
            "(donor dead, timeout, crc/identity mismatch, pool "
            "pressure; replica-reported, probe-aggregated)").labels(
                **lab)
        ref = weakref.ref(self)
        for state in STATES:
            reg.gauge(
                "dl4j_fleet_replicas",
                "fleet replicas by lifecycle state").labels(
                    state=state, **lab).set_function(
                (lambda st: lambda: (
                    (lambda o: o.state_counts().get(st, 0) if o else 0)(
                        ref())))(state))
        for bstate in (CircuitBreaker.CLOSED, CircuitBreaker.HALF_OPEN,
                       CircuitBreaker.OPEN):
            reg.gauge(
                "dl4j_fleet_breaker",
                "replica circuit breakers by state").labels(
                    state=bstate, **lab).set_function(
                (lambda st: lambda: (
                    (lambda o: o.breaker_counts().get(st, 0) if o else 0)(
                        ref())))(bstate))
        reg.gauge(
            "dl4j_fleet_outstanding",
            "in-flight requests across the fleet").labels(
                **lab).set_function(
            lambda: (lambda o: o.total_outstanding() if o else 0)(ref()))
        for t in TIERS:
            reg.gauge(
                "dl4j_tier_backlog",
                "in-flight (or replica-queued) requests per SLO "
                "tier").labels(tier=t, **tscope).set_function(
                (lambda _t: lambda: (
                    (lambda o: o._tier_inflight[_t] if o else 0)(
                        ref())))(t))
        reg.gauge(
            "dl4j_fleet_utilization",
            "fleet load as a fraction of shed capacity (outstanding / "
            "shed_high_water; per-ready-replica outstanding when no "
            "mark is set) — near 1.0 under a batch flood means the "
            "bulk lane is soaking idle capacity").labels(
                **lab).set_function(
            lambda: (lambda o: o.utilization() if o else 0.0)(ref()))
        # crash-safe control plane (docs/OBSERVABILITY.md) — series
        # definitions shared with the supervisor (statefile module)
        from deeplearning4j_tpu.utils.statefile import \
            controlplane_metrics

        self._m_restarts, self._m_adoptions = controlplane_metrics(
            "fleet", self.label,
            lambda: (lambda o: o.incarnation if o else 0)(ref()),
            ("adopted", "dead", "recycled", "attached"))

        if self._prior_journal is not None:
            try:
                self._adopt_prior(self._prior_journal)
            except Exception:
                # an unexpectedly-shaped journal degrades to a fresh
                # spawn (the torn-journal rung) — never a crash that
                # burns the watchdog's restart budget
                log.exception("fleet %s: journal adoption failed; "
                              "starting fresh", self.label)
            finally:
                self._adopting = False  # a failed adoption must not
                # leave journaling suppressed for the fleet's lifetime
        self._journal_write()
        if start:
            self.start()

    # ------------------------------------------------------- lifecycle
    def start(self) -> "Fleet":
        if self._monitor is None or not self._monitor.is_alive():
            self._closed.clear()
            self._monitor = threading.Thread(
                target=self._monitor_loop, daemon=True,
                name=f"fleet-monitor-{self.label}")
            self._monitor.start()
        return self

    def close(self, stop_replicas: bool = False,
              timeout: float = 10.0, handoff: bool = False) -> None:
        """Stop the monitor; optionally terminate spawned replica
        processes (attached-by-URL replicas are never touched).

        `handoff=True` (only meaningful with a journal): the router is
        going away but the warm fleet is not — spawned replicas are
        RELEASED from this incarnation's atexit orphan sweep
        (procs.release_spawned) and the journal gets a final commit
        naming them, so the next incarnation re-adopts the whole world
        through `/readyz` with zero respawns and zero recompiles."""
        self._closed.set()
        if self._monitor is not None:
            self._monitor.join(timeout=timeout)
        if handoff and self.journal is not None:
            with self._lock:
                owned = [r.proc for r in self._replicas.values()
                         if r.spawned and r.proc is not None]
            self._journal_write()
            for proc in owned:
                procs.release_spawned(proc)
            log.warning(
                "fleet %s: handing %d spawned replica(s) off to the "
                "next incarnation (journal %s)", self.label,
                len(owned), self.journal.path)
            return
        if stop_replicas:
            with self._lock:
                owned = [r.proc for r in self._replicas.values()
                         if r.spawned and r.proc is not None]
            for proc in owned:
                ReplicaSpawner.stop(proc, timeout=timeout)
            if self.journal is not None:
                # a full teardown hands nothing off: clear the journal
                # so the next incarnation starts fresh instead of
                # probing dead endpoints
                self.journal.clear()

    def __enter__(self) -> "Fleet":
        return self

    def __exit__(self, *exc) -> None:
        self.close(stop_replicas=True)

    # ---------------------------------------- crash-safe control plane
    def _journal_write(self) -> None:
        """Commit the fleet journal (utils/statefile.py atomic rename):
        replica endpoints, states, spawn fingerprints, the serving
        checkpoint. Called at every membership/state transition. A
        failed write is logged and survived — the previous committed
        journal stays valid, and the pid fingerprints reject whatever
        changed since."""
        if self.journal is None or self._adopting:
            return
        with self._lock:
            replicas = {}
            for rid, rep in self._replicas.items():
                entry = {"url": rep.client.url, "state": rep.state,
                         "spawned": rep.spawned,
                         "checkpoint": (rep.last_ready
                                        or {}).get("checkpoint")}
                if rep.proc is not None:
                    entry["pid"] = rep.proc.pid
                    entry["start_time"] = rep.start_time
                replicas[rid] = entry
            state = {
                "plane": "fleet",
                "fleet": self.label,
                "incarnation": self.incarnation,
                "current_checkpoint": self.current_checkpoint,
                "current_step": self.current_step,
                "model_checkpoints": {
                    m: list(v)
                    for m, v in self.model_checkpoints.items()},
                "replicas": replicas,
                "written_at": time.time(),
            }
        with self._journal_io_lock:
            self.journal.try_write(state)

    def _adopt_prior(self, prior: dict) -> None:
        """Re-adopt the previous incarnation's journaled world. Every
        entry re-attaches as STARTING; spawned entries additionally
        verify their (pid, start-time) fingerprint and become
        `AdoptedProc` members — the ordinary monitor then readmits
        each one through `/readyz` WARM: zero respawns, zero
        recompiles. Dead/recycled pids are skipped (spawner/autoscaler
        replace them); a recycled pid is never signalled."""
        self._m_restarts.inc()
        self._adopting = True
        if self.current_checkpoint is None:
            self.current_checkpoint = prior.get("current_checkpoint")
            self.current_step = prior.get("current_step")
        if not self.model_checkpoints:
            self.model_checkpoints = {
                m: (v[0], v[1]) for m, v in
                (prior.get("model_checkpoints") or {}).items()
                if isinstance(v, (list, tuple)) and len(v) == 2}
        max_rid = -1
        for rid, e in (prior.get("replicas") or {}).items():
            if rid.startswith("r"):
                try:
                    max_rid = max(max_rid, int(rid[1:]))
                except ValueError:
                    pass
            url = e.get("url")
            if not url:
                continue
            pid = e.get("pid")
            spawned = bool(e.get("spawned"))
            if spawned and pid:
                kind = procs.classify_pid(pid, e.get("start_time"))
                if kind == "adopted":
                    proc = procs.AdoptedProc(pid, e.get("start_time"))
                    procs.register_spawned(proc)
                    self.attach(url, replica_id=rid, proc=proc,
                                spawned=True, adopted=True)
            else:
                # attached-by-URL member: re-attach; the /readyz probe
                # readmits it (or staleness evicts a dead endpoint)
                self.attach(url, replica_id=rid, adopted=True)
                kind = "attached"
            self._m_adoptions[kind].inc()
            self.adoption_events.append(
                {"replica": rid, "kind": kind, "url": url, "pid": pid,
                 "at": time.time()})
            log.warning("fleet %s: incarnation %d %s prior replica %s "
                        "(%s)", self.label, self.incarnation,
                        "re-adopts" if kind in ("adopted", "attached")
                        else f"found {kind}", rid, url)
        with self._lock:
            # fresh replica ids must never collide with journaled ones
            self._rid_seq = itertools.count(max_rid + 1)
        self._adopting = False

    # ------------------------------------------------------ membership
    def attach(self, url: str, replica_id: Optional[str] = None,
               proc: Optional[subprocess.Popen] = None,
               spawned: bool = False,
               adopted: bool = False) -> FleetReplica:
        """Add a replica endpoint (STARTING until /readyz passes)."""
        with self._lock:
            rid = replica_id or f"r{next(self._rid_seq)}"
            if rid in self._replicas:
                raise ValueError(f"replica id {rid!r} already attached")
            rep = FleetReplica(rid, ReplicaClient(url), proc=proc,
                               spawned=spawned, adopted=adopted,
                               breaker=CircuitBreaker(
                                   threshold=self.breaker_threshold,
                                   reset_s=self.breaker_reset_s))
            self._replicas[rid] = rep
        self.tracker.add_worker(rid)
        self._journal_write()
        return rep

    def spawn(self, n: int = 1) -> List[FleetReplica]:
        """Spawn n local replica processes through the spawner."""
        if self.spawner is None:
            raise RuntimeError("fleet has no spawner configured")
        out = []
        for _ in range(n):
            proc, url = self.spawner.spawn()
            out.append(self.attach(url, proc=proc, spawned=True))
            self._m_spawned.inc()
        return out

    def retire(self, replica_id: str, drain_timeout: float = 30.0
               ) -> None:
        """Drain one replica out of rotation and remove it (terminating
        its process when the fleet spawned it)."""
        with self._lock:
            rep = self._replicas.get(replica_id)
            if rep is None:
                raise KeyError(f"no replica {replica_id!r}")
            rep.state = DRAINING
        self._drain(rep, drain_timeout)
        with self._lock:
            self._replicas.pop(replica_id, None)
        self.tracker.remove_worker(replica_id)
        if rep.spawned and rep.proc is not None:
            ReplicaSpawner.stop(rep.proc)
        self._m_retired.inc()
        self._journal_write()

    def scale_to(self, n: int, drain_timeout: float = 30.0) -> dict:
        """Manual autoscaling hook: spawn or retire (least-loaded,
        fleet-spawned first) until `n` non-evicted replicas remain."""
        spawned, retired = [], []
        with self._lock:
            live = [r for r in self._replicas.values()
                    if r.state != EVICTED]
        if len(live) < n:
            spawned = [r.id for r in self.spawn(n - len(live))]
        while len(live) > n:
            # retire the least-loaded spawned replica first; attached
            # replicas only when nothing spawned remains
            live.sort(key=lambda r: (not r.spawned, r.outstanding))
            victim = live.pop(0)
            self.retire(victim.id, drain_timeout=drain_timeout)
            retired.append(victim.id)
        return {"replicas": n, "spawned": spawned, "retired": retired}

    # -------------------------------------------------- health monitor
    def _monitor_loop(self) -> None:
        while not self._closed.is_set():
            try:
                self.poll()
            except Exception:  # the monitor must survive anything
                log.exception("fleet monitor poll failed")
            self._closed.wait(self.heartbeat_interval)

    def poll(self) -> None:
        """One monitor pass: probe every replica, evict the stale,
        readmit rejoiners, run the autoscaler. Public so tests drive
        it deterministically. Probes run CONCURRENTLY with the short
        dedicated `probe_timeout`: one hung replica (SIGSTOP'd, wedged
        accept loop) costs the sweep a single probe window — it can
        never starve the other replicas' heartbeats past the staleness
        eviction threshold."""
        with self._lock:
            reps = list(self._replicas.values())
        if len(reps) == 1:
            self._probe(reps[0])
        elif reps:
            threads = [threading.Thread(target=self._probe, args=(rep,),
                                        daemon=True,
                                        name=f"fleet-probe-{rep.id}")
                       for rep in reps]
            for t in threads:
                t.start()
            # both probes (healthz + readyz) are socket-timeout bound,
            # so the join wall is ~2 probe windows whatever hangs
            join_by = time.monotonic() + 2.0 * self.probe_timeout + 1.0
            for t in threads:
                t.join(timeout=max(0.0, join_by - time.monotonic()))
        # the scaleout eviction idiom: stale heartbeats name the dead
        for wid in self.tracker.stale_workers():
            with self._lock:
                rep = self._replicas.get(wid)
            if rep is not None and rep.state != EVICTED:
                self._evict(rep, "heartbeat timeout")
        if ((self.autoscaler is not None and self.spawner is not None)
                or self._pools):
            self.autoscale_tick()

    def _probe(self, rep: FleetReplica) -> None:
        try:
            rep.client.healthz(timeout=self.probe_timeout)
        except Exception:
            return  # no heartbeat recorded; staleness evicts
        # liveness ok -> heartbeat (re-registers an evicted member,
        # InMemoryStateTracker's elasticity contract)
        self.tracker.heartbeat(rep.id)
        if rep.state == DRAINING:
            return  # mid-reload/retire: rolling_reload owns its state
        with self._lock:
            # breaker-evicted members readmit ONLY through the breaker's
            # half-open window: /readyz may well answer 200 on a replica
            # whose request path is still wedged, so an open breaker
            # outranks a healthy-looking readiness probe until reset_s
            # has elapsed
            half_open_trial = (rep.state == EVICTED
                               and rep.breaker.state != CircuitBreaker.CLOSED)
            if half_open_trial and not rep.breaker.allow_probe():
                return
        try:
            ready, payload = rep.client.readyz(
                timeout=self.probe_timeout)
        except Exception:
            if half_open_trial:
                with self._lock:
                    rep.breaker.reopen()
            return
        rep.last_ready = payload
        self._ensure_role_gauge(rep.role, rep.model_id or "default")
        self._fold_kv_summary(rep, payload)
        if ready and rep.state in (STARTING, EVICTED):
            with self._lock:
                rep.breaker.record_success()  # closes a half-open trial
            self._admit(rep)
        elif not ready:
            if half_open_trial:
                with self._lock:
                    rep.breaker.reopen()
            if rep.state in (READY, SUSPECT):
                self._evict(rep, payload.get("reason", "readiness lost"))

    # ---------------------------------------- fleet KV plane (fleetkv)
    def _fold_kv_summary(self, rep: FleetReplica,
                         payload: dict) -> None:
        """Delta one replica's cumulative ship stats (carried by its
        /readyz kv_summary) into the fleet-level counters. A replica
        restart resets its cumulative figures — a negative delta means
        exactly that, so the new figure is taken whole."""
        summary = (payload or {}).get("kv_summary")
        if not isinstance(summary, dict):
            return
        with self._lock:
            seen = rep.kv_seen or {}
            for key, counter in (
                    ("page_ships", self._m_page_ships),
                    ("ship_bytes", self._m_ship_bytes),
                    ("ship_failures", self._m_ship_failures)):
                now = int(summary.get(key, 0))
                delta = now - int(seen.get(key, 0))
                if delta < 0:
                    delta = now
                if delta > 0:
                    counter.inc(delta)
                seen[key] = now
            rep.kv_seen = seen

    def kv_summaries(self, model_id: Optional[str] = None) -> dict:
        """READY replicas' affinity summaries: {replica_id ->
        (kv_summary payload, url)}. The router's placement input
        (fleetkv.RouterAffinity.plan); replicas without a summary
        (plane off, pre-first-probe, summary chaos) simply don't
        appear — affinity degrades, routing never blocks on it.
        Prefill-role replicas never appear either: they donate pages
        through the explicit /prefill handoff, and an affinity prefer
        pointing at one would route a stream to a replica that rejects
        streams. `model_id` (when given) keeps model B's summaries
        from placing model A's prompt."""
        with self._lock:
            out = {}
            for rid, rep in self._replicas.items():
                if rep.state != READY:
                    continue
                if rep.role == "prefill":
                    continue
                if (model_id is not None
                        and (rep.model_id or "default") != model_id):
                    continue
                summary = (rep.last_ready or {}).get("kv_summary")
                if isinstance(summary, dict):
                    out[rid] = (summary, rep.client.url)
            return out

    def note_affinity(self, hit: bool) -> None:
        """Router-side placement outcome: hit = the request landed on
        the replica whose summary matched its head chunks."""
        (self._m_affinity_hits if hit
         else self._m_affinity_misses).inc()

    def _prefix_section(self, model_id: Optional[str] = None) -> dict:
        """Fleet-wide prefix-cache view for /stats: each replica's
        last-reported hit/page figures plus the fleet totals and the
        router's affinity hit rate. Figures come from the same
        kv_summary the affinity plane rides on, so a replica whose
        plane is off simply contributes zeros. `model_id` narrows the
        view to one model's replicas (the per-model /stats section);
        the affinity rate is router-global, so it only appears on the
        fleet-wide view."""
        per: Dict[str, dict] = {}
        hits = misses = pages = ships = 0
        with self._lock:
            for rid, rep in self._replicas.items():
                if (model_id is not None
                        and (rep.model_id or "default") != model_id):
                    continue
                summary = (rep.last_ready or {}).get("kv_summary")
                if not isinstance(summary, dict):
                    continue
                row = {
                    "hits": int(summary.get("hits", 0)),
                    "misses": int(summary.get("misses", 0)),
                    "pages_cached": int(summary.get("pages_cached", 0)),
                    "page_ships": int(summary.get("page_ships", 0)),
                }
                per[rid] = row
                hits += row["hits"]
                misses += row["misses"]
                pages += row["pages_cached"]
                ships += row["page_ships"]
        out = {
            "replicas": per,
            "hits": hits,
            "misses": misses,
            "pages_cached": pages,
            "page_ships": ships,
        }
        if model_id is None:
            ahits = int(self._m_affinity_hits.value)
            amisses = int(self._m_affinity_misses.value)
            placed = ahits + amisses
            out["ship_bytes"] = int(self._m_ship_bytes.value)
            out["ship_failures"] = int(self._m_ship_failures.value)
            out["affinity"] = {
                "hits": ahits,
                "misses": amisses,
                "rate": round(ahits / placed, 4) if placed else 0.0,
            }
        return out

    def _converge_target(self, rep: FleetReplica
                         ) -> Tuple[Optional[str], Optional[int]]:
        """The checkpoint identity `rep` must serve to enter rotation:
        its model's pinned (path, step) when a model-scoped
        rolling_reload promoted one, else the fleet-wide pin."""
        pinned = self.model_checkpoints.get(rep.model_id or "default")
        if pinned is not None:
            return pinned
        return self.current_checkpoint, self.current_step

    def _needs_converge(self, rep: FleetReplica) -> bool:
        """True when `rep` reports a checkpoint identity other than
        its converge target. Only armed once a rolling_reload pinned
        one (fleet-wide step, or the replica's model): before any
        promotion the fleet has no opinion on what its members
        serve."""
        if self._reload_active:
            return False  # rolling_reload is rewriting identity now
        target, step = self._converge_target(rep)
        if target is None:
            return False
        if (step is None
                and (rep.model_id or "default")
                not in self.model_checkpoints):
            return False  # fleet-wide pin needs a step to be armed
        ck = (rep.last_ready or {}).get("checkpoint") or {}
        path = ck.get("path")
        return not (path
                    and os.path.abspath(path)
                    == os.path.abspath(target)
                    and ck.get("step") == step)

    def _admit(self, rep: FleetReplica) -> None:
        if self._needs_converge(rep):
            # a newcomer (capacity-gap spawn, readmitted eviction, late
            # adoption) must not enter rotation serving anything but
            # ITS MODEL's promoted champion — THAT would be a torn
            # promotion. Bring it to the converge target first; on
            # failure it stays out of rotation and the next monitor
            # pass retries — dark beats stale.
            target, tstep = self._converge_target(rep)
            ok, info = self._reload_one(
                rep, target, tstep,
                None, ready_timeout=max(30.0, self.request_timeout))
            if not ok:
                log.warning(
                    "fleet %s: replica %s failed to converge onto "
                    "%s@%s (%s); held out of rotation", self.label,
                    rep.id, target, tstep, info.get("error"))
                return
            log.info("fleet %s: replica %s converged onto %s@%s before "
                     "admission", self.label, rep.id, target, tstep)
        with self._lock:
            was_evicted = rep.state == EVICTED
            rep.state = READY
            rep.failures = 0
            rep.admitted_at = time.time()
        if was_evicted:
            self._m_readmissions.inc()
            log.info("fleet %s: replica %s readmitted", self.label,
                     rep.id)
        self._journal_write()

    def _evict(self, rep: FleetReplica, reason: str) -> None:
        with self._lock:
            if rep.state == EVICTED:
                return
            rep.state = EVICTED
            rep.evicted_at = time.time()
            rep.eviction_reason = reason
        # removed from the registry; the next successful heartbeat
        # re-registers it (stale_workers stops naming it meanwhile)
        self.tracker.remove_worker(rep.id)
        self._m_evictions.inc()
        log.warning("fleet %s: evicting replica %s (%s)", self.label,
                    rep.id, reason)
        self._journal_write()

    def note_request_failure(self, rep: FleetReplica,
                             exc: BaseException,
                             breaker_eligible: bool = True) -> None:
        """Request-path failure feedback. Connection-level failures
        evict immediately (the process is gone — waiting out the
        heartbeat just fails more requests); HTTP-level failures only
        count (the monitor decides on readiness). A request TIMEOUT
        (socket.timeout is an OSError) means slow, not dead — ONE
        pathological request must not evict a replica that still
        answers /healthz. Instead it marks the replica SUSPECT
        (deprioritized) and feeds its circuit breaker; after
        `breaker_threshold` CONSECUTIVE timeouts the breaker opens and
        evicts the hung-but-TCP-alive member the heartbeat path cannot
        see. Readmission then goes through the breaker's half-open
        /readyz probe (`_probe`).

        `breaker_eligible=False` marks a timeout whose wait window was
        an impatient deadline SLICE, not a fair request_timeout: it
        still fails this attempt and triggers a retry, but says nothing
        reliable about the replica — a client hammering tiny
        `X-Deadline-Ms` budgets must not be able to trip breakers and
        evict healthy members."""
        opened = False
        is_timeout = isinstance(exc, TimeoutError)
        with self._lock:
            rep.failures += 1
            if is_timeout:
                self._m_timeouts.inc()
                if not breaker_eligible:
                    return
                opened = rep.breaker.record_timeout()
                if rep.state == READY:
                    rep.state = SUSPECT
        if is_timeout:
            if opened:
                self._m_breaker_opens.inc()
                self._evict(rep, "circuit breaker open after "
                            f"{rep.breaker.threshold} consecutive "
                            "request timeouts")
        elif isinstance(exc, OSError):
            self._evict(rep, f"connection failure: {exc}")

    def note_request_success(self, rep: FleetReplica) -> None:
        """A completed request closes the replica's breaker and clears
        a SUSPECT verdict — suspicion is about request progress, and
        the request just progressed."""
        with self._lock:
            rep.failures = 0
            rep.breaker.record_success()
            if rep.state == SUSPECT:
                rep.state = READY

    # ------------------------------------------------------- dispatch
    def ready_replicas(self) -> List[FleetReplica]:
        with self._lock:
            return [r for r in self._replicas.values()
                    if r.state == READY]

    def ready_count(self) -> int:
        return len(self.ready_replicas())

    def wait_ready(self, n: int = 1, timeout: float = 120.0) -> None:
        """Block until >= n replicas are READY (spin-up gate)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.ready_count() >= n:
                return
            time.sleep(min(0.05, self.heartbeat_interval))
        raise TimeoutError(
            f"only {self.ready_count()}/{n} replicas ready after "
            f"{timeout}s: {self.state_counts()}")

    def total_outstanding(self) -> int:
        with self._lock:
            return sum(r.outstanding for r in self._replicas.values())

    def state_counts(self) -> Dict[str, int]:
        with self._lock:
            counts = {s: 0 for s in STATES}
            for r in self._replicas.values():
                counts[r.state] += 1
            return counts

    def breaker_counts(self) -> Dict[str, int]:
        with self._lock:
            counts = {CircuitBreaker.CLOSED: 0,
                      CircuitBreaker.HALF_OPEN: 0,
                      CircuitBreaker.OPEN: 0}
            for r in self._replicas.values():
                counts[r.breaker.state] += 1
            return counts

    def utilization(self) -> float:
        """Fleet load normalized to its shed capacity: outstanding /
        shed_high_water when a mark is set (1.0 = shedding), else mean
        outstanding per ready replica. The bench's "batch soaks idle
        capacity" gauge (docs/OBSERVABILITY.md)."""
        total = self.total_outstanding()
        if self.shed_high_water:
            return total / float(self.shed_high_water)
        return total / float(max(1, self.ready_count()))

    def batch_backlog(self) -> int:
        """Batch-tier work parked on this fleet: bulk streams in
        flight or queued behind replica admission (the router holds a
        batch stream open while its rows wait for slots, so in-flight
        IS the backlog). The autoscaler's batch signal."""
        with self._lock:
            return self._tier_inflight[TIER_BATCH]

    # --------------------------------------- roles & models (disagg)
    def _ensure_role_gauge(self, role: str, model: str) -> None:
        """Register the dl4j_fleet_role_replicas{role=,model=} gauge
        child at first sight of a (role, model) pair — the series are
        discovered from /readyz payloads, never pre-declared."""
        key = (role, model)
        with self._lock:
            if key in self._role_gauge_keys:
                return
            self._role_gauge_keys.add(key)
        ref = weakref.ref(self)
        telemetry.get_registry().gauge(
            "dl4j_fleet_role_replicas",
            "READY fleet replicas by disaggregated role and model "
            '(docs/FLEET.md "Disaggregated roles")').labels(
                role=role, model=model, fleet=self.label).set_function(
            (lambda rl, m: lambda: (
                (lambda o: o.role_model_count(rl, m) if o else 0)(
                    ref())))(role, model))

    def role_model_count(self, role: str, model: str) -> int:
        with self._lock:
            return sum(1 for r in self._replicas.values()
                       if r.state == READY and r.role == role
                       and (r.model_id or "default") == model)

    def role_counts(self, model_id: Optional[str] = None
                    ) -> Dict[str, int]:
        """READY replicas by role (optionally one model's) — the
        router's cheap "does this fleet have a prefill pool" check."""
        with self._lock:
            counts: Dict[str, int] = {}
            for r in self._replicas.values():
                if r.state != READY:
                    continue
                if (model_id is not None
                        and (r.model_id or "default") != model_id):
                    continue
                counts[r.role] = counts.get(r.role, 0) + 1
            return counts

    @staticmethod
    def _routable(rep: FleetReplica, role: Optional[str],
                  model_id: Optional[str]) -> bool:
        """Role/model admission filter (docs/FLEET.md "Disaggregated
        roles"). `role=None` means a STREAM-capable replica — unified
        or decode: a prefill-role replica never serves /predict or
        /generate, so it is excluded unless explicitly requested with
        role="prefill". Any non-prefill role is satisfied by a
        unified replica (the default deployment IS the unified pool).
        `model_id=None` skips model filtering (single-model fleets);
        otherwise replicas that announce no model count as
        "default"."""
        rrole = rep.role
        if role is None:
            if rrole == "prefill":
                return False
        elif role == "prefill":
            if rrole != "prefill":
                return False
        elif rrole not in (role, "unified"):
            return False
        if (model_id is not None
                and (rep.model_id or "default") != model_id):
            return False
        return True

    def select(self, route: str = "predict",
               exclude: Sequence[str] = (),
               tier: str = TIER_INTERACTIVE,
               count: bool = True,
               prefer: Optional[str] = None,
               prefer_slack: int = 4,
               role: Optional[str] = None,
               model_id: Optional[str] = None) -> FleetReplica:
        """Least-outstanding READY replica (round-robin tiebreak) —
        the ReplicaSet policy lifted across processes. SUSPECT
        replicas (recent request timeouts, breaker not yet open) stay
        in the pool but rank AFTER any equally-loaded READY peer:
        under load their in-flight hangs pile up `outstanding` so real
        traffic skews to healthy members, while the trickle they still
        receive is exactly what either clears the suspicion (a
        success) or trips the breaker (N consecutive timeouts) — a
        suspect starved of all traffic could never resolve either way.
        Under idle/sequential traffic even the deprioritized rank would
        starve a suspect (every peer sits at outstanding 0), so
        suspicion additionally DECAYS back to READY after a quiet
        `breaker_reset_s` — the replica re-enters the tiebreak rotation
        and the next request delivers the breaker its verdict either
        way. Sheds with OverloadedError past the global high-water
        mark — and the BATCH tier additionally past its own, lower
        `batch_high_water`, with Retry-After derived from the shed
        tier's backlog. Raises NoReadyReplicas when nothing is
        admittable. The caller owns `release(rep, tier)` (same tier).

        `prefer` names a replica the fleet KV plane wants this request
        on (prefix affinity / consistent-hash placement —
        serving/fleetkv.py). It is a PREFERENCE with strict bounds:
        honored only when the target is READY (never SUSPECT — a
        suspect must not attract a convoy of its favorite prefix), not
        excluded, and within `prefer_slack` outstanding requests of
        the least-loaded candidate. Every shed above still fires
        first; when the preference loses, selection falls back to the
        least-outstanding policy unchanged.

        `role`/`model_id` scope the candidate pool for a disaggregated
        or multi-model fleet (`_routable`): the default role=None
        excludes prefill-role replicas — a generate stream or predict
        must NEVER land on one — and role="prefill" is how the router
        dispatches the handoff's prefill leg. The `prefer` hint passes
        through the same filter by construction (it is resolved inside
        the filtered candidate set), so an affinity plan can never
        override the role/model fence."""
        if tier not in TIERS:
            raise ValueError(
                f"unknown tier {tier!r} (expected one of {TIERS})")
        with self._lock:
            now = time.monotonic()
            for r in self._replicas.values():
                if (r.state == SUSPECT
                        and r.breaker.last_timeout_at is not None
                        and now - r.breaker.last_timeout_at
                        >= r.breaker.reset_s):
                    # decay does NOT reset the consecutive-timeout
                    # streak: only a completed request proves progress
                    r.state = READY
            ids = list(self._replicas)
            ready = [r for r in self._replicas.values()
                     if r.state in (READY, SUSPECT)
                     and r.id not in exclude
                     and self._routable(r, role, model_id)]
            if not ready:
                raise NoReadyReplicas(
                    f"no ready replica for role="
                    f"{role or 'unified/decode'} model="
                    f"{model_id or 'any'} "
                    f"(states: {self.state_counts()})")
            total = sum(r.outstanding
                        for r in self._replicas.values())
            if (tier == TIER_BATCH and self.batch_high_water is not None
                    and total >= self.batch_high_water):
                # the bulk lane sheds FIRST, while interactive
                # admission still has headroom up to the global mark
                self._m_shed[route].inc()
                self._m_tier_shed[TIER_BATCH].inc()
                raise OverloadedError(
                    f"fleet batch lane at high-water mark ({total} in "
                    f"flight >= {self.batch_high_water})",
                    retry_after_ms=backlog_retry_ms(
                        self._tier_inflight[TIER_BATCH] + 1,
                        _TIER_ITEM_MS[TIER_BATCH]),
                    tier=TIER_BATCH)
            if (self.shed_high_water is not None
                    and total >= self.shed_high_water):
                self._m_shed[route].inc()
                self._m_tier_shed[tier].inc()
                raise OverloadedError(
                    f"fleet at high-water mark ({total} in flight "
                    f">= {self.shed_high_water})",
                    retry_after_ms=backlog_retry_ms(
                        self._tier_inflight[tier] + 1,
                        _TIER_ITEM_MS[tier]),
                    tier=tier)
            n = len(ids)
            best = None
            if prefer is not None:
                cand = next((r for r in ready
                             if r.id == prefer and r.state == READY),
                            None)
                if cand is not None:
                    floor = min(r.outstanding for r in ready)
                    if cand.outstanding - floor <= prefer_slack:
                        best = cand
            if best is None:
                best = min(ready, key=lambda r: (
                    r.outstanding, r.state == SUSPECT,
                    (ids.index(r.id) - self._rr) % n))
            self._rr = (ids.index(best.id) + 1) % n
            best.outstanding += 1
            self._tier_inflight[tier] += 1
            if not exclude and count:
                # first attempt only: a retried client request counts
                # ONCE in dl4j_fleet_requests (retries have their own
                # counter, retry attempts carry a non-empty exclude
                # set by construction, and preemption re-admissions
                # pass count=False — same client request)
                self._m_requests[route].inc()
                self._m_tier_requests[tier].inc()
            return best

    def release(self, rep: FleetReplica,
                tier: str = TIER_INTERACTIVE) -> None:
        """Return a `select`ed replica; `tier` must match the select
        call so per-tier in-flight accounting balances."""
        with self._lock:
            rep.outstanding -= 1
            self._tier_inflight[tier] -= 1

    def observe(self, route: str, seconds: float,
                tier: Optional[str] = None) -> None:
        self._m_latency[route].observe(seconds)
        if tier is not None:
            self._m_tier_latency[tier].observe(seconds)

    def forward_predict(self, body: bytes,
                        deadline: Optional[Deadline] = None,
                        tier: str = TIER_INTERACTIVE,
                        model_id: Optional[str] = None
                        ) -> Tuple[int, dict, bytes]:
        """Route one /predict: least-loaded replica, transparent retry
        on a healthy peer after connection failures, request timeouts,
        or replica 5xx (idempotent, so at-least-once is safe) — under
        the fleet's explicit `retry_budget`. With a `deadline`, each
        hop's socket timeout is a SLICE of the remaining budget
        (remaining / attempts-left, capped by request_timeout) so a
        hung replica spends one slice and leaves room to retry, and
        the shrunk budget is forwarded downstream as `X-Deadline-Ms`.
        The SLO `tier` gates admission (batch sheds at its own mark)
        and is forwarded as `X-Priority` so the replica's batcher
        applies its tiered queue bound too. Returns (status, headers,
        body) from the replica that answered."""
        start = time.perf_counter()
        tried: set = set()
        last_5xx: Optional[Tuple[int, dict, bytes]] = None
        last_err: Optional[BaseException] = None
        try:
            if deadline is not None and deadline.expired:
                # shed before any replica is touched: machine-readable
                # 504, no compute anywhere
                self._m_deadline["predict"].inc()
                deadline.check("router dispatch")
            with self._lock:
                attempts = max(1, min(len(self._replicas),
                                      1 + self.retry_budget))
            for attempt in range(attempts):
                if deadline is not None and deadline.expired:
                    self._m_deadline["predict"].inc()
                    deadline.check("router retry")
                try:
                    rep = self.select(route="predict", exclude=tried,
                                      tier=tier, model_id=model_id)
                except NoReadyReplicas:
                    break  # fall through to best-effort answer below
                if tried:
                    # a retry is an attempt actually MADE on a peer
                    # after a failure, not the failure itself
                    self._m_retries.inc()
                if deadline is None:
                    hop_timeout = self.request_timeout
                    headers = {}
                else:
                    hop_timeout = max(0.05, min(
                        self.request_timeout,
                        deadline.remaining_s() / (attempts - attempt)))
                    # forward the HOP's own window, not the whole
                    # remaining budget: once the router stops waiting
                    # and replays on a peer, the first replica's
                    # admission gates shed the abandoned work instead
                    # of computing an answer nobody will read
                    headers = {DEADLINE_HEADER:
                               str(max(1, int(hop_timeout * 1000)))}
                if tier != TIER_INTERACTIVE:
                    headers[PRIORITY_HEADER] = tier
                headers = headers or None
                # a timeout at a deadline-sliced window shorter than a
                # fair request_timeout says the CLIENT was impatient,
                # not that the replica hung — it must not feed the
                # breaker (min() with probe_timeout keeps short
                # explicitly-configured request_timeouts eligible)
                fair_window = min(self.request_timeout,
                                  self.probe_timeout)
                try:
                    status, hdrs, data = rep.client.request(
                        "POST", "/predict", body,
                        timeout=hop_timeout, headers=headers)
                except Exception as e:
                    self.note_request_failure(
                        rep, e,
                        breaker_eligible=hop_timeout >= fair_window)
                    tried.add(rep.id)
                    last_err = e
                    continue
                finally:
                    self.release(rep, tier)
                if status >= 500:
                    # replica answered but failed/shed: try a peer,
                    # keep the reply in case every peer does the same
                    tried.add(rep.id)
                    last_5xx = (status, hdrs, data)
                    continue
                self.note_request_success(rep)
                return status, hdrs, data
            if last_5xx is not None:
                return last_5xx
            raise NoReadyReplicas(
                "every ready replica failed /predict"
                + (f" (last error: {last_err})" if last_err else ""))
        finally:
            self.observe("predict", time.perf_counter() - start,
                         tier=tier)

    # --------------------------------------------------- rolling reload
    def _drain(self, rep: FleetReplica, timeout: float) -> bool:
        """Wait for a DRAINING replica's in-flight requests to land."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if rep.outstanding == 0:
                    return True
            time.sleep(0.01)
        return False

    def _reload_one(self, rep: FleetReplica, path: str,
                    step: Optional[int], probe: Optional[dict],
                    ready_timeout: float) -> Tuple[bool, dict]:
        """Reload one drained replica and probe it back to readiness.
        Returns (ok, info); info["weights_changed"] says whether the
        replica now holds the NEW checkpoint (reload-stage failures
        keep the old weights — the engine's validated atomic swap)."""
        payload = {"path": path}
        if step is not None:
            payload["step"] = step
        try:
            status, _, data = rep.client.request(
                "POST", "/reload", json.dumps(payload).encode(),
                timeout=self.request_timeout)
        except Exception as e:
            return False, {"stage": "reload", "weights_changed": False,
                           "error": f"{type(e).__name__}: {e}"}
        if status != 200:
            return False, {"stage": "reload", "weights_changed": False,
                           "status": status,
                           "error": data.decode(errors="replace")}
        # readiness probe: the reload may have cost compile/cache state
        deadline = time.monotonic() + ready_timeout
        ready = False
        while time.monotonic() < deadline:
            try:
                ready, ready_payload = rep.client.readyz(
                    timeout=self.probe_timeout)
            except Exception:
                ready = False
            else:
                # refresh the identity snapshot NOW — journal/stats
                # must show the reloaded checkpoint without waiting a
                # heartbeat (the deployment controller reads this)
                rep.last_ready = ready_payload
            if ready:
                break
            time.sleep(0.05)
        if not ready:
            return False, {"stage": "readyz", "weights_changed": True,
                           "error": f"not ready within {ready_timeout}s"}
        if probe is not None:
            try:
                status, _, data = rep.client.request(
                    "POST", "/predict", json.dumps(probe).encode(),
                    timeout=self.request_timeout)
            except Exception as e:
                return False, {"stage": "probe",
                               "weights_changed": True,
                               "error": f"{type(e).__name__}: {e}"}
            if status != 200:
                return False, {"stage": "probe",
                               "weights_changed": True,
                               "status": status,
                               "error": data.decode(errors="replace")}
        return True, {"weights_changed": True}

    def rolling_reload(self, path: str, step: Optional[int] = None,
                       rollback_path: Optional[str] = None,
                       rollback_step: Optional[int] = None,
                       probe: Optional[dict] = None,
                       drain_timeout: float = 30.0,
                       ready_timeout: float = 120.0,
                       model_id: Optional[str] = None) -> dict:
        """Orchestrate `POST /reload` across the fleet with zero
        downtime: one replica at a time — drain (stop routing to it,
        wait out its in-flight requests), reload, `/readyz`-probe
        (plus the optional `/predict` validation `probe`), readmit.
        The FIRST replica is the canary: if it fails validation, the
        reload aborts and every replica already moved to the new
        checkpoint rolls back to `rollback_path` (default: the
        checkpoint the fleet was serving) — the fleet never stays
        mixed. Requests in flight elsewhere are untouched throughout,
        and each replica's own swap is atomic, so no response ever
        mixes old and new weights.

        `model_id` scopes the reload to ONE model's replicas in a
        multi-model fleet (every role pool of that model; the others
        keep serving untouched) and pins the promoted identity in
        `model_checkpoints[model_id]` — the per-model convergence
        target newcomers of that model must reach before admission.
        The default rollback target is then that model's previously
        pinned checkpoint, not the fleet-wide one."""
        if not self._reload_lock.acquire(blocking=False):
            raise OverloadedError(
                "a rolling reload is already in progress",
                retry_after_ms=5000)
        self._reload_active = True
        try:
            # SUSPECT replicas route traffic too (select() admits
            # them), so they MUST be reloaded — skipping one would
            # leave it serving the old checkpoint indefinitely
            with self._lock:
                targets = [r for r in self._replicas.values()
                           if r.state in (READY, SUSPECT)
                           and (model_id is None
                                or (r.model_id or "default")
                                == model_id)]
            if not targets:
                raise NoReadyReplicas(
                    "no ready replicas to reload"
                    + (f" for model {model_id!r}" if model_id else ""))
            if rollback_path is not None:
                rollback = rollback_path
            elif (model_id is not None
                  and model_id in self.model_checkpoints):
                rollback, pinned_step = self.model_checkpoints[model_id]
                if rollback_step is None:
                    rollback_step = pinned_step
            else:
                rollback = self.current_checkpoint
            done: List[str] = []
            for i, rep in enumerate(targets):
                with self._lock:
                    rep.state = DRAINING
                drained = self._drain(rep, drain_timeout)
                ok, info = self._reload_one(rep, path, step, probe,
                                            ready_timeout)
                if ok:
                    with self._lock:
                        rep.state = READY
                    done.append(rep.id)
                    continue
                # ---- failure: canary (or later member) — roll back
                result = {
                    "reloaded": False, "path": path,
                    "failed_replica": rep.id, "canary": i == 0,
                    "drained": drained, "error": info,
                    "completed_before_failure": list(done),
                }
                if model_id is not None:
                    result["model_id"] = model_id
                to_roll = list(done)
                if info.get("weights_changed"):
                    to_roll.append(rep.id)
                elif self._replica_alive(rep):
                    # reload-stage failure kept the OLD weights: the
                    # replica is still consistent — readmit it
                    with self._lock:
                        rep.state = READY
                else:
                    self._evict(rep, "failed during rolling reload")
                rolled, roll_failed = self._roll_back(
                    to_roll, rollback, rollback_step,
                    drain_timeout, ready_timeout)
                result["rollback_path"] = rollback
                result["rolled_back"] = rolled
                result["rollback_failed"] = roll_failed
                outcome = ("rolled_back"
                           if not roll_failed and (rolled or not to_roll)
                           else "failed")
                self._m_reloads[outcome].inc()
                return result
            if model_id is None:
                self.current_checkpoint = path
                self.current_step = step
            else:
                self.model_checkpoints[model_id] = (path, step)
            self._m_reloads["ok"].inc()
            self._journal_write()  # the serving checkpoint is journaled
            # state: a restarted router must know the rollback target
            out = {"reloaded": True, "path": path, "step": step,
                   "replicas": done}
            if model_id is not None:
                out["model_id"] = model_id
            return out
        finally:
            self._reload_active = False
            self._reload_lock.release()

    def _roll_back(self, replica_ids: List[str],
                   rollback: Optional[str], rollback_step: Optional[int],
                   drain_timeout: float, ready_timeout: float
                   ) -> Tuple[List[str], List[str]]:
        """Reload members back onto the previously-serving checkpoint.
        The validation probe is NOT re-run here: the rollback target
        already served validated traffic, and a probe built to catch
        the NEW checkpoint failing must not strand the rollback."""
        rolled: List[str] = []
        failed: List[str] = []
        if rollback is None:
            # nowhere to roll back to: members on the new checkpoint
            # leave rotation rather than serving mixed weights
            for rid in replica_ids:
                with self._lock:
                    rep = self._replicas.get(rid)
                if rep is not None:
                    self._evict(rep, "mixed weights, no rollback path")
                failed.append(rid)
            return rolled, failed
        for rid in replica_ids:
            with self._lock:
                rep = self._replicas.get(rid)
            if rep is None:
                continue
            with self._lock:
                rep.state = DRAINING
            self._drain(rep, drain_timeout)
            ok, _ = self._reload_one(rep, rollback, rollback_step,
                                     None, ready_timeout)
            if ok:
                with self._lock:
                    rep.state = READY
                rolled.append(rid)
            else:
                self._evict(rep, "rollback reload failed")
                failed.append(rid)
        return rolled, failed

    def _replica_alive(self, rep: FleetReplica) -> bool:
        try:
            rep.client.healthz(timeout=self.probe_timeout)
            return True
        except Exception:
            return False

    # ------------------------------------------------------ autoscaling
    def add_pool(self, *, model_id: str = "default",
                 role: str = "unified",
                 spawner: Optional[ReplicaSpawner] = None,
                 autoscaler: Optional[Autoscaler] = None) -> None:
        """Register a (model, role) replica pool for pool-scoped
        autoscaling (docs/FLEET.md "Disaggregated roles"):
        `autoscale_tick` then sizes each registered pool independently
        between ITS autoscaler's min/max using ITS spawner — whose
        serve_args bake in the matching `--role`/`--model-id` — so
        per-role AND per-model floors/ceilings hold on one fleet. With
        no pools registered the legacy single-pool fleet-level signal
        runs unchanged. `spawner=None` falls back to the fleet
        spawner; `autoscaler=None` registers the pool for placement
        bookkeeping only (spawn_pool still works)."""
        with self._lock:
            self._pools[(model_id, role)] = {
                "spawner": (spawner if spawner is not None
                            else self.spawner),
                "autoscaler": autoscaler,
            }

    def spawn_pool(self, model_id: str, role: str,
                   n: int = 1) -> List[FleetReplica]:
        """Spawn n replicas into a registered (model, role) pool and
        stamp their pool membership (STARTING members have no
        announced identity yet — the stamp is what attributes them to
        the right pool's autoscaler)."""
        with self._lock:
            pool = self._pools.get((model_id, role))
        spawner = (pool or {}).get("spawner") or self.spawner
        if spawner is None:
            raise RuntimeError(
                f"no spawner for pool ({model_id!r}, {role!r})")
        out = []
        for _ in range(n):
            proc, url = spawner.spawn()
            rep = self.attach(url, proc=proc, spawned=True)
            rep.pool = (model_id, role)
            out.append(rep)
            self._m_spawned.inc()
        return out

    def _pool_members(self, model: str, role: str
                      ) -> List[FleetReplica]:
        """Non-evicted replicas belonging to a (model, role) pool: by
        spawn stamp when present, else by announced identity (caller
        holds the lock)."""
        out = []
        for r in self._replicas.values():
            if r.state == EVICTED:
                continue
            if r.pool is not None:
                if r.pool == (model, role):
                    out.append(r)
            elif (r.role == role
                  and (r.model_id or "default") == model):
                out.append(r)
        return out

    def _autoscale_pools(self) -> int:
        """One pool-scoped autoscale pass: each registered pool's
        queue-depth signal is computed over ITS members only, and
        spawn/retire act through ITS spawner. Returns the net delta."""
        applied = 0
        with self._lock:
            pools = list(self._pools.items())
        for (model, role), pool in pools:
            scaler = pool.get("autoscaler")
            if scaler is None:
                continue
            with self._lock:
                members = self._pool_members(model, role)
                live = [r for r in members
                        if r.state in (READY, SUSPECT, STARTING)]
                outstanding = sum(r.outstanding for r in members)
            delta = scaler.decide(len(live), outstanding)
            if delta > 0:
                self.spawn_pool(model, role, 1)
                scaler.note_action()
                applied += 1
            elif delta < 0:
                ready = [r for r in live
                         if r.state == READY and r.spawned]
                if ready:
                    victim = min(ready, key=lambda r: r.outstanding)
                    self.retire(victim.id)
                    scaler.note_action()
                    applied -= 1
        return applied

    def autoscale_tick(self) -> int:
        """Apply one autoscaler decision; returns the delta applied.
        With registered pools (add_pool) the pass is pool-scoped; the
        legacy fleet-level signal runs otherwise."""
        if self._reload_active:
            return 0  # never resize mid-reload
        if self._pools:
            return self._autoscale_pools()
        if self.autoscaler is None or self.spawner is None:
            return 0
        with self._lock:
            live = [r for r in self._replicas.values()
                    if r.state in (READY, SUSPECT, STARTING)]
            outstanding = sum(r.outstanding
                              for r in self._replicas.values())
            batch_backlog = self._tier_inflight[TIER_BATCH]
        delta = self.autoscaler.decide(len(live), outstanding,
                                       batch_backlog=batch_backlog)
        if delta > 0:
            self.spawn(1)
            self.autoscaler.note_action()
            return 1
        if delta < 0:
            ready = [r for r in live if r.state == READY and r.spawned]
            if not ready:
                return 0
            victim = min(ready, key=lambda r: r.outstanding)
            self.retire(victim.id)
            self.autoscaler.note_action()
            return -1
        return 0

    # --------------------------------------------------- observability
    def snapshot(self) -> dict:
        now = time.time()
        with self._lock:
            reps = {rid: r.snapshot(now)
                    for rid, r in self._replicas.items()}
        heartbeats = self.tracker.heartbeats()
        for rid, hb in heartbeats.items():
            if rid in reps:
                reps[rid]["heartbeat_age_s"] = round(now - hb, 3)
        # per-checkpoint-identity aggregation: "path@step" -> [rids].
        # The deployment controller's torn-promotion gate reads this
        # off the router's /stats — a converged fleet shows exactly one
        # identity key across its READY replicas (docs/PIPELINE.md)
        served: Dict[str, list] = {}
        # per-model aggregation (docs/FLEET.md "Disaggregated roles"):
        # one router, N models — each model's role pools, served
        # checkpoints, and prefix-cache view keyed by model_id (the
        # multi-model /stats section the deployment controller and the
        # cross-model isolation drill read)
        models: Dict[str, dict] = {}
        for rid, r in sorted(reps.items()):
            if r.get("state") == EVICTED:
                continue  # not serving: a stale identity is not "served"
            ck = r.get("checkpoint")
            key = (f"{ck.get('path')}@{ck.get('step')}" if ck else "none")
            served.setdefault(key, []).append(rid)
            m = r.get("model_id") or "default"
            sec = models.setdefault(
                m, {"replicas": [], "roles": {},
                    "checkpoints_served": {}})
            sec["replicas"].append(rid)
            ro = r.get("role") or "unified"
            sec["roles"][ro] = sec["roles"].get(ro, 0) + 1
            sec["checkpoints_served"].setdefault(key, []).append(rid)
        for m, sec in models.items():
            sec["prefix_cache"] = self._prefix_section(model_id=m)
            pinned = self.model_checkpoints.get(m)
            if pinned is not None:
                sec["current_checkpoint"] = pinned[0]
                sec["current_step"] = pinned[1]
        return {
            "replicas": reps,
            "checkpoints_served": served,
            "roles": self.role_counts(),
            "models": models,
            "states": self.state_counts(),
            "breakers": self.breaker_counts(),
            "outstanding": self.total_outstanding(),
            "incarnation": self.incarnation,
            "state_dir": self.state_dir,
            "adoptions": list(self.adoption_events),
            "shed_high_water": self.shed_high_water,
            "current_checkpoint": self.current_checkpoint,
            "current_step": self.current_step,
            "rolling_reload_active": self._reload_active,
            "retry_budget": self.retry_budget,
            "requests": {route: int(c.value)
                         for route, c in self._m_requests.items()},
            "retries": int(self._m_retries.value),
            "stream_resume_attempts": self.stream_resume_attempts,
            "stream_resumes": int(self._m_stream_resumes.value),
            "stream_resume_failures": int(
                self._m_stream_resume_failures.value),
            "stream_tokens_replayed": int(
                self._m_stream_tokens_replayed.value),
            "stream_tokens_deduped": int(
                self._m_stream_tokens_deduped.value),
            "disagg": {
                "handoffs": int(self._m_disagg_handoffs.value),
                "handoff_bytes": int(
                    self._m_disagg_handoff_bytes.value),
                "handoff_failures": int(
                    self._m_disagg_handoff_failures.value),
                "fallbacks": int(self._m_disagg_fallbacks.value),
            },
            "request_timeouts": int(self._m_timeouts.value),
            "breaker_opens": int(self._m_breaker_opens.value),
            "deadline_exceeded": {route: int(c.value)
                                  for route, c in
                                  self._m_deadline.items()},
            "shed": {route: int(c.value)
                     for route, c in self._m_shed.items()},
            "tiers": {
                "batch_high_water": self.batch_high_water,
                "inflight": {t: self._tier_inflight[t] for t in TIERS},
                "requests": {t: int(c.value) for t, c
                             in self._m_tier_requests.items()},
                "shed": {t: int(c.value) for t, c
                         in self._m_tier_shed.items()},
                "preempt_resumes": int(self._m_preempt_resumes.value),
                "utilization": round(self.utilization(), 4),
            },
            "prefix_cache": self._prefix_section(),
            "evictions": int(self._m_evictions.value),
            "readmissions": int(self._m_readmissions.value),
            "reloads": {outcome: int(c.value)
                        for outcome, c in self._m_reloads.items()},
            "spawned": int(self._m_spawned.value),
            "retired": int(self._m_retired.value),
            "autoscaler": (None if self.autoscaler is None else {
                "min_replicas": self.autoscaler.min_replicas,
                "max_replicas": self.autoscaler.max_replicas,
                "scale_up_at": self.autoscaler.scale_up_at,
                "scale_down_at": self.autoscaler.scale_down_at,
                "batch_backlog_up_at":
                    self.autoscaler.batch_backlog_up_at,
            }),
        }
