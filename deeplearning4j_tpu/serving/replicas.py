"""Multi-replica dispatch: one engine per local device, round-robin.

A single engine serializes on its device. For a host with several
accelerator chips (or the 8-device virtual CPU mesh the tests run on),
`ReplicaSet` clones the params onto each device as an independent
`InferenceEngine` and round-robins requests across them — each replica
compiles its own bucket programs once, and a shared `MicroBatcher` can
sit in front so coalesced batches fan out over chips.

This is intra-host scale-out; cross-host serving stacks the scaleout/
runtime on top (each host runs its own replica set).
"""

from __future__ import annotations

import itertools
import threading
from typing import List, Optional, Sequence

from deeplearning4j_tpu.serving.batcher import MicroBatcher
from deeplearning4j_tpu.serving.engine import InferenceEngine

__all__ = ["ReplicaSet"]


class ReplicaSet:
    def __init__(self, engines: Sequence[InferenceEngine]):
        if not engines:
            raise ValueError("ReplicaSet needs at least one engine")
        self.engines: List[InferenceEngine] = list(engines)
        self._rr = itertools.cycle(self.engines)
        self._gen_rr = 0  # separate cursor for generate_stream dispatch
        self._lock = threading.Lock()

    @classmethod
    def for_network(cls, net, n_replicas: Optional[int] = None,
                    devices=None, **engine_kw) -> "ReplicaSet":
        """One engine per local device (params device_put to each);
        `n_replicas` caps how many devices are used."""
        import jax

        if devices is None:
            devices = jax.local_devices()
        if n_replicas is not None:
            if n_replicas < 1:
                raise ValueError(
                    f"n_replicas must be >= 1, got {n_replicas}")
            devices = devices[:n_replicas]
        return cls([InferenceEngine.for_network(net, device=d, **engine_kw)
                    for d in devices])

    def _next(self) -> InferenceEngine:
        with self._lock:
            return next(self._rr)

    # --------------------------------------------------------- dispatch
    def infer(self, x):
        return self._next().infer(x)

    def generate(self, prompt, n_tokens: int):
        """Per-request compiled-scan decode on the next replica (the
        legacy path; concurrent generate traffic belongs on
        `generate_stream` — the slot scheduler is its own batcher)."""
        return self._next().generate(prompt, n_tokens)

    def generate_stream(self, prompt, max_tokens: int, eos_id=None):
        """Submit one prompt to a replica's continuous-batching decode
        loop (round-robin over the replicas that run one). Each loop
        slot-schedules its own streams, so this fans concurrent
        generate traffic across chips without coalescing delays."""
        with self._lock:
            loops = [e for e in self.engines if e.decode_loop is not None]
            if not loops:
                raise ValueError(
                    "no replica runs a decode loop (construct engines "
                    "with decode_slots= or call start_decode_loop)")
            engine = loops[self._gen_rr % len(loops)]
            self._gen_rr += 1
        return engine.generate_stream(prompt, max_tokens, eos_id)

    def warmup(self, feature_shape, **kw) -> None:
        for engine in self.engines:
            engine.warmup(feature_shape, **kw)

    def batcher(self, **kw) -> MicroBatcher:
        """A shared micro-batcher whose coalesced batches round-robin
        over the replicas."""
        return MicroBatcher(self.infer, **kw)

    # --------------------------------------------------------- hot reload
    def load_params(self, params) -> None:
        """Swap every replica's weights (each engine validates shapes
        and swaps atomically — in-flight requests finish on the old
        params; see InferenceEngine.load_params)."""
        for engine in self.engines:
            engine.load_params(params)

    def load_checkpoint(self, path: str, step: Optional[int] = None) -> dict:
        """Hot-reload all replicas from a checkpoint — a sharded
        directory (deeplearning4j_tpu.checkpoint) or a legacy single-file
        npz — without dropping in-flight requests. The checkpoint's
        params tree must match the serving model's architecture (the
        per-leaf validation errors name the first mismatched leaf).
        Returns the checkpoint's info dict (step/cursor/metadata)."""
        import os

        if os.path.isdir(path):
            from deeplearning4j_tpu.checkpoint import restore_network

            net, info = restore_network(path, step)
        else:
            if step is not None:
                # a single-file checkpoint holds exactly one state —
                # silently serving it against an explicit step pin would
                # defeat a rollback-to-step intent
                raise ValueError(
                    f"step={step} was requested but {path!r} is a "
                    "single-file checkpoint with no steps; point at a "
                    "sharded checkpoint directory to pin a step")
            from deeplearning4j_tpu.scaleout.checkpoint import \
                load_checkpoint

            net, info = load_checkpoint(path)
        self.load_params(net.param_table)
        return info

    # ---------------------------------------------------- observability
    def program_cache_size(self) -> int:
        sizes = [e.program_cache_size() for e in self.engines]
        return -1 if any(s < 0 for s in sizes) else sum(sizes)

    def snapshot(self) -> dict:
        reps = [e.snapshot() for e in self.engines]
        buckets: dict = {}
        for r in reps:
            for b, c in r.get("bucket_forwards", {}).items():
                buckets[b] = buckets.get(b, 0) + c
        return {
            "replicas": len(self.engines),
            "requests": sum(r["requests"] for r in reps),
            "rows": sum(r["rows"] for r in reps),
            "errors": sum(r["errors"] for r in reps),
            "compiled_programs": self.program_cache_size(),
            # aggregated per-bucket forward counts across replicas
            "bucket_forwards": {str(b): buckets[b]
                                for b in sorted(buckets)},
            "per_replica": reps,
        }
