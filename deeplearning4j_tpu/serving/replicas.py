"""Multi-replica dispatch: one engine per local device, least-loaded.

A single engine serializes on its device. For a host with several
accelerator chips (or the 8-device virtual CPU mesh the tests run on),
`ReplicaSet` clones the params onto each device as an independent
`InferenceEngine` and dispatches requests across them — each replica
compiles its own bucket programs once, and a shared `MicroBatcher` can
sit in front so coalesced batches fan out over chips.

Dispatch policy: **least outstanding requests**, with round-robin as
the tiebreak. Blind round-robin behind a coalescing batcher is fine
when every forward costs the same, but ragged buckets don't — a replica
stuck on a top-bucket forward keeps receiving work it can't start. The
set tracks per-engine in-flight counts under ONE lock; `infer`,
`generate`, and `generate_stream` all select through the same locked
helper (the decode-loop cursor shares the lock discipline rather than
keeping its own), so the idle-replica-first property holds across both
traffic classes. On a uniform idle stream the tiebreak degenerates to
exact round-robin — the historical behavior, still pinned by tests.

This is intra-host scale-out; cross-host serving stacks the fleet
router (`serving/fleet.py`, docs/FLEET.md) on top — each host runs its
own replica set behind one `serve_network` endpoint.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import List, Optional, Sequence

from deeplearning4j_tpu.serving.batcher import MicroBatcher
from deeplearning4j_tpu.serving.engine import InferenceEngine

__all__ = ["ReplicaSet"]


class ReplicaSet:
    def __init__(self, engines: Sequence[InferenceEngine]):
        if not engines:
            raise ValueError("ReplicaSet needs at least one engine")
        self.engines: List[InferenceEngine] = list(engines)
        self._lock = threading.Lock()
        self._rr = 0  # tiebreak cursor, shared by ALL dispatch paths
        self._outstanding = [0] * len(self.engines)

    @classmethod
    def for_network(cls, net, n_replicas: Optional[int] = None,
                    devices=None, **engine_kw) -> "ReplicaSet":
        """One engine per local device (params device_put to each);
        `n_replicas` caps how many devices are used."""
        import jax

        if devices is None:
            devices = jax.local_devices()
        if n_replicas is not None:
            if n_replicas < 1:
                raise ValueError(
                    f"n_replicas must be >= 1, got {n_replicas}")
            devices = devices[:n_replicas]
        return cls([InferenceEngine.for_network(net, device=d, **engine_kw)
                    for d in devices])

    # --------------------------------------------------------- selection
    def _select(self, eligible: Sequence[int], load_of=None,
                acquire: bool = False) -> int:
        """Pick the least-loaded eligible engine index (round-robin
        tiebreak) and advance the shared cursor. Caller holds no lock;
        this takes the set's one lock — the single dispatch discipline
        for every traffic class. `load_of(i)` overrides the load metric
        (the decode path keys on live loop pressure instead of the
        per-call outstanding counter). `acquire=True` also increments
        the winner's outstanding count INSIDE the same critical
        section — select-then-increment under two lock grabs would let
        two concurrent requests pick the same idle engine."""
        if load_of is None:
            load_of = lambda i: self._outstanding[i]  # noqa: E731
        n = len(self.engines)
        with self._lock:
            best = min(eligible,
                       key=lambda i: (load_of(i), (i - self._rr) % n))
            self._rr = (best + 1) % n
            if acquire:
                self._outstanding[best] += 1
            return best

    @contextmanager
    def _checkout(self):
        """Select an engine for one short request, holding its
        outstanding slot for the call's duration."""
        idx = self._select(range(len(self.engines)), acquire=True)
        try:
            yield self.engines[idx]
        finally:
            with self._lock:
                self._outstanding[idx] -= 1

    # --------------------------------------------------------- dispatch
    def infer(self, x):
        with self._checkout() as engine:
            return engine.infer(x)

    def generate(self, prompt, n_tokens: int):
        """Per-request compiled-scan decode on the least-loaded replica
        (the legacy path; concurrent generate traffic belongs on
        `generate_stream` — the slot scheduler is its own batcher)."""
        with self._checkout() as engine:
            return engine.generate(prompt, n_tokens)

    def generate_stream(self, prompt, max_tokens: int, eos_id=None,
                        speculation: bool = True):
        """Submit one prompt to a replica's continuous-batching decode
        loop: least loop pressure (queued + occupied slots) wins, with
        the same shared round-robin cursor as `infer` breaking ties —
        so concurrent generate traffic fans across chips toward the
        idlest loop, without coalescing delays. `speculation=False`
        opts the request out of speculative drafting on loops that
        have it on (bit-identical output either way)."""
        loops = [i for i, e in enumerate(self.engines)
                 if e.decode_loop is not None]
        if not loops:
            raise ValueError(
                "no replica runs a decode loop (construct engines "
                "with decode_slots= or call start_decode_loop)")
        idx = self._select(
            loops, load_of=lambda i: self.engines[i].decode_loop.load)
        return self.engines[idx].generate_stream(prompt, max_tokens,
                                                 eos_id,
                                                 speculation=speculation)

    def warmup(self, feature_shape, **kw) -> None:
        for engine in self.engines:
            engine.warmup(feature_shape, **kw)

    def batcher(self, **kw) -> MicroBatcher:
        """A shared micro-batcher whose coalesced batches fan out over
        the replicas (least-outstanding first)."""
        return MicroBatcher(self.infer, **kw)

    # --------------------------------------------------------- hot reload
    def load_params(self, params, *, checkpoint=None) -> None:
        """Swap every replica's weights (each engine validates shapes
        and swaps atomically — in-flight requests finish on the old
        params; see InferenceEngine.load_params). `checkpoint` records
        the served identity ({path, step}) on every engine."""
        for engine in self.engines:
            engine.load_params(params, checkpoint=checkpoint)

    def load_checkpoint(self, path: str, step: Optional[int] = None) -> dict:
        """Hot-reload all replicas from a checkpoint — a sharded
        directory (deeplearning4j_tpu.checkpoint) or a legacy single-file
        npz — without dropping in-flight requests. The checkpoint's
        params tree must match the serving model's architecture (the
        per-leaf validation errors name the first mismatched leaf).
        Returns the checkpoint's info dict (step/cursor/metadata)."""
        import os

        if os.path.isdir(path):
            from deeplearning4j_tpu.checkpoint import restore_network

            net, info = restore_network(path, step)
        else:
            if step is not None:
                # a single-file checkpoint holds exactly one state —
                # silently serving it against an explicit step pin would
                # defeat a rollback-to-step intent
                raise ValueError(
                    f"step={step} was requested but {path!r} is a "
                    "single-file checkpoint with no steps; point at a "
                    "sharded checkpoint directory to pin a step")
            from deeplearning4j_tpu.scaleout.checkpoint import \
                load_checkpoint

            net, info = load_checkpoint(path)
        self.load_params(net.param_table,
                         checkpoint={"path": os.path.abspath(path),
                                     "step": info.get("step", step)})
        return info

    def load_draft_params(self, params, *, checkpoint=None) -> None:
        """Swap the speculative draft model's weights on every replica
        whose decode loop runs a model drafter (the `/reload`
        `{"target": "draft"}` canary path). Raises when NO replica has
        a draft model — a canary that silently reloaded nothing must
        not report success."""
        loaded = 0
        for engine in self.engines:
            loop = engine.decode_loop
            if (loop is not None and loop._drafter is not None
                    and loop._drafter.kind == "model"):
                engine.load_draft_params(params, checkpoint=checkpoint)
                loaded += 1
        if not loaded:
            raise ValueError(
                "no replica runs a model drafter (serve with "
                "speculation > 0 and drafter='model')")

    # ---------------------------------------------------- observability
    @property
    def checkpoint(self):
        """Checkpoint identity the set serves ({path, step} or None) —
        every engine gets the same identity through load_params, so the
        first engine speaks for the set."""
        return self.engines[0].checkpoint

    def program_cache_size(self) -> int:
        sizes = [e.program_cache_size() for e in self.engines]
        return -1 if any(s < 0 for s in sizes) else sum(sizes)

    def outstanding(self) -> List[int]:
        """Per-engine in-flight request counts (a point-in-time copy)."""
        with self._lock:
            return list(self._outstanding)

    def snapshot(self) -> dict:
        reps = [e.snapshot() for e in self.engines]
        buckets: dict = {}
        for r in reps:
            for b, c in r.get("bucket_forwards", {}).items():
                buckets[b] = buckets.get(b, 0) + c
        return {
            "replicas": len(self.engines),
            "checkpoint": self.checkpoint,
            "requests": sum(r["requests"] for r in reps),
            "rows": sum(r["rows"] for r in reps),
            "errors": sum(r["errors"] for r in reps),
            "outstanding": self.outstanding(),
            "compiled_programs": self.program_cache_size(),
            # aggregated per-bucket forward counts across replicas
            "bucket_forwards": {str(b): buckets[b]
                                for b in sorted(buckets)},
            "per_replica": reps,
        }
