"""Drafters for speculative decoding on the deterministic decode lane.

The decode step is bandwidth-bound: every delivered token pays one full
weight + KV-page sweep. Because the whole serving stack is
deterministic-argmax end to end, the classic draft-and-verify trick
(Leviathan et al.'s speculative decoding, here in its greedy/exact
form) costs nothing in output quality: a cheap drafter proposes k
continuation tokens per slot, ONE widened verify dispatch scores all
k+1 positions against the target model, and the accepted prefix is the
longest run where the draft agrees with the target's own argmax — with
the first disagreement replaced by the target's token. Output is
bit-identical to non-speculative decode by construction; only the
number of target dispatches per delivered token changes.

Two drafter flavors, selected by `DecodeLoop(drafter=...)`:

- `NgramDrafter` ("ngram") — zero weights, pure host-side prompt
  lookup: the longest n-gram suffix of the slot's own history (prompt +
  everything generated so far) is searched backwards in that history,
  and on a miss in the corpus of recent prompts the prefix-cache trie
  already retains (`PrefixIndex.iter_sequences`). Chat-shaped traffic
  — templated prompts, multi-turn replays, the repetitive continuations
  greedy tiny models settle into — makes this surprisingly strong, and
  it ships with no extra HBM or checkpoint.
- `ModelDrafter` ("model") — a small draft transformer (its own
  `TransformerConfig` + params) proposing k greedy tokens from a fixed
  right-aligned token window. The whole fleet ships it through the same
  checkpoint `/reload` path as the target (`target: "draft"`), so the
  deployment pipeline can canary a new draft model without touching
  serving weights. One jitted scan program, fixed `(S, window)` shape —
  the drafter adds exactly one compiled program for the server's life.

A drafter only ever *proposes*; `DecodeLoop`'s verify step is the sole
authority on what gets emitted. A bad drafter costs acceptance rate
(visible as dl4j_spec_accepted / dl4j_spec_proposed), never
correctness.
"""

from __future__ import annotations

from typing import Callable, Iterable, List, Optional, Sequence

import numpy as np

__all__ = ["NgramDrafter", "ModelDrafter", "build_drafter"]


class NgramDrafter:
    """Prompt-lookup drafter: propose the continuation that followed the
    most recent earlier occurrence of the history's n-gram suffix.

    Search order per suffix length n (longest first, down to 1):
    the slot's OWN history (most recent occurrence wins — self-repeating
    greedy continuations and multi-turn replays hit here), then the
    shared corpus (`corpus()` — the prefix-cache trie's retained prompt
    sequences, most recently inserted first). Zero device state."""

    kind = "ngram"

    def __init__(self, ngram: int = 3,
                 corpus: Optional[Callable[[], Iterable[Sequence[int]]]]
                 = None):
        if ngram < 1:
            raise ValueError(f"ngram must be >= 1, got {ngram}")
        self.ngram = int(ngram)
        self._corpus = corpus

    @staticmethod
    def _lookup(seq: Sequence[int], suffix: List[int],
                k: int) -> Optional[List[int]]:
        """Continuation after the most recent occurrence of `suffix` in
        `seq` that has a FULL k-token continuation; an occurrence with a
        shorter (but non-empty) continuation is kept only as fallback.
        The distinction matters for exactly the histories this drafter
        lives on: a self-repeating greedy tail's LAST occurrence sits at
        the end of the history where only ~1 follower exists, while an
        occurrence one period earlier yields the same loop k tokens
        deep — proposing 1 token/round where k fit would forfeit most
        of the verify round's amortization. (The trivial match at the
        very end has no followers at all and never fires.)"""
        n = len(suffix)
        best = None
        for i in range(len(seq) - n, -1, -1):
            if i + n < len(seq) and list(seq[i:i + n]) == suffix:
                cont = [int(t) for t in seq[i + n:i + n + k]]
                if len(cont) == k:
                    return cont
                if best is None:
                    best = cont
        return best

    def propose(self, history: Sequence[int], k: int) -> List[int]:
        """Up to k proposed continuation tokens for `history` (possibly
        fewer, possibly none — the verify round simply narrows)."""
        if k < 1 or len(history) < 2:
            return []
        history = [int(t) for t in history]
        for n in range(min(self.ngram, len(history) - 1), 0, -1):
            suffix = history[-n:]
            hit = self._lookup(history, suffix, k)
            if hit:
                return hit
            if self._corpus is not None:
                for seq in self._corpus():
                    hit = self._lookup(list(seq), suffix, k)
                    if hit:
                        return hit
        return []


class ModelDrafter:
    """Small draft transformer proposing k greedy tokens per slot.

    `propose_all(windows, k)` takes the right-aligned `(S, window)`
    token batch (left zero-padding for short histories) and rolls the
    window k times through ONE jitted `lax.scan`: each step takes the
    argmax at the last column and shifts it in. Shapes are fixed at
    construction, so the drafter compiles exactly one program — the
    `decode_step_programs <= 2` pin stays honest (the draft program is
    counted separately via `draft_programs()`).

    The left padding / window-relative positions can only hurt draft
    QUALITY (acceptance rate), never correctness — the target-model
    verify step is the only thing that decides emitted tokens."""

    kind = "model"

    def __init__(self, params, cfg, *, window: int = 32):
        if window < 1:
            raise ValueError(f"draft window must be >= 1, got {window}")
        self.cfg = cfg
        self.params = params
        self.window = int(min(window, cfg.max_len))
        self._draft = None  # built lazily — import jax only when used

    def _build(self):
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.models.transformer import \
            transformer_logits

        cfg = self.cfg

        def draft_fn(params, window, k):
            def step(win, _):
                logits = transformer_logits(params, win, cfg)
                nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(
                    jnp.int32)
                win = jnp.concatenate([win[:, 1:], nxt[:, None]],
                                      axis=1)
                return win, nxt

            _, toks = jax.lax.scan(step, window, None, length=k)
            return toks.T  # (S, k)

        from deeplearning4j_tpu import compilecache

        self._draft = compilecache.maybe_wrap(
            jax.jit(draft_fn, static_argnums=2),
            f"draft:{compilecache.config_digest(cfg)}"
            f"|w={self.window}|dev={jax.devices()[0]}",
            static_argnums=(2,))

    def warm(self, rows: int, k: int) -> bool:
        """AOT load-or-compile the `(rows, window)` draft-scan program
        via the persistent compile cache, without executing it (warmup
        plan replay — docs/WARMUP.md). False when no cache is active or
        the program had to stay lazy."""
        import jax

        if self._draft is None:
            self._build()
        if not hasattr(self._draft, "warm"):
            return False
        sds = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
            self.params)
        return self._draft.warm(
            sds, jax.ShapeDtypeStruct((int(rows), self.window),
                                      np.int32), int(k))

    def propose_all(self, windows: np.ndarray, k: int) -> np.ndarray:
        """(S, window) int32 right-aligned histories -> (S, k) int32
        proposals. Rows the caller doesn't need are computed anyway
        (fixed shape) and ignored."""
        import jax.numpy as jnp

        if self._draft is None:
            self._build()
        return np.asarray(self._draft(self.params,
                                      jnp.asarray(windows, jnp.int32),
                                      int(k)))

    def draft_programs(self) -> int:
        """Compiled draft programs (0 until first use, then pinned 1)."""
        from deeplearning4j_tpu.utils.jitcache import jit_cache_size

        if self._draft is None:
            return 0
        return jit_cache_size(self._draft)

    def load_params(self, params) -> None:
        """Swap the draft weights (same single-reference-assignment
        contract as the target's hot reload; shapes validated by the
        caller via checkpoint.restore.validate_like)."""
        self.params = params


def build_drafter(drafter: str, *, k: int, cfg, draft_params=None,
                  draft_cfg=None, draft_window: int = 32,
                  ngram: int = 3, corpus=None):
    """Construct the drafter `DecodeLoop(speculation=k, drafter=...)`
    asked for, validating the pieces it needs."""
    if drafter == "ngram":
        return NgramDrafter(ngram=ngram, corpus=corpus)
    if drafter == "model":
        if draft_params is None or draft_cfg is None:
            raise ValueError(
                "drafter='model' needs draft_params= and draft_cfg= "
                "(a small TransformerConfig + its weights)")
        if draft_cfg.vocab_size != cfg.vocab_size:
            raise ValueError(
                f"draft model vocab_size {draft_cfg.vocab_size} != "
                f"target vocab_size {cfg.vocab_size} — proposed token "
                "ids must be target-vocabulary ids")
        return ModelDrafter(draft_params, draft_cfg, window=draft_window)
    raise ValueError(
        f"drafter must be 'ngram' or 'model', got {drafter!r}")
