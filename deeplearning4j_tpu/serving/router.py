"""Fleet router tier: the HTTP front end over out-of-process replicas.

Two pieces live here (the fleet state machine itself is
`serving/fleet.py`):

- `ReplicaClient` — a thin stdlib HTTP client for ONE replica serving
  endpoint (`serve_network`'s surface: /predict, /generate, /reload,
  /healthz, /readyz, /stats). One connection per call: the router's
  concurrency comes from its own handler threads, and a fresh
  connection per request means a dead replica fails THIS call with a
  clean OSError instead of poisoning a pooled socket.
- `serve_fleet(fleet)` — the router's own HTTP server (same
  utils/httpd.py lifecycle as every embedded server in the repo):

  - ``POST /predict``  — least-outstanding ready replica; connection
    failures and replica 5xx retry transparently on a healthy peer
    (idempotent, so at-least-once is safe); total-outstanding past the
    fleet's high-water mark sheds with 503 + Retry-After — per SLO
    tier: an `X-Priority: batch` request sheds at the batch lane's
    own lower mark and the header is forwarded to the replica.
  - ``POST /generate`` — DURABLE streams (docs/FLEET.md "Stream
    failover"): the router always drives the replica in streaming mode
    and keeps a per-stream continuation record — the request spec plus
    every token already relayed per row. When the serving replica
    dies, is breaker-evicted, or resets mid-stream, the router
    re-admits the unfinished rows on a surviving READY replica by
    submitting ``prompt + tokens-delivered-so-far`` as the new context
    (the prefix cache makes the replay prefill near-free; greedy
    argmax decode makes the continuation bit-identical) and resumes
    relaying from the first undelivered token, deduplicating by
    absolute ``token_index`` — the client sees every token exactly
    once. Resumes are bounded (``Fleet(stream_resume_attempts=)``) and
    budget-aware (the remaining ``X-Deadline-Ms`` shrinks across
    hops); exhaustion answers 502 with a structured
    ``{"error": "replica_failed", ..., "retryable": true,
    "resume_attempts": N}`` before the first byte, or the same object
    in-band as the final NDJSON line after it. Bodies the router can't
    parse into a continuation record degrade to the legacy blind
    passthrough (no resume).

    The SAME machinery makes slot preemption lossless
    (docs/SERVING.md "Priority tiers"): a batch row whose decode slot
    was evicted for an interactive arrival comes back with
    ``finish_reason: "preempted"`` — the router treats that as
    NON-terminal, keeps the row's continuation record, and re-admits
    it on the next free slot exactly like a failover resume, except it
    burns no ``stream_resume_attempts`` budget and excludes no
    replica (the preempting replica is healthy). A shed re-admission
    (503: the batch lane is full) waits out the tier-aware
    ``Retry-After`` and tries again.
    DISAGGREGATED fleets (docs/FLEET.md "Disaggregated roles") add a
    prefill handoff in front of the durable stream: when the fleet
    has READY prefill-role replicas, the router first drives
    ``POST /prefill`` on the least-loaded one — parking the prompt's
    full KV pages in that replica's prefix trie — and then names it
    as the decode placement's ``kv_donor`` so the decode replica
    pulls the pages peer-to-peer over ``/kv/export`` before its own
    (now trivial) prefill. ANY failure along the handoff degrades
    the stream to plain unified prefill, bit-identically (greedy
    argmax decode from the same causal context).

    MULTI-MODEL fleets route by model: an ``X-Model`` header (or a
    ``"model_id"`` body field on /generate) scopes replica selection,
    affinity placement, and the prefill handoff to replicas
    announcing that model in /readyz; absent both, any
    stream-capable replica serves (single-model fleets unchanged).
  - ``POST /reload``   — rolling/canary reload across the fleet
    (drain -> per-replica /reload -> /readyz probe -> readmit, one at
    a time; automatic rollback when the canary fails — Fleet.rolling_reload).
    A ``"model_id"`` field scopes the reload to one model's replicas.
  - ``POST /scale``    — autoscaling hook: ``{"replicas": N}`` spawns
    or retires to N (requires a spawner).
  - ``GET /healthz``   — router liveness + per-state replica counts.
  - ``GET /readyz``    — 200 iff at least one replica is ready.
  - ``GET /stats``     — Fleet.snapshot().
  - ``GET /metrics`` / ``/snapshot`` — the router process's telemetry
    registry: the `dl4j_fleet_*` series (docs/OBSERVABILITY.md).

Every reply slurps the POST body first (HTTP/1.1 keep-alive would
desync otherwise — the same lesson serving/server.py carries).
"""

from __future__ import annotations

import json
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler
from typing import Optional, Tuple

from deeplearning4j_tpu.serving.errors import (DEADLINE_HEADER,
                                               PRIORITY_HEADER,
                                               TIER_INTERACTIVE, Deadline,
                                               DeadlineExceededError,
                                               OverloadedError,
                                               deadline_body,
                                               overload_body, parse_tier,
                                               replica_failed_body)
from deeplearning4j_tpu.serving import fleetkv
from deeplearning4j_tpu.telemetry import exposition
from deeplearning4j_tpu.testing import chaos
from deeplearning4j_tpu.utils.httpd import ServerHandle, start_http_server

__all__ = ["ReplicaClient", "FleetHandle", "serve_fleet"]


#: safety valves on the lossless-preemption loop. A batch stream under
#: constant interactive pressure can be preempted and re-admitted many
#: times (that is the design), but a pathological flood must not pin a
#: router thread forever: after this many preemption re-admissions the
#: stream fails with the in-band retryable shape instead.
_PREEMPT_RESUME_CAP = 64
#: ... and a re-admission that keeps getting SHED (batch lane full)
#: waits out Retry-After at most this many times (each wait is bounded
#: at 5s, so the worst case is minutes, not forever).
_PREEMPT_SHED_WAITS_CAP = 600


class _ClientGone(Exception):
    """The DOWNSTREAM client hung up mid-stream. Never attributed to
    the replica (a client closing its laptop must not evict a healthy
    replica) — the router just stops relaying and lets the replica-side
    connection close cancel the slots."""


class _RowState:
    """One row of a /generate continuation record: the original spec
    plus every token already relayed to the client. `prompt +
    delivered` is the replay context a resume submits; `len(delivered)`
    is both the next absolute token_index expected (the exactly-once
    dedupe key) and the amount to subtract from max_tokens on
    re-admission."""

    __slots__ = ("index", "prompt", "max_tokens", "delivered",
                 "finish_reason")

    def __init__(self, index: int, prompt, max_tokens: int):
        self.index = index            # row position in the CLIENT's request
        self.prompt = prompt          # original prompt token ids
        self.max_tokens = max_tokens  # original per-row budget
        self.delivered = []           # tokens already relayed, in order
        self.finish_reason = None     # set -> row is terminal


def _parse_continuation(data: dict):
    """Build the per-stream continuation record the failover engine
    keeps, or return None when the body doesn't speak the decode-loop
    contract (the router then degrades to the legacy blind passthrough
    and the replica's own validation answers). Returns
    (rows, eos_id, prefix_cache, speculation)."""
    try:
        raw = data["prompt"]
        if not isinstance(raw, list) or not raw:
            return None
        if not isinstance(raw[0], list):
            raw = [raw]
        prompts = []
        for row in raw:
            if not isinstance(row, list) or not row:
                return None
            prompts.append([int(t) for t in row])
        mt = data.get("max_tokens", data.get("n_tokens", 16))
        if isinstance(mt, list):
            if len(mt) != len(prompts):
                return None
            per_row = [int(m) for m in mt]
        else:
            per_row = [int(mt)] * len(prompts)
        if any(m < 1 for m in per_row):
            return None
        if "token_index_base" in data:
            # the router OWNS the dedupe offsets; a client already
            # speaking them is itself a resuming router — pass through
            return None
        eos = data.get("eos_id")
        eos = None if eos is None else int(eos)
        rows = [_RowState(i, p, m)
                for i, (p, m) in enumerate(zip(prompts, per_row))]
        return (rows, eos, bool(data.get("prefix_cache", True)),
                bool(data.get("speculation", True)))
    except (TypeError, ValueError, KeyError):
        return None


def _head_row(data: dict):
    """Best-effort first prompt row as an int list for affinity
    hashing on the passthrough path, or None when the body doesn't
    carry token ids (string prompts route by least-outstanding).
    Callers must already have checked the `prefix_cache` opt-out —
    opted-out token ids are never hashed."""
    raw = data.get("prompt")
    if not isinstance(raw, list) or not raw:
        return None
    row = raw[0] if isinstance(raw[0], list) else raw
    try:
        return [int(t) for t in row]
    except (TypeError, ValueError):
        return None


class ReplicaClient:
    """Stdlib HTTP client for one replica serving endpoint."""

    def __init__(self, url: str, timeout: float = 30.0):
        if "//" not in url:
            url = "http://" + url
        parsed = urllib.parse.urlsplit(url)
        if parsed.hostname is None or parsed.port is None:
            raise ValueError(
                f"replica url needs host:port, got {url!r}")
        self.host = parsed.hostname
        self.port = int(parsed.port)
        self.url = f"http://{self.host}:{self.port}"
        self.timeout = float(timeout)

    # ------------------------------------------------------------- raw
    def open(self, method: str, path: str, body: Optional[bytes] = None,
             timeout: Optional[float] = None,
             headers: Optional[dict] = None):
        """Issue a request and return (connection, response) with the
        body NOT yet read — the streaming proxy relays it chunk by
        chunk. The caller owns `connection.close()`. `headers` extends
        the defaults (how the router forwards `X-Deadline-Ms`)."""
        import http.client

        conn = http.client.HTTPConnection(
            self.host, self.port,
            timeout=self.timeout if timeout is None else timeout)
        hdrs = {"Content-Type": "application/json"} if body else {}
        if headers:
            hdrs.update(headers)
        try:
            conn.request(method, path, body=body, headers=hdrs)
            resp = conn.getresponse()
        except BaseException:
            conn.close()
            raise
        return conn, resp

    def request(self, method: str, path: str,
                body: Optional[bytes] = None,
                timeout: Optional[float] = None,
                headers: Optional[dict] = None
                ) -> Tuple[int, dict, bytes]:
        """One whole request: (status, headers-dict, body-bytes)."""
        conn, resp = self.open(method, path, body, timeout,
                               headers=headers)
        try:
            data = resp.read()
            return resp.status, dict(resp.getheaders()), data
        finally:
            conn.close()

    # ------------------------------------------------------ conveniences
    def get_json(self, path: str, timeout: Optional[float] = None
                 ) -> Tuple[int, dict]:
        status, _, data = self.request("GET", path, timeout=timeout)
        try:
            payload = json.loads(data) if data else {}
        except ValueError:
            payload = {"raw": data.decode(errors="replace")}
        return status, payload

    def healthz(self, timeout: Optional[float] = None) -> dict:
        """Liveness probe; raises on connection failure or non-200."""
        status, payload = self.get_json("/healthz", timeout)
        if status != 200:
            raise RuntimeError(f"healthz answered {status}")
        return payload

    def readyz(self, timeout: Optional[float] = None
               ) -> Tuple[bool, dict]:
        """Readiness probe: (ready, payload). Connection failures
        propagate (the caller distinguishes dead from not-ready)."""
        status, payload = self.get_json("/readyz", timeout)
        return status == 200, payload

    def stats(self, timeout: Optional[float] = None) -> dict:
        status, payload = self.get_json("/stats", timeout)
        if status != 200:
            raise RuntimeError(f"stats answered {status}")
        return payload


class FleetHandle:
    """A running fleet router: http handle + the fleet behind it."""

    def __init__(self, fleet, http: Optional[ServerHandle] = None):
        self.fleet = fleet
        self.http = http
        self.started_at = time.time()

    @property
    def url(self) -> str:
        return self.http.url

    @property
    def port(self) -> int:
        return self.http.port

    def close(self, stop_replicas: bool = False,
              handoff: bool = False) -> None:
        """Stop routing, then stop the fleet's control plane (and the
        spawned replica processes too when `stop_replicas`).
        `handoff=True` leaves the journaled replicas running for the
        next router incarnation to re-adopt (docs/FLEET.md "Router
        restart runbook")."""
        self.http.close()
        self.fleet.close(stop_replicas=stop_replicas, handoff=handoff)

    def __enter__(self) -> "FleetHandle":
        return self

    def __exit__(self, *exc) -> None:
        # mirror Fleet.__exit__: spawned replica processes die with the
        # context (attached-by-URL replicas are never touched)
        self.close(stop_replicas=True)


def serve_fleet(fleet, host: str = "127.0.0.1",
                port: int = 0,
                fleet_kv: str = fleetkv.MODE_ON) -> FleetHandle:
    """Start the router HTTP tier over a (started) Fleet.

    `fleet_kv` sets the router half of the fleet KV plane
    (docs/FLEET.md "Fleet KV plane"): ``"on"`` routes /generate by
    prefix affinity AND names a donor replica for peer-to-peer page
    shipping, ``"affinity-only"`` routes but never ships,
    ``"off"`` disables both (placement falls back to pure
    least-outstanding)."""
    from deeplearning4j_tpu.serving.fleet import NoReadyReplicas

    affinity = fleetkv.RouterAffinity(fleet_kv)
    handle = FleetHandle(fleet)

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"  # streaming passthrough needs it

        def log_message(self, *args):  # quiet
            pass

        def _reply(self, code: int, payload: dict,
                   extra_headers=()) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            for k, v in extra_headers:
                self.send_header(k, v)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _reply_raw(self, code: int, ctype: str, body: bytes) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _reply_overloaded(self, e: OverloadedError) -> None:
            self._reply(503, overload_body(e),
                        extra_headers=[("Retry-After",
                                        str(e.retry_after_s))])

        # ----------------------------------------------------- routes
        def do_GET(self):
            try:
                if self.path.startswith("/healthz"):
                    self._reply(200, {"ok": True,
                                      "replicas": fleet.state_counts(),
                                      "incarnation": fleet.incarnation})
                elif self.path.startswith("/readyz"):
                    n = fleet.ready_count()
                    self._reply(200 if n else 503,
                                {"ready": n > 0, "ready_replicas": n})
                elif self.path.startswith("/stats"):
                    self._reply(200, {
                        "uptime_s": round(
                            time.time() - handle.started_at, 3),
                        "fleet": fleet.snapshot()})
                elif (hit := exposition.handle_metrics_get(
                        self.path)) is not None:
                    self._reply_raw(*hit)
                else:
                    self._reply(404, {"error": f"no route {self.path}"})
            except Exception as e:  # always answer with a status line
                self._reply(500, {"error": f"{type(e).__name__}: {e}"})

        def do_POST(self):
            # slurp the body BEFORE any reply (keep-alive framing)
            length = int(self.headers.get("Content-Length") or 0)
            self._body = self.rfile.read(length) if length > 0 else None
            try:
                chaos.hit("router.forward", path=self.path)
                if self.path.startswith("/predict"):
                    self._predict()
                elif self.path.startswith("/generate"):
                    self._generate()
                elif self.path.startswith("/reload"):
                    self._reload()
                elif self.path.startswith("/scale"):
                    self._scale()
                else:
                    self._reply(404, {"error": f"no route {self.path}"})
            except OverloadedError as e:
                self._reply_overloaded(e)
            except DeadlineExceededError as e:
                # the machine-readable budget-spent shape — same wire
                # contract as the replica server's 504
                self._reply(504, deadline_body(e))
            except NoReadyReplicas as e:
                self._reply(503, {"error": "no_ready_replicas",
                                  "detail": str(e)},
                            extra_headers=[("Retry-After", "1")])
            except (ValueError, KeyError, TypeError) as e:
                self._reply(400, {"error": str(e)})
            except Exception as e:
                self._reply(500, {"error": f"{type(e).__name__}: {e}"})

        def _read_json(self) -> dict:
            if self._body is None:
                raise ValueError("missing request body")
            data = json.loads(self._body)
            if not isinstance(data, dict):
                raise ValueError("request body must be a JSON object")
            return data

        def _model_id(self, data: Optional[dict] = None
                      ) -> Optional[str]:
            """The request's model scope: `X-Model` header first (the
            only channel /predict has — its body is forwarded raw),
            then a `"model_id"` body field. None routes un-scoped
            (any stream-capable replica — single-model fleets never
            pay the filter)."""
            mid = self.headers.get("X-Model")
            if not mid and isinstance(data, dict):
                mid = data.get("model_id")
            if mid is None:
                return None
            mid = str(mid).strip()
            return mid or None

        def _predict(self):
            if self._body is None:
                raise ValueError("missing request body")
            # header-borne budget (clients of the router speak the
            # header; the router forwards the SHRUNK remainder)
            deadline = Deadline.from_request(self.headers)
            # header-borne tier too: /predict bodies are forwarded
            # raw, so only `X-Priority` reaches the fleet's per-tier
            # admission here (a body-only "priority" field is still
            # honored by the replica's own batcher)
            tier = parse_tier(self.headers)
            status, headers, data = fleet.forward_predict(
                self._body, deadline=deadline, tier=tier,
                model_id=self._model_id())
            ctype = headers.get("Content-Type", "application/json")
            extra = [("Retry-After", headers["Retry-After"])] \
                if "Retry-After" in headers else []
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            for k, v in extra:
                self.send_header(k, v)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _hop_budget(self, deadline, tier=TIER_INTERACTIVE):
            """Per-attempt (timeout, forwarded-headers, breaker-
            eligible) derived from the REMAINING budget — recomputed on
            every resume hop so the forwarded `X-Deadline-Ms` only ever
            shrinks, plus the forwarded `X-Priority` so the replica's
            decode admission applies the same tier. A timeout at a
            deadline-sliced window shorter than a fair wait says the
            CLIENT was impatient, not that the replica hung — same
            eligibility rule forward_predict applies
            (fleet.note_request_failure's contract)."""
            if deadline is None:
                hop_timeout, fwd_headers = fleet.generate_timeout, {}
            else:
                hop_timeout = deadline.timeout(fleet.generate_timeout)
                fwd_headers = {DEADLINE_HEADER: deadline.header_value()}
            if tier != TIER_INTERACTIVE:
                fwd_headers[PRIORITY_HEADER] = tier
            eligible = hop_timeout >= min(fleet.generate_timeout,
                                          fleet.probe_timeout)
            return hop_timeout, fwd_headers or None, eligible

        def _kv_place(self, tokens, use_prefix: bool,
                      model_id: Optional[str] = None):
            """Prefix-affinity placement for one request, or None.

            The opt-out contract (docs/FLEET.md): a body carrying
            `"prefix_cache": false` reaches this with `use_prefix`
            False and returns BEFORE any hashing — prompt-derived
            fingerprints of opted-out requests are never computed on
            the router, just as the replica never seeds its summary
            with them. A placement fault degrades to least-outstanding
            routing, never to a failed request. `model_id` scopes the
            summary set so cross-model prefixes never attract each
            other's traffic."""
            if not use_prefix or not affinity.enabled:
                return None
            try:
                return affinity.plan(
                    tokens, fleet.kv_summaries(model_id=model_id))
            except Exception:
                return None

        def _disagg_handoff(self, rows, deadline, tier,
                            model_id, use_prefix: bool):
            """The prefill leg of a disaggregated handoff: drive
            /prefill on the least-loaded prefill-role replica so the
            prompts' full KV pages are parked in ITS prefix trie,
            then return its URL for the decode placement's
            `kv_donor` hint (decode_loop.kv_ship pulls the pages
            peer-to-peer before prefill). Returns None — plain
            unified prefill, bit-identical — when the fleet has no
            prefill pool for this model, shipping is off, the prompt
            is shorter than one KV page, or ANY step of the dispatch
            fails."""
            import http.client as _hc

            if not use_prefix or not affinity.shipping:
                return None
            try:
                if fleet.role_counts(model_id).get("prefill", 0) < 1:
                    return None
                pre = fleet.select(route="generate", role="prefill",
                                   model_id=model_id, tier=tier,
                                   count=False)
            except Exception:
                return None  # no pool / shed: not a handoff failure
            try:
                hop_timeout, fwd_headers, eligible = \
                    self._hop_budget(deadline, tier)
                body = json.dumps(
                    {"prompt": [r.prompt for r in rows]}).encode()
                try:
                    status, _, raw = pre.client.request(
                        "POST", "/prefill", body,
                        timeout=hop_timeout, headers=fwd_headers)
                except (OSError, _hc.HTTPException) as e:
                    fleet.note_request_failure(
                        pre, e, breaker_eligible=eligible)
                    raise
                if status != 200:
                    raise RuntimeError(f"/prefill answered {status}")
                report = json.loads(raw)
                fleet.note_request_success(pre)
                if int(report.get("chunks") or 0) < 1:
                    # prompts shorter than one full page: nothing was
                    # parked, so a donor hint would buy nothing —
                    # neither a handoff nor a failure
                    return None
                fleet._m_disagg_handoffs.inc()
                fleet._m_disagg_handoff_bytes.inc(
                    int(report.get("kv_bytes") or 0))
                return pre.client.url
            except Exception:
                # ANY failure degrades to plain prefill on the decode
                # replica — the stream is bit-identical either way
                fleet._m_disagg_handoff_failures.inc()
                fleet._m_disagg_fallbacks.inc()
                return None
            finally:
                fleet.release(pre, tier)

        def _generate(self):
            data = self._read_json()  # parsed for stream/deadline
            streaming = bool(data.get("stream", False))
            deadline = Deadline.from_request(self.headers, data)
            tier = parse_tier(self.headers, data)  # unknown -> 400
            if deadline is not None and deadline.expired:
                fleet._m_deadline["generate"].inc()
                deadline.check("router dispatch")  # raises -> 504
            parsed = _parse_continuation(data)
            model_id = self._model_id(data)
            start = time.perf_counter()
            try:
                if parsed is None:
                    self._generate_passthrough(streaming, deadline,
                                               tier, data, model_id)
                else:
                    self._generate_durable(parsed, streaming, deadline,
                                           tier, model_id)
            except _ClientGone:
                self.close_connection = True
            finally:
                fleet.observe("generate", time.perf_counter() - start,
                              tier=tier)

        def _generate_durable(self, parsed, streaming, deadline, tier,
                              model_id=None):
            """Failover-durable /generate: drive the replica in
            streaming mode (even for a non-streaming client), fold its
            NDJSON into the continuation record, and on replica failure
            re-admit the unfinished rows on a survivor with
            `prompt + delivered` as the new context. The client's
            response headers are sent LAZILY — while no byte has been
            relayed, a total failure can still answer a clean 502.

            Preemption rides the same loop: rows finishing with
            `"preempted"` stay non-terminal and re-admit on the next
            iteration — with `attempt` still 0, so a preemption resume
            burns no failover budget, excludes no replica, and a shed
            re-admission waits out the tier-aware Retry-After."""
            import http.client as _hc

            rows, eos_id, use_prefix, use_spec = parsed
            replica_errs = (OSError, _hc.HTTPException)
            failed = []        # replica ids excluded from resume placement
            resumes = 0        # successful re-admissions (stream opened)
            resume_tried = 0   # resume attempts started (reported on fail)
            preempt_resumes = 0  # lossless preemption re-admissions
            preempt_waits = 0    # shed re-admissions waited out
            preempt_pending = False  # next stream-open IS a preempt resume
            state = {"headers_sent": False}

            def chunk(obj: dict) -> None:
                # lazy headers: the first relayed line commits us to the
                # in-band error contract; before it, status codes work
                try:
                    if not state["headers_sent"]:
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         "application/x-ndjson")
                        self.send_header("Transfer-Encoding", "chunked")
                        self.end_headers()
                        state["headers_sent"] = True
                    raw = (json.dumps(obj) + "\n").encode()
                    self.wfile.write(f"{len(raw):x}\r\n".encode()
                                     + raw + b"\r\n")
                    self.wfile.flush()
                except _ClientGone:
                    raise
                except Exception as e:
                    raise _ClientGone(str(e)) from e

            def end_chunked() -> None:
                try:
                    self.wfile.write(b"0\r\n\r\n")
                except Exception:
                    pass
                self.close_connection = True

            def reply_complete() -> None:
                reasons = [r.finish_reason for r in rows]
                toks = [r.prompt + r.delivered
                        if r.finish_reason not in ("error",
                                                   "deadline_exceeded")
                        else None
                        for r in rows]
                if streaming:
                    done_line = {"done": True, "tokens": toks,
                                 "finish_reasons": reasons,
                                 "resumes": resumes}
                    if preempt_resumes:
                        done_line["preempt_resumes"] = preempt_resumes
                    chunk(done_line)
                    end_chunked()
                elif "deadline_exceeded" in reasons:
                    self._reply(504, {"error": "deadline_exceeded",
                                      "detail": "generation deadline "
                                      "exceeded on the replica",
                                      "finish_reasons": reasons})
                elif "error" in reasons:
                    self._reply(500, {"error": "generation failed",
                                      "finish_reasons": reasons})
                else:
                    out = {"tokens": toks, "finish_reasons": reasons}
                    if resumes:
                        out["resumes"] = resumes
                    if preempt_resumes:
                        out["preempt_resumes"] = preempt_resumes
                    self._reply(200, out)

            def reply_inband(obj: dict) -> None:
                # the replica spoke a terminal in-band error (deadline,
                # chaos reset already surfaced as JSON, ...): relay its
                # shape, NOT a replica failure
                if streaming:
                    chunk(obj)
                    end_chunked()
                elif obj.get("error") == "deadline_exceeded":
                    self._reply(504, obj)
                else:
                    self._reply(500, obj)

            def reply_failed(replica_id, detail: str) -> None:
                # resume budget exhausted (attempts or deadline): the
                # in-band retryable fallback, now carrying how many
                # resumes were burned
                fleet._m_stream_resume_failures.inc()
                body = replica_failed_body(replica_id, detail,
                                           resume_attempts=resume_tried)
                if state["headers_sent"]:
                    chunk(body)
                    end_chunked()
                else:
                    self._reply(502, body)

            # affinity placement hashes only the PROMPT head (chunk-
            # aligned), so one plan covers every hop: delivered tokens
            # extend the tail, never the head. Opted-out bodies skip
            # the hash entirely (use_prefix False -> None).
            placement = self._kv_place(rows[0].prompt, use_prefix,
                                       model_id)
            # disaggregated handoff (prefill-role pool only): park the
            # prompt KV on a prefill replica and name it as donor for
            # the FIRST hop. Resume hops replay prompt + delivered on
            # a survivor; the parked pages are stale for that longer
            # context, so resumes use the affinity donor path instead.
            handoff_donor = self._disagg_handoff(
                rows, deadline, tier, model_id, use_prefix)
            affinity_noted = False
            attempt = 0
            last = (None, "no replica attempted")  # (id, detail)
            while True:
                pending = [r for r in rows if r.finish_reason is None]
                if not pending:
                    reply_complete()
                    return
                if attempt > 0:
                    # ---------------- a failover resume: bounded + budget-aware
                    if attempt > fleet.stream_resume_attempts:
                        reply_failed(*last)
                        return
                    if deadline is not None and deadline.expired:
                        reply_failed(last[0], f"{last[1]} (deadline "
                                     "spent before resume)")
                        return
                    resume_tried += 1
                    try:
                        chaos.hit("router.stream_resume",
                                  attempt=attempt, replica=last[0])
                    except Exception as e:
                        last = (last[0], f"resume blocked: "
                                f"{type(e).__name__}: {e}")
                        attempt += 1
                        continue
                    prefer = (placement.prefer
                              if placement is not None
                              and placement.prefer not in failed
                              else None)
                    try:
                        replica = fleet.select(
                            route="generate",
                            exclude=tuple(failed),
                            tier=tier, prefer=prefer,
                            prefer_slack=fleetkv.PLACEMENT_SLACK,
                            model_id=model_id)
                    except (NoReadyReplicas, OverloadedError) as e:
                        reply_failed(last[0], f"{last[1]}; no surviving "
                                     f"replica to resume on ({e})")
                        return
                else:
                    try:
                        replica = fleet.select(
                            route="generate", tier=tier,
                            count=not preempt_pending,
                            prefer=(placement.prefer
                                    if placement is not None else None),
                            prefer_slack=fleetkv.PLACEMENT_SLACK,
                            model_id=model_id)
                    except OverloadedError:
                        if not preempt_pending:
                            raise  # initial admission: shed the client
                        # a preemption re-admission shed at the FLEET
                        # mark: same backpressure as a replica-side
                        # 503 — wait a beat and try again
                        preempt_waits += 1
                        if preempt_waits > _PREEMPT_SHED_WAITS_CAP or (
                                deadline is not None
                                and deadline.expired):
                            reply_failed(last[0], "preempted stream "
                                         "could not re-admit (fleet "
                                         "overloaded)")
                            return
                        time.sleep(0.2)
                        continue
                if placement is not None and not affinity_noted:
                    # scored once per stream, on first placement: hit =
                    # the summaries matched AND the request landed on
                    # the matched replica
                    affinity_noted = True
                    fleet.note_affinity(placement.depth > 0 and
                                        replica.id == placement.prefer)
                hop_timeout, fwd_headers, eligible = \
                    self._hop_budget(deadline, tier)
                body = {
                    # replay context: everything the client already has
                    "prompt": [r.prompt + r.delivered for r in pending],
                    "max_tokens": [r.max_tokens - len(r.delivered)
                                   for r in pending],
                    "stream": True,
                    "prefix_cache": use_prefix,
                    # the client's speculation opt-in/out survives the
                    # failover hop (output is bit-identical either way —
                    # this preserves intent, not correctness)
                    "speculation": use_spec,
                    # absolute indices resume where delivery stopped, so
                    # dedupe below is a pure integer comparison
                    "token_index_base": [len(r.delivered)
                                         for r in pending],
                }
                if eos_id is not None:
                    body["eos_id"] = eos_id
                if (handoff_donor is not None and attempt == 0
                        and not preempt_pending):
                    # disaggregated handoff: the prefill replica just
                    # parked this prompt's pages — it outranks any
                    # affinity donor for the first hop
                    body["kv_donor"] = handoff_donor
                elif (affinity.shipping and placement is not None
                        and placement.depth > 0
                        and placement.donor_url
                        and replica.id != placement.donor
                        and placement.donor not in failed):
                    # the request landed OFF the replica holding its
                    # cached head (shed pressure, SUSPECT, slack): name
                    # the donor so the receiver ships the hot pages
                    # peer-to-peer before prefill (decode_loop.kv_ship
                    # — any ship failure falls back to plain prefill)
                    body["kv_donor"] = placement.donor_url
                replayed = sum(len(r.prompt) + len(r.delivered)
                               for r in pending)
                conn = None
                try:
                    try:
                        conn, resp = replica.client.open(
                            "POST", "/generate",
                            json.dumps(body).encode(),
                            timeout=hop_timeout, headers=fwd_headers)
                    except replica_errs as e:
                        fleet.note_request_failure(
                            replica, e, breaker_eligible=eligible)
                        failed.append(replica.id)
                        last = (replica.id, f"{type(e).__name__}: {e}")
                        attempt += 1
                        continue
                    if resp.status != 200:
                        raw = resp.read()
                        if preempt_pending and attempt == 0:
                            if resp.status == 503:
                                # a preemption re-admission was SHED
                                # (batch lane full): honor the
                                # tier-aware Retry-After, bounded by
                                # the remaining budget — backpressure,
                                # not failure; no failover budget
                                # burned, nobody excluded
                                fleet.note_request_success(replica)
                                preempt_waits += 1
                                if preempt_waits > \
                                        _PREEMPT_SHED_WAITS_CAP:
                                    reply_failed(
                                        replica.id,
                                        "preempted stream could not "
                                        "re-admit (lane stayed full)")
                                    return
                                ra = resp.getheader("Retry-After")
                                try:
                                    wait = (min(float(ra), 5.0)
                                            if ra else 0.2)
                                except ValueError:
                                    wait = 0.2
                                if deadline is not None:
                                    if deadline.expired:
                                        reply_failed(
                                            replica.id,
                                            "deadline spent re-"
                                            "admitting a preempted "
                                            "stream")
                                        return
                                    wait = min(wait, max(
                                        0.05, deadline.remaining_s()))
                                time.sleep(wait)
                                continue
                            # any other refusal mid-preemption-resume:
                            # headers may already be out, so speak the
                            # in-band retryable shape, never a raw
                            # status line
                            reply_failed(
                                replica.id,
                                "preempted stream re-admission "
                                f"refused: HTTP {resp.status}")
                            return
                        if attempt > 0:
                            # a survivor refusing the resume (shedding,
                            # validation): exclude it and keep going
                            failed.append(replica.id)
                            last = (replica.id,
                                    f"resume refused: HTTP {resp.status}")
                            attempt += 1
                            continue
                        fleet.note_request_success(replica)
                        if resp.status == 400:
                            # the replica rejected the streaming upgrade
                            # (no decode loop): nothing was delivered
                            # yet, so forward the ORIGINAL body untouched
                            # and relay whatever the replica says
                            self._relay_plain(replica, hop_timeout,
                                              fwd_headers, eligible)
                            return
                        extra = []
                        ra = resp.getheader("Retry-After")
                        if ra:
                            extra.append(("Retry-After", ra))
                        ctype = resp.getheader("Content-Type",
                                               "application/json")
                        self.send_response(resp.status)
                        self.send_header("Content-Type", ctype)
                        for k, v in extra:
                            self.send_header(k, v)
                        self.send_header("Content-Length", str(len(raw)))
                        self.end_headers()
                        self.wfile.write(raw)
                        return
                    if attempt > 0:
                        resumes += 1
                        fleet._m_stream_resumes.inc()
                        fleet._m_stream_tokens_replayed.inc(replayed)
                    elif preempt_pending:
                        # a lossless preemption re-admission opened:
                        # counted apart from failover resumes, but the
                        # replayed-context accounting is the same (the
                        # prefix cache absorbs the replay either way)
                        preempt_resumes += 1
                        fleet._m_preempt_resumes.inc()
                        fleet._m_stream_tokens_replayed.inc(replayed)
                    preempt_pending = False
                    kind, payload = self._relay_continuation(
                        resp, pending, eos_id,
                        chunk if streaming else None)
                    if kind == "broken":
                        fleet.note_request_failure(
                            replica, payload, breaker_eligible=eligible)
                        failed.append(replica.id)
                        last = (replica.id,
                                f"{type(payload).__name__}: {payload}")
                        attempt += 1
                        continue
                    fleet.note_request_success(replica)
                    if kind == "inband":
                        reply_inband(payload)
                        return
                    if kind == "preempted":
                        # `payload` rows lost their batch slot to an
                        # interactive arrival; their continuation
                        # records are intact, so the next iteration
                        # re-admits them — attempt stays 0 (no
                        # failover budget burned, no exclusion)
                        if preempt_resumes >= _PREEMPT_RESUME_CAP:
                            reply_failed(
                                replica.id,
                                f"preempted {preempt_resumes} times "
                                "without finishing (resume cap)")
                            return
                        preempt_pending = True
                        last = (replica.id, "slot preempted")
                        continue
                    # kind == "done": loop re-checks pending (empty
                    # unless the replica under-reported — it won't)
                finally:
                    if conn is not None:
                        conn.close()
                    fleet.release(replica, tier)

        def _relay_continuation(self, resp, pending, eos_id, emit):
            """Fold one replica's NDJSON stream into the continuation
            record, relaying token chunks via `emit` (None buffers for
            a non-streaming client). Returns:

            - ("done", None)    — the replica finished every row;
            - ("preempted", n)  — the stream ended cleanly but n rows
              lost their batch slot to an interactive arrival
              (`finish_reason: "preempted"`); their records stay
              NON-terminal and the caller re-admits them losslessly;
            - ("inband", obj)   — terminal in-band error object
              (deadline and friends — NOT a replica failure);
            - ("broken", exc)   — the replica died / hung / broke the
              protocol mid-stream; the caller resumes elsewhere.

            Exactly-once is enforced HERE: every token chunk carries an
            absolute `token_index`; anything below the next expected
            index was already relayed before the failover and is
            dropped (deduped), a gap above it means lost tokens and is
            treated as a replica failure so the resume replays them."""
            try:
                while True:
                    line = resp.readline()  # http.client de-chunks
                    if not line:
                        return ("broken", ConnectionError(
                            "replica stream ended without a done line"))
                    if not line.endswith(b"\n"):
                        return ("broken", ConnectionError(
                            "replica stream died mid-line"))
                    if not line.strip():
                        continue
                    try:
                        obj = json.loads(line)
                    except ValueError:
                        return ("broken", ConnectionError(
                            "undecodable stream line from replica"))
                    if obj.get("done"):
                        reasons = obj.get("finish_reasons") or []
                        n_preempted = 0
                        for li, row in enumerate(pending):
                            if row.finish_reason is None:
                                reason = (reasons[li]
                                          if li < len(reasons)
                                          else "error")
                                if reason == "preempted":
                                    # NOT terminal: the row keeps its
                                    # continuation record and the
                                    # caller re-admits it on the next
                                    # free slot (lossless preemption)
                                    n_preempted += 1
                                else:
                                    row.finish_reason = reason
                        if n_preempted:
                            return ("preempted", n_preempted)
                        return ("done", None)
                    if "token" in obj:
                        li = obj.get("row", 0)
                        if not isinstance(li, int) \
                                or not 0 <= li < len(pending):
                            return ("broken", ConnectionError(
                                f"stream row {li!r} out of range"))
                        row = pending[li]
                        expected = len(row.delivered)
                        idx = int(obj.get("token_index", expected))
                        if idx < expected:
                            # a replayed token the client already has
                            fleet._m_stream_tokens_deduped.inc()
                            continue
                        if idx > expected:
                            return ("broken", ConnectionError(
                                f"token index gap (got {idx}, "
                                f"expected {expected})"))
                        tok = int(obj["token"])
                        row.delivered.append(tok)
                        if eos_id is not None and tok == eos_id:
                            row.finish_reason = "eos"
                        elif len(row.delivered) >= row.max_tokens:
                            row.finish_reason = "max_tokens"
                        if emit is not None:
                            # rewrite to the CLIENT's row numbering
                            emit({"row": row.index, "token": tok,
                                  "token_index": idx})
                        continue
                    if "error" in obj:
                        return ("inband", obj)
                    # unknown line shape: tolerate (forward-compat)
            except _ClientGone:
                raise
            except Exception as e:
                return ("broken", e)

        def _relay_plain(self, replica, hop_timeout, fwd_headers,
                         eligible) -> None:
            """Re-forward the client's ORIGINAL body to `replica` and
            relay the whole reply — the legacy escape hatch when the
            replica rejected the router's streaming upgrade (a serve
            process without a decode loop still answers plain
            /generate)."""
            import http.client as _hc

            try:
                status, headers, data = replica.client.request(
                    "POST", "/generate", self._body,
                    timeout=hop_timeout, headers=fwd_headers)
            except (OSError, _hc.HTTPException) as e:
                fleet.note_request_failure(replica, e,
                                           breaker_eligible=eligible)
                self._reply(502, replica_failed_body(
                    replica.id, f"{type(e).__name__}: {e}"))
                return
            if status < 500:
                fleet.note_request_success(replica)
            extra = [("Retry-After", headers["Retry-After"])] \
                if "Retry-After" in headers else []
            ctype = headers.get("Content-Type", "application/json")
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            for k, v in extra:
                self.send_header(k, v)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _generate_passthrough(self, streaming, deadline,
                                  tier=TIER_INTERACTIVE, data=None,
                                  model_id=None):
            """The pre-failover path, kept for bodies that don't parse
            into a continuation record (string prompts, exotic fields,
            a client that is itself a resuming router): one replica,
            blind relay, no resume (a preempted row surfaces its
            `"preempted"` finish_reason to the client unresumed).
            Affinity still places token-list bodies (the body is
            forwarded untouched, so no donor hint is injected here —
            the affinity hit itself makes shipping unnecessary)."""
            placement = None
            if data is not None and bool(data.get("prefix_cache",
                                                  True)):
                tokens = _head_row(data)
                if tokens:
                    placement = self._kv_place(tokens, True, model_id)
            replica = fleet.select(
                route="generate", tier=tier,
                prefer=(placement.prefer
                        if placement is not None else None),
                prefer_slack=fleetkv.PLACEMENT_SLACK,
                model_id=model_id)
            if placement is not None:
                fleet.note_affinity(placement.depth > 0 and
                                    replica.id == placement.prefer)
            import http.client as _hc

            replica_errs = (OSError, _hc.HTTPException)
            try:
                hop_timeout, fwd_headers, eligible = \
                    self._hop_budget(deadline, tier)
                try:
                    conn, resp = replica.client.open(
                        "POST", "/generate", self._body,
                        timeout=hop_timeout, headers=fwd_headers)
                except replica_errs as e:
                    # failed before any byte reached the client: fail
                    # FAST with a structured, retryable error
                    fleet.note_request_failure(replica, e,
                                               breaker_eligible=eligible)
                    self._reply(502, replica_failed_body(
                        replica.id, f"{type(e).__name__}: {e}"))
                    return
                try:
                    if streaming and resp.status == 200:
                        self._relay_stream(replica, resp,
                                           breaker_eligible=eligible)
                        return
                    try:
                        body = resp.read()
                    except replica_errs as e:
                        # replica died mid-body; the client has seen
                        # nothing yet, so the structured 502 still fits
                        fleet.note_request_failure(
                            replica, e, breaker_eligible=eligible)
                        self._reply(502, replica_failed_body(
                            replica.id, f"{type(e).__name__}: {e}"))
                        return
                    if resp.status < 500:
                        fleet.note_request_success(replica)
                    extra = []
                    ra = resp.getheader("Retry-After")
                    if ra:
                        extra.append(("Retry-After", ra))
                    ctype = resp.getheader("Content-Type",
                                           "application/json")
                    self.send_response(resp.status)
                    self.send_header("Content-Type", ctype)
                    for k, v in extra:
                        self.send_header(k, v)
                    self.send_header("Content-Length",
                                     str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                finally:
                    conn.close()
            finally:
                fleet.release(replica, tier)

        def _relay_stream(self, replica, resp,
                          breaker_eligible: bool = True) -> None:
            """Chunked NDJSON passthrough; a mid-stream replica failure
            is reported in-band (headers are long gone). Replica reads
            and client writes fail SEPARATELY: only a replica-side
            failure is attributed to the replica — a client hanging up
            must never evict a healthy replica."""
            self.send_response(200)
            self.send_header("Content-Type",
                             resp.getheader("Content-Type",
                                            "application/x-ndjson"))
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

            def chunk(raw: bytes) -> None:
                self.wfile.write(f"{len(raw):x}\r\n".encode()
                                 + raw + b"\r\n")
                self.wfile.flush()

            try:
                while True:
                    try:
                        piece = resp.readline()  # http.client de-chunks
                    except Exception as e:  # replica died mid-stream
                        fleet.note_request_failure(
                            replica, e, breaker_eligible=breaker_eligible)
                        chunk((json.dumps({
                            "error": "replica_failed",
                            "replica": replica.id,
                            "detail": f"{type(e).__name__}: {e}"})
                            + "\n").encode())
                        break
                    if not piece:
                        fleet.note_request_success(replica)
                        break
                    chunk(piece)
                self.wfile.write(b"0\r\n\r\n")
            except Exception:  # client hung up: nothing left to tell it
                pass
            self.close_connection = True

        def _reload(self):
            data = self._read_json()
            path = data.get("path")
            if not path:
                raise ValueError("reload needs {'path': <checkpoint>}")
            step = data.get("step")
            rb_step = data.get("rollback_step")
            mid = data.get("model_id")
            result = fleet.rolling_reload(
                str(path), step=None if step is None else int(step),
                rollback_path=data.get("rollback_path"),
                rollback_step=None if rb_step is None else int(rb_step),
                probe=data.get("probe"),
                model_id=None if mid is None else str(mid))
            self._reply(200 if result.get("reloaded") else 409, result)

        def _scale(self):
            data = self._read_json()
            n = data.get("replicas")
            if not isinstance(n, int) or n < 0:
                raise ValueError("scale needs {'replicas': N >= 0}")
            result = fleet.scale_to(n)
            self._reply(200, result)

    handle.http = start_http_server(Handler, host=host, port=port)
    return handle
