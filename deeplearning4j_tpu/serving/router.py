"""Fleet router tier: the HTTP front end over out-of-process replicas.

Two pieces live here (the fleet state machine itself is
`serving/fleet.py`):

- `ReplicaClient` — a thin stdlib HTTP client for ONE replica serving
  endpoint (`serve_network`'s surface: /predict, /generate, /reload,
  /healthz, /readyz, /stats). One connection per call: the router's
  concurrency comes from its own handler threads, and a fresh
  connection per request means a dead replica fails THIS call with a
  clean OSError instead of poisoning a pooled socket.
- `serve_fleet(fleet)` — the router's own HTTP server (same
  utils/httpd.py lifecycle as every embedded server in the repo):

  - ``POST /predict``  — least-outstanding ready replica; connection
    failures and replica 5xx retry transparently on a healthy peer
    (idempotent, so at-least-once is safe); total-outstanding past the
    fleet's high-water mark sheds with 503 + Retry-After.
  - ``POST /generate`` — one ready replica, streamed straight through
    (chunked NDJSON passthrough). NOT retried: a generate is expensive
    and the stream may already be partially delivered — failures
    before the first byte answer 502 with a structured
    ``{"error": "replica_failed", "replica": ..., "retryable": true}``;
    failures mid-stream emit the same error object in-band as the
    final NDJSON line.
  - ``POST /reload``   — rolling/canary reload across the fleet
    (drain -> per-replica /reload -> /readyz probe -> readmit, one at
    a time; automatic rollback when the canary fails — Fleet.rolling_reload).
  - ``POST /scale``    — autoscaling hook: ``{"replicas": N}`` spawns
    or retires to N (requires a spawner).
  - ``GET /healthz``   — router liveness + per-state replica counts.
  - ``GET /readyz``    — 200 iff at least one replica is ready.
  - ``GET /stats``     — Fleet.snapshot().
  - ``GET /metrics`` / ``/snapshot`` — the router process's telemetry
    registry: the `dl4j_fleet_*` series (docs/OBSERVABILITY.md).

Every reply slurps the POST body first (HTTP/1.1 keep-alive would
desync otherwise — the same lesson serving/server.py carries).
"""

from __future__ import annotations

import json
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler
from typing import Optional, Tuple

from deeplearning4j_tpu.serving.errors import (DEADLINE_HEADER, Deadline,
                                               DeadlineExceededError,
                                               OverloadedError,
                                               deadline_body,
                                               overload_body)
from deeplearning4j_tpu.telemetry import exposition
from deeplearning4j_tpu.testing import chaos
from deeplearning4j_tpu.utils.httpd import ServerHandle, start_http_server

__all__ = ["ReplicaClient", "FleetHandle", "serve_fleet"]


class ReplicaClient:
    """Stdlib HTTP client for one replica serving endpoint."""

    def __init__(self, url: str, timeout: float = 30.0):
        if "//" not in url:
            url = "http://" + url
        parsed = urllib.parse.urlsplit(url)
        if parsed.hostname is None or parsed.port is None:
            raise ValueError(
                f"replica url needs host:port, got {url!r}")
        self.host = parsed.hostname
        self.port = int(parsed.port)
        self.url = f"http://{self.host}:{self.port}"
        self.timeout = float(timeout)

    # ------------------------------------------------------------- raw
    def open(self, method: str, path: str, body: Optional[bytes] = None,
             timeout: Optional[float] = None,
             headers: Optional[dict] = None):
        """Issue a request and return (connection, response) with the
        body NOT yet read — the streaming proxy relays it chunk by
        chunk. The caller owns `connection.close()`. `headers` extends
        the defaults (how the router forwards `X-Deadline-Ms`)."""
        import http.client

        conn = http.client.HTTPConnection(
            self.host, self.port,
            timeout=self.timeout if timeout is None else timeout)
        hdrs = {"Content-Type": "application/json"} if body else {}
        if headers:
            hdrs.update(headers)
        try:
            conn.request(method, path, body=body, headers=hdrs)
            resp = conn.getresponse()
        except BaseException:
            conn.close()
            raise
        return conn, resp

    def request(self, method: str, path: str,
                body: Optional[bytes] = None,
                timeout: Optional[float] = None,
                headers: Optional[dict] = None
                ) -> Tuple[int, dict, bytes]:
        """One whole request: (status, headers-dict, body-bytes)."""
        conn, resp = self.open(method, path, body, timeout,
                               headers=headers)
        try:
            data = resp.read()
            return resp.status, dict(resp.getheaders()), data
        finally:
            conn.close()

    # ------------------------------------------------------ conveniences
    def get_json(self, path: str, timeout: Optional[float] = None
                 ) -> Tuple[int, dict]:
        status, _, data = self.request("GET", path, timeout=timeout)
        try:
            payload = json.loads(data) if data else {}
        except ValueError:
            payload = {"raw": data.decode(errors="replace")}
        return status, payload

    def healthz(self, timeout: Optional[float] = None) -> dict:
        """Liveness probe; raises on connection failure or non-200."""
        status, payload = self.get_json("/healthz", timeout)
        if status != 200:
            raise RuntimeError(f"healthz answered {status}")
        return payload

    def readyz(self, timeout: Optional[float] = None
               ) -> Tuple[bool, dict]:
        """Readiness probe: (ready, payload). Connection failures
        propagate (the caller distinguishes dead from not-ready)."""
        status, payload = self.get_json("/readyz", timeout)
        return status == 200, payload

    def stats(self, timeout: Optional[float] = None) -> dict:
        status, payload = self.get_json("/stats", timeout)
        if status != 200:
            raise RuntimeError(f"stats answered {status}")
        return payload


class FleetHandle:
    """A running fleet router: http handle + the fleet behind it."""

    def __init__(self, fleet, http: Optional[ServerHandle] = None):
        self.fleet = fleet
        self.http = http
        self.started_at = time.time()

    @property
    def url(self) -> str:
        return self.http.url

    @property
    def port(self) -> int:
        return self.http.port

    def close(self, stop_replicas: bool = False,
              handoff: bool = False) -> None:
        """Stop routing, then stop the fleet's control plane (and the
        spawned replica processes too when `stop_replicas`).
        `handoff=True` leaves the journaled replicas running for the
        next router incarnation to re-adopt (docs/FLEET.md "Router
        restart runbook")."""
        self.http.close()
        self.fleet.close(stop_replicas=stop_replicas, handoff=handoff)

    def __enter__(self) -> "FleetHandle":
        return self

    def __exit__(self, *exc) -> None:
        # mirror Fleet.__exit__: spawned replica processes die with the
        # context (attached-by-URL replicas are never touched)
        self.close(stop_replicas=True)


def serve_fleet(fleet, host: str = "127.0.0.1",
                port: int = 0) -> FleetHandle:
    """Start the router HTTP tier over a (started) Fleet."""
    from deeplearning4j_tpu.serving.fleet import NoReadyReplicas

    handle = FleetHandle(fleet)

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"  # streaming passthrough needs it

        def log_message(self, *args):  # quiet
            pass

        def _reply(self, code: int, payload: dict,
                   extra_headers=()) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            for k, v in extra_headers:
                self.send_header(k, v)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _reply_raw(self, code: int, ctype: str, body: bytes) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _reply_overloaded(self, e: OverloadedError) -> None:
            self._reply(503, overload_body(e),
                        extra_headers=[("Retry-After",
                                        str(e.retry_after_s))])

        # ----------------------------------------------------- routes
        def do_GET(self):
            try:
                if self.path.startswith("/healthz"):
                    self._reply(200, {"ok": True,
                                      "replicas": fleet.state_counts(),
                                      "incarnation": fleet.incarnation})
                elif self.path.startswith("/readyz"):
                    n = fleet.ready_count()
                    self._reply(200 if n else 503,
                                {"ready": n > 0, "ready_replicas": n})
                elif self.path.startswith("/stats"):
                    self._reply(200, {
                        "uptime_s": round(
                            time.time() - handle.started_at, 3),
                        "fleet": fleet.snapshot()})
                elif (hit := exposition.handle_metrics_get(
                        self.path)) is not None:
                    self._reply_raw(*hit)
                else:
                    self._reply(404, {"error": f"no route {self.path}"})
            except Exception as e:  # always answer with a status line
                self._reply(500, {"error": f"{type(e).__name__}: {e}"})

        def do_POST(self):
            # slurp the body BEFORE any reply (keep-alive framing)
            length = int(self.headers.get("Content-Length") or 0)
            self._body = self.rfile.read(length) if length > 0 else None
            try:
                chaos.hit("router.forward", path=self.path)
                if self.path.startswith("/predict"):
                    self._predict()
                elif self.path.startswith("/generate"):
                    self._generate()
                elif self.path.startswith("/reload"):
                    self._reload()
                elif self.path.startswith("/scale"):
                    self._scale()
                else:
                    self._reply(404, {"error": f"no route {self.path}"})
            except OverloadedError as e:
                self._reply_overloaded(e)
            except DeadlineExceededError as e:
                # the machine-readable budget-spent shape — same wire
                # contract as the replica server's 504
                self._reply(504, deadline_body(e))
            except NoReadyReplicas as e:
                self._reply(503, {"error": "no_ready_replicas",
                                  "detail": str(e)},
                            extra_headers=[("Retry-After", "1")])
            except (ValueError, KeyError, TypeError) as e:
                self._reply(400, {"error": str(e)})
            except Exception as e:
                self._reply(500, {"error": f"{type(e).__name__}: {e}"})

        def _read_json(self) -> dict:
            if self._body is None:
                raise ValueError("missing request body")
            data = json.loads(self._body)
            if not isinstance(data, dict):
                raise ValueError("request body must be a JSON object")
            return data

        def _predict(self):
            if self._body is None:
                raise ValueError("missing request body")
            # header-borne budget (clients of the router speak the
            # header; the router forwards the SHRUNK remainder)
            deadline = Deadline.from_request(self.headers)
            status, headers, data = fleet.forward_predict(
                self._body, deadline=deadline)
            ctype = headers.get("Content-Type", "application/json")
            extra = [("Retry-After", headers["Retry-After"])] \
                if "Retry-After" in headers else []
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            for k, v in extra:
                self.send_header(k, v)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _generate(self):
            data = self._read_json()  # parsed for stream/deadline
            streaming = bool(data.get("stream", False))
            deadline = Deadline.from_request(self.headers, data)
            if deadline is not None and deadline.expired:
                fleet._m_deadline["generate"].inc()
                deadline.check("router dispatch")  # raises -> 504
            replica = fleet.select(route="generate")
            start = time.perf_counter()
            import http.client as _hc

            replica_errs = (OSError, _hc.HTTPException)
            try:
                if deadline is None:
                    hop_timeout, fwd_headers = fleet.generate_timeout, None
                else:
                    # generate is never replayed, so the whole remaining
                    # budget rides this one hop
                    hop_timeout = deadline.timeout(fleet.generate_timeout)
                    fwd_headers = {DEADLINE_HEADER:
                                   deadline.header_value()}
                # a timeout at a deadline-sliced window shorter than a
                # fair wait says the CLIENT was impatient, not that the
                # replica hung — same eligibility rule forward_predict
                # applies (fleet.note_request_failure's contract)
                eligible = hop_timeout >= min(fleet.generate_timeout,
                                              fleet.probe_timeout)
                try:
                    conn, resp = replica.client.open(
                        "POST", "/generate", self._body,
                        timeout=hop_timeout, headers=fwd_headers)
                except replica_errs as e:
                    # failed before any byte reached the client: fail
                    # FAST with a structured, retryable error (the
                    # router never replays a generate itself)
                    fleet.note_request_failure(replica, e,
                                               breaker_eligible=eligible)
                    self._reply(502, {
                        "error": "replica_failed",
                        "replica": replica.id,
                        "detail": f"{type(e).__name__}: {e}",
                        "retryable": True})
                    return
                try:
                    if streaming and resp.status == 200:
                        self._relay_stream(replica, resp,
                                           breaker_eligible=eligible)
                        return
                    try:
                        body = resp.read()
                    except replica_errs as e:
                        # replica died mid-body; the client has seen
                        # nothing yet, so the structured 502 still fits
                        fleet.note_request_failure(
                            replica, e, breaker_eligible=eligible)
                        self._reply(502, {
                            "error": "replica_failed",
                            "replica": replica.id,
                            "detail": f"{type(e).__name__}: {e}",
                            "retryable": True})
                        return
                    if resp.status < 500:
                        fleet.note_request_success(replica)
                    extra = []
                    ra = resp.getheader("Retry-After")
                    if ra:
                        extra.append(("Retry-After", ra))
                    ctype = resp.getheader("Content-Type",
                                           "application/json")
                    self.send_response(resp.status)
                    self.send_header("Content-Type", ctype)
                    for k, v in extra:
                        self.send_header(k, v)
                    self.send_header("Content-Length",
                                     str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                finally:
                    conn.close()
            finally:
                fleet.release(replica)
                fleet.observe("generate", time.perf_counter() - start)

        def _relay_stream(self, replica, resp,
                          breaker_eligible: bool = True) -> None:
            """Chunked NDJSON passthrough; a mid-stream replica failure
            is reported in-band (headers are long gone). Replica reads
            and client writes fail SEPARATELY: only a replica-side
            failure is attributed to the replica — a client hanging up
            must never evict a healthy replica."""
            self.send_response(200)
            self.send_header("Content-Type",
                             resp.getheader("Content-Type",
                                            "application/x-ndjson"))
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

            def chunk(raw: bytes) -> None:
                self.wfile.write(f"{len(raw):x}\r\n".encode()
                                 + raw + b"\r\n")
                self.wfile.flush()

            try:
                while True:
                    try:
                        piece = resp.readline()  # http.client de-chunks
                    except Exception as e:  # replica died mid-stream
                        fleet.note_request_failure(
                            replica, e, breaker_eligible=breaker_eligible)
                        chunk((json.dumps({
                            "error": "replica_failed",
                            "replica": replica.id,
                            "detail": f"{type(e).__name__}: {e}"})
                            + "\n").encode())
                        break
                    if not piece:
                        fleet.note_request_success(replica)
                        break
                    chunk(piece)
                self.wfile.write(b"0\r\n\r\n")
            except Exception:  # client hung up: nothing left to tell it
                pass
            self.close_connection = True

        def _reload(self):
            data = self._read_json()
            path = data.get("path")
            if not path:
                raise ValueError("reload needs {'path': <checkpoint>}")
            step = data.get("step")
            rb_step = data.get("rollback_step")
            result = fleet.rolling_reload(
                str(path), step=None if step is None else int(step),
                rollback_path=data.get("rollback_path"),
                rollback_step=None if rb_step is None else int(rb_step),
                probe=data.get("probe"))
            self._reply(200 if result.get("reloaded") else 409, result)

        def _scale(self):
            data = self._read_json()
            n = data.get("replicas")
            if not isinstance(n, int) or n < 0:
                raise ValueError("scale needs {'replicas': N >= 0}")
            result = fleet.scale_to(n)
            self._reply(200, result)

    handle.http = start_http_server(Handler, host=host, port=port)
    return handle
