"""Preallocated KV cache: O(1)-per-token transformer decode.

The demo `transformer.generate` recomputes the full prefix every token —
O(T) attention AND O(T) ffn/embedding work per emitted token. Serving
needs the standard two-phase shape (the "portable O(1) autoregressive
caching" design in PAPERS.md):

- **prefill**: one pass over the prompt (flash attention, same math as
  `transformer_logits`) that also writes every block's K/V into a
  preallocated `(B, H, max_len, hd)` buffer;
- **decode**: one token per step — project q/k/v for the single new
  position, write k/v at the cursor, attend over the cache with a
  `position <= cursor` mask. Per-token work no longer grows with the
  number of generated tokens' recompute (the masked-score sweep over the
  fixed buffer is one fused (B,H,1,L) einsum).

Shapes are fixed by `cfg.max_len`, so the whole generate loop (prefill +
`lax.scan` of decode steps) is ONE compiled program per
(batch, prompt_len, n_tokens) signature — the cursor is a traced scalar,
never a shape. Parity: `generate(cache=True)` matches the naive path to
1e-5 (tests/test_serving.py) because both run the same block math; the
only difference is exact masked softmax here vs online softmax there.

Memory envelope: 2 (K and V) * n_layers * B * max_len * d_model elements
per cache — `kv_cache_bytes` computes it; docs/SERVING.md budgets it.
"""

from __future__ import annotations

from functools import partial
from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.attention.blockwise import NEG_INF
from deeplearning4j_tpu.attention.flash_pallas import flash_attention
from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                   _layer_norm)

__all__ = ["KVCache", "init_cache", "kv_cache_bytes", "prefill",
           "decode_step", "generate_cached"]


class KVCache(NamedTuple):
    """Per-block K/V buffers plus the write cursor.

    `layers`: tuple (one per transformer block) of {"k", "v"} arrays of
    shape (B, n_heads, max_len, head_dim); positions >= `cursor` are
    unwritten zeros, masked out of every attention sweep.
    """

    layers: Tuple[Any, ...]
    cursor: jax.Array  # int32 scalar: number of filled positions


def _check_cache_args(batch_size: int, length, max_len: int) -> int:
    """Shared validation: `length=None` means the full window; an
    EXPLICIT length=0 (or negative) is rejected — the old `length or
    max_len` idiom silently allocated the full window for it, which is
    never what a caller asking for a 0-length cache meant."""
    if batch_size < 1:
        raise ValueError(f"batch_size must be >= 1, got {batch_size}")
    if length is None:
        return max_len
    if length < 1:
        raise ValueError(
            f"length must be >= 1, got {length} (omit it or pass None "
            f"for the full max_len window)")
    return length


def init_cache(cfg: TransformerConfig, batch_size: int,
               length: int = None) -> KVCache:
    """Empty cache for `batch_size` streams. `length` defaults to
    cfg.max_len — always allocating the full window keeps decode-step
    shapes identical across requests (one program, any prompt)."""
    length = _check_cache_args(batch_size, length, cfg.max_len)
    hd = cfg.d_model // cfg.n_heads
    shape = (batch_size, cfg.n_heads, length, hd)
    layers = tuple({"k": jnp.zeros(shape, cfg.dtype),
                    "v": jnp.zeros(shape, cfg.dtype)}
                   for _ in range(cfg.n_layers))
    return KVCache(layers, jnp.int32(0))


def kv_cache_bytes(cfg: TransformerConfig, batch_size: int,
                   length: int = None) -> int:
    """HBM the cache pins per batch — the serving memory envelope for
    the contiguous path (the paged pool's twin is
    `paged_kv.paged_kv_bytes`, which budgets pages, not requests)."""
    length = _check_cache_args(batch_size, length, cfg.max_len)
    itemsize = jnp.dtype(cfg.dtype).itemsize
    return 2 * cfg.n_layers * batch_size * length * cfg.d_model * itemsize


def _heads(h, w, cfg: TransformerConfig):
    b, t, d = h.shape
    hd = d // cfg.n_heads
    return (h @ w).reshape(b, t, cfg.n_heads, hd).transpose(0, 2, 1, 3)


def _ffn(p, x):
    h = _layer_norm(p["ln2"], x)
    return x + jax.nn.gelu(h @ p["W1"] + p["b1"]) @ p["W2"] + p["b2"]


def prefill(params, tokens, cache: KVCache, cfg: TransformerConfig):
    """Run the prompt (B, T0) through every block, writing K/V into the
    cache at positions [0, T0). Returns (last-position logits (B, vocab),
    cache with cursor=T0). Starts a fresh stream: any prior cache content
    is overwritten from position 0."""
    b, t0 = tokens.shape
    x = params["embed"][tokens] + params["pos"][:t0]
    new_layers = []
    for p, layer in zip(params["blocks"], cache.layers):
        h = _layer_norm(p["ln1"], x)
        q = _heads(h, p["Wq"], cfg)
        k = _heads(h, p["Wk"], cfg)
        v = _heads(h, p["Wv"], cfg)
        att = flash_attention(q, k, v, True, interpret=cfg.interpret)
        att = att.transpose(0, 2, 1, 3).reshape(b, t0, cfg.d_model)
        x = x + att @ p["Wo"]
        x = _ffn(p, x)
        new_layers.append({
            "k": jax.lax.dynamic_update_slice(
                layer["k"], k.astype(layer["k"].dtype), (0, 0, 0, 0)),
            "v": jax.lax.dynamic_update_slice(
                layer["v"], v.astype(layer["v"].dtype), (0, 0, 0, 0)),
        })
    x = _layer_norm(params["ln_f"], x)
    logits = x[:, -1, :] @ params["embed"].T
    return logits, KVCache(tuple(new_layers), jnp.int32(t0))


def decode_step(params, token, cache: KVCache, cfg: TransformerConfig):
    """One decode step: embed `token` (B,) at position `cache.cursor`,
    attend over the cache, return (logits (B, vocab), advanced cache).
    Fixed shapes throughout — the cursor is traced, so every step of
    every request shares one compiled program."""
    b = token.shape[0]
    d = cfg.d_model
    hd = d // cfg.n_heads
    cur = cache.cursor
    pos = jax.lax.dynamic_slice_in_dim(params["pos"], cur, 1, axis=0)
    x = params["embed"][token][:, None, :] + pos  # (B, 1, d)
    length = cache.layers[0]["k"].shape[2]
    mask = jnp.arange(length) <= cur  # (L,): positions filled after write
    scale = 1.0 / jnp.sqrt(jnp.float32(hd))
    new_layers = []
    for p, layer in zip(params["blocks"], cache.layers):
        h = _layer_norm(p["ln1"], x)
        q = _heads(h, p["Wq"], cfg)                        # (B, H, 1, hd)
        k_new = _heads(h, p["Wk"], cfg).astype(layer["k"].dtype)
        v_new = _heads(h, p["Wv"], cfg).astype(layer["v"].dtype)
        ks = jax.lax.dynamic_update_slice(layer["k"], k_new, (0, 0, cur, 0))
        vs = jax.lax.dynamic_update_slice(layer["v"], v_new, (0, 0, cur, 0))
        # exact masked softmax in f32 over the fixed-length buffer
        s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                       ks.astype(jnp.float32)) * scale
        s = jnp.where(mask[None, None, None, :], s, NEG_INF)
        w = jax.nn.softmax(s, axis=-1)
        att = jnp.einsum("bhqk,bhkd->bhqd", w, vs.astype(jnp.float32))
        att = att.astype(x.dtype).transpose(0, 2, 1, 3).reshape(b, 1, d)
        x = x + att @ p["Wo"]
        x = _ffn(p, x)
        new_layers.append({"k": ks, "v": vs})
    x = _layer_norm(params["ln_f"], x)
    logits = x[:, 0, :] @ params["embed"].T
    return logits, KVCache(tuple(new_layers), cur + 1)


@partial(jax.jit, static_argnums=(2, 3))
def generate_cached(params, prompt, cfg: TransformerConfig,
                    n_tokens: int):
    """Greedy decode with the KV cache: prompt (B, T0) ->
    (B, T0 + n_tokens), same contract (and same tokens, to decode-order
    tie-breaks) as the naive `transformer.generate`. One compiled
    program per (B, T0, n_tokens) signature; the decode loop is a
    `lax.scan` whose body is a single O(1) step."""
    b, t0 = prompt.shape
    # shapes and n_tokens are static here, so these guard EVERY entry
    # point (engine.generate, HTTP /generate) at trace time — without
    # them an overlong decode would silently clamp the cursor into the
    # last KV slot and emit garbage
    if n_tokens < 1:
        raise ValueError(f"n_tokens must be >= 1, got {n_tokens}")
    if t0 + n_tokens > cfg.max_len:
        raise ValueError(
            f"generation would exceed max_len ({t0} prompt + {n_tokens} "
            f"new > {cfg.max_len})")
    cache = init_cache(cfg, b)
    logits, cache = prefill(params, prompt, cache, cfg)
    first = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # token at t0

    def step(carry, _):
        cache, tok = carry
        logits, cache = decode_step(params, tok, cache, cfg)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (cache, nxt), tok

    if n_tokens == 1:
        gen = first[:, None]
    else:
        (_, last), emitted = jax.lax.scan(
            step, (cache, first), None, length=n_tokens - 1)
        gen = jnp.concatenate(
            [jnp.moveaxis(emitted, 0, 1), last[:, None]], axis=1)
    return jnp.concatenate([prompt, gen], axis=1)
