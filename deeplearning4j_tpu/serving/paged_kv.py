"""Paged KV cache: block-pool K/V storage for continuous-batching decode.

The contiguous `KVCache` (kv_cache.py) reserves `(B, H, max_len, hd)`
per request — HBM for the worst case, not for the tokens actually
written, and one slow request holds its whole batch's reservation until
the batch finishes. This module stores KV in a shared **block pool** of
fixed-size pages (the PagedAttention design carried into the repo's
portable O(1)-cache decode, PAPERS.md arXiv:2603.09555):

- per layer, one `(n_pages + 1, n_heads, page_size, head_dim)` pool for
  K and one for V. The LAST page is the **trash page**: masked slots
  (inactive / paused) direct their writes there so the scatter in the
  compiled step never needs a data-dependent shape. The host allocator
  never hands the trash page out.
- a per-slot **page table** `(S, pages_per_slot)` of pool indices maps a
  slot's logical positions `[0, max_len)` onto physical pages.
  Unallocated entries hold the trash index so gathers are always valid
  (their positions are masked out of attention by the slot's length).

KV memory therefore scales with tokens actually written: a slot holds
`ceil(tokens / page_size)` pages, pages return to the pool the moment a
request completes, and admission is a free-page check instead of a
whole-`max_len` reservation (`serving/decode_loop.py` owns that
accounting; `paged_kv_bytes` is the envelope).

Shapes in both compiled entry points are fixed for the life of the
server: `paged_decode_step` is ONE program over S slots (page table,
lengths and the active mask are traced arrays — requests join and leave
without recompiling), `paged_prefill` compiles one program per
prompt-length bucket (buckets are page multiples, `prompt_buckets`).

Parity: positions beyond a slot's length are masked to NEG_INF before
the softmax, so `exp` underflows to exactly 0 and garbage in unwritten
page tails contributes exactly 0 — the paged step is the contiguous
`decode_step` to float tolerance (tests/test_paged_decode.py pins 1e-5
teacher-forced).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.attention.blockwise import NEG_INF
from deeplearning4j_tpu.attention.flash_pallas import flash_attention
from deeplearning4j_tpu.attention.paged_pallas import paged_attention
from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                   _layer_norm)
from deeplearning4j_tpu.serving.kv_cache import _ffn, _heads

__all__ = ["PagedKVPool", "init_paged_pool", "paged_kv_bytes",
           "pages_per_slot", "pages_for_tokens", "prompt_buckets",
           "paged_prefill", "paged_prefill_ctx", "paged_decode_step",
           "paged_verify_step", "copy_page", "extract_page",
           "install_page", "decode_read_bytes"]


class PagedKVPool(NamedTuple):
    """Per-block K/V page pools. `layers`: tuple (one per transformer
    block) of {"k", "v"} arrays of shape (n_pages + 1, n_heads,
    page_size, head_dim); index `n_pages` (the last page) is the trash
    page for masked writes."""

    layers: Tuple[Any, ...]

    @property
    def page_size(self) -> int:
        return self.layers[0]["k"].shape[2]

    @property
    def n_pages(self) -> int:
        """Usable pages (the trash page is excluded)."""
        return self.layers[0]["k"].shape[0] - 1

    @property
    def trash_page(self) -> int:
        return self.layers[0]["k"].shape[0] - 1


def pages_per_slot(cfg: TransformerConfig, page_size: int) -> int:
    """Page-table width: pages covering the model's full window."""
    return -(-cfg.max_len // page_size)


def pages_for_tokens(n_tokens: int, page_size: int) -> int:
    """Physical pages holding `n_tokens` written positions."""
    return -(-n_tokens // page_size)


def prompt_buckets(cfg: TransformerConfig, page_size: int
                   ) -> Tuple[int, ...]:
    """Prefill prompt-length buckets: page-multiple powers of two up to
    the full window, so ragged prompts compile a handful of prefill
    programs, ever (the DeviceFeed ladder idea applied to T)."""
    top = pages_per_slot(cfg, page_size) * page_size
    buckets, b = [], page_size
    while b < top:
        buckets.append(b)
        b *= 2
    buckets.append(top)
    return tuple(buckets)


def init_paged_pool(cfg: TransformerConfig, n_pages: int,
                    page_size: int) -> PagedKVPool:
    """Allocate the block pool (`n_pages` usable + 1 trash page per
    layer). Pool HBM is fixed at construction — per-request cost is
    page-table bookkeeping, not allocation."""
    if n_pages < 1:
        raise ValueError(f"n_pages must be >= 1, got {n_pages}")
    if page_size < 1:
        raise ValueError(f"page_size must be >= 1, got {page_size}")
    hd = cfg.d_model // cfg.n_heads
    shape = (n_pages + 1, cfg.n_heads, page_size, hd)
    layers = tuple({"k": jnp.zeros(shape, cfg.dtype),
                    "v": jnp.zeros(shape, cfg.dtype)}
                   for _ in range(cfg.n_layers))
    return PagedKVPool(layers)


def paged_kv_bytes(cfg: TransformerConfig, n_pages: int,
                   page_size: int) -> int:
    """HBM the whole pool pins (including the trash page) — the serving
    memory envelope. Unlike the contiguous `kv_cache_bytes(cfg, B)` this
    is independent of concurrency: occupancy (pages in use / n_pages)
    is the load signal, exported as dl4j_kv_pages_{total,in_use}."""
    if n_pages < 1:
        raise ValueError(f"n_pages must be >= 1, got {n_pages}")
    if page_size < 1:
        raise ValueError(f"page_size must be >= 1, got {page_size}")
    itemsize = jnp.dtype(cfg.dtype).itemsize
    return (2 * cfg.n_layers * (n_pages + 1) * page_size
            * cfg.d_model * itemsize)


def paged_prefill(params, tokens, true_len, pool: PagedKVPool,
                  page_ids, cfg: TransformerConfig):
    """Run a BATCH of padded prompts (B, Tb) through every block in one
    dispatch, scattering each row's K/V into the pool pages listed in
    its `page_ids` row (shape (B, Tb/page_size); entries past a row's
    real pages — and every entry of a padding row — hold the trash
    index). `true_len` is (B,); returns (logits (B, vocab), each row at
    its own position `true_len - 1`, updated pool).

    Batching matters: an admission burst (N queued prompts hitting
    freed slots between decode steps) costs one compiled call instead
    of N — the scheduler pads B up to a small pow2 ladder so program
    count stays bounded (DecodeLoop._admit).

    Same math as the contiguous `prefill` — causal flash attention means
    positions < true_len never see the zero-padding, and the padding's
    garbage K/V lands either in the real last page's tail (masked out of
    decode by the slot length) or on the trash page."""
    b, tb = tokens.shape
    ps = pool.page_size
    # the page-multiple bucket can overshoot max_len (e.g. max_len=100,
    # page_size=16 -> top bucket 112): clamp the position ids so the
    # overshoot rows (pure padding, causally invisible to real
    # positions) reuse the last embedding instead of reading OOB
    pos_ids = jnp.minimum(jnp.arange(tb), cfg.max_len - 1)
    x = params["embed"][tokens] + params["pos"][pos_ids]
    flat_ids = page_ids.reshape(-1)                    # (B * Tb/ps,)
    new_layers = []
    for p, layer in zip(params["blocks"], pool.layers):
        h = _layer_norm(p["ln1"], x)
        q = _heads(h, p["Wq"], cfg)
        k = _heads(h, p["Wk"], cfg)
        v = _heads(h, p["Wv"], cfg)
        att = flash_attention(q, k, v, True, interpret=cfg.interpret)
        att = att.transpose(0, 2, 1, 3).reshape(b, tb, cfg.d_model)
        x = x + att @ p["Wo"]
        x = _ffn(p, x)
        # (B, H, Tb, hd) -> (B * Tb/ps pages, H, ps, hd) page scatter
        def pages(arr, like):
            a = arr.astype(like.dtype)
            a = a.reshape(b, cfg.n_heads, tb // ps, ps, -1)
            return a.transpose(0, 2, 1, 3, 4).reshape(
                b * (tb // ps), cfg.n_heads, ps, -1)
        new_layers.append({
            "k": layer["k"].at[flat_ids].set(pages(k, layer["k"])),
            "v": layer["v"].at[flat_ids].set(pages(v, layer["v"])),
        })
    x = _layer_norm(params["ln_f"], x)
    # gather each row's LAST REAL position before the vocab projection —
    # (B, d) @ (d, vocab) instead of a (B, Tb, vocab) matmul
    idx = jnp.broadcast_to((true_len - 1)[:, None, None],
                           (b, 1, cfg.d_model))
    last_x = jnp.take_along_axis(x, idx, axis=1)[:, 0, :]
    return last_x @ params["embed"].T, PagedKVPool(tuple(new_layers))


def copy_page(pool: PagedKVPool, src, dst) -> PagedKVPool:
    """Copy-on-write fork helper: duplicate ONE physical page (every
    layer's K and V rows) from pool index `src` into `dst`. `src`/`dst`
    are traced int32 scalars, so the jitted caller compiles exactly one
    program for every fork the server ever performs — the only compiled
    surface prefix sharing adds (decode_loop.DecodeLoop)."""
    layers = tuple({"k": layer["k"].at[dst].set(layer["k"][src]),
                    "v": layer["v"].at[dst].set(layer["v"][src])}
                   for layer in pool.layers)
    return PagedKVPool(layers)


def extract_page(pool: PagedKVPool, page: int):
    """Host-side copy of ONE physical page across every layer — the
    fleet KV plane's export read (serving/fleetkv.py). Returns a list
    of (k, v) numpy arrays of shape (n_heads, page_size, head_dim),
    one pair per layer. Pure reads on the immutable pool arrays: a
    concurrent pool swap in the decode loop cannot tear a page whose
    content is pinned (CoW writers fork elsewhere)."""
    import numpy as np

    return [(np.asarray(layer["k"][page]), np.asarray(layer["v"][page]))
            for layer in pool.layers]


def install_page(pool: PagedKVPool, page: int, chunk) -> PagedKVPool:
    """Write one shipped page's K/V rows (`chunk[l] = (k, v)` per
    layer, the `extract_page` shape) into pool index `page`. Eager
    single-page scatters — constant shapes, so XLA caches one program
    per dtype regardless of how many pages ever ship, and nothing here
    touches the decode loop's jitted program set."""
    if len(chunk) != len(pool.layers):
        raise ValueError(
            f"shipped page has {len(chunk)} layers, pool has "
            f"{len(pool.layers)}")
    want = pool.layers[0]["k"].shape[1:]
    layers = []
    for layer, (k, v) in zip(pool.layers, chunk):
        if tuple(k.shape) != tuple(want) or tuple(v.shape) != tuple(want):
            raise ValueError(
                f"shipped page shape {tuple(k.shape)} != pool page "
                f"shape {tuple(want)}")
        layers.append({"k": layer["k"].at[page].set(k),
                       "v": layer["v"].at[page].set(v)})
    return PagedKVPool(tuple(layers))


def paged_prefill_ctx(params, tokens, true_len, pool: PagedKVPool,
                      page_ids, ctx_table, ctx_len,
                      cfg: TransformerConfig):
    """Prefill a batch of prompt TAILS whose prefix K/V already sits in
    pool pages (the prefix-cache warm path): row b's tokens are prompt
    positions `[ctx_len[b], ctx_len[b] + true_len[b])`, its cached
    prefix occupies the pages in `ctx_table[b]` (trash-padded, masked by
    `ctx_len`), and its tail K/V scatters into `page_ids[b]` exactly
    like `paged_prefill`. Returns (logits (B, vocab) at each row's last
    real tail position, updated pool).

    Tails always start on a page boundary (the admission path only
    reuses FULL cached chunks), so the whole-page scatter reshape is
    unchanged. Attention is the decode step's exact masked softmax in
    f32 over [gathered prefix pages ‖ tail], not the flash kernel —
    tail queries see every real prefix position plus the causal window
    of the tail itself; masked lanes underflow to exactly 0 so trash /
    page-tail garbage contributes exactly 0. Shared prefix pages are
    only READ — sharing stays host-side bookkeeping."""
    b, tb = tokens.shape
    ps = pool.page_size
    hd = cfg.d_model // cfg.n_heads
    w_ctx = ctx_table.shape[1] * ps
    pos_ids = jnp.minimum(ctx_len[:, None] + jnp.arange(tb),
                          cfg.max_len - 1)
    x = params["embed"][tokens] + params["pos"][pos_ids]
    flat_ids = page_ids.reshape(-1)
    # prefix cols real below ctx_len; tail cols causal within the tail
    m_ctx = jnp.arange(w_ctx)[None, :] < ctx_len[:, None]      # (B, Wc)
    m_self = (jnp.arange(tb)[None, :] <= jnp.arange(tb)[:, None])
    scale = 1.0 / jnp.sqrt(jnp.float32(hd))
    new_layers = []
    for p, layer in zip(params["blocks"], pool.layers):
        h = _layer_norm(p["ln1"], x)
        q = _heads(h, p["Wq"], cfg)                   # (B, H, Tb, hd)
        k = _heads(h, p["Wk"], cfg)
        v = _heads(h, p["Wv"], cfg)
        # gather the cached prefix: (B, Pc, H, ps, hd) -> (B, H, Wc, hd)
        kc = layer["k"][ctx_table].transpose(0, 2, 1, 3, 4).reshape(
            b, cfg.n_heads, w_ctx, hd)
        vc = layer["v"][ctx_table].transpose(0, 2, 1, 3, 4).reshape(
            b, cfg.n_heads, w_ctx, hd)
        qf = q.astype(jnp.float32)
        sc_ctx = jnp.einsum("bhqd,bhkd->bhqk", qf,
                            kc.astype(jnp.float32)) * scale
        sc_self = jnp.einsum("bhqd,bhkd->bhqk", qf,
                             k.astype(jnp.float32)) * scale
        sc = jnp.concatenate([
            jnp.where(m_ctx[:, None, None, :], sc_ctx, NEG_INF),
            jnp.where(m_self[None, None, :, :], sc_self, NEG_INF),
        ], axis=-1)
        wts = jax.nn.softmax(sc, axis=-1)
        vf = jnp.concatenate([vc.astype(jnp.float32),
                              v.astype(jnp.float32)], axis=2)
        att = jnp.einsum("bhqk,bhkd->bhqd", wts, vf)
        att = att.astype(x.dtype).transpose(0, 2, 1, 3).reshape(
            b, tb, cfg.d_model)
        x = x + att @ p["Wo"]
        x = _ffn(p, x)

        # (B, H, Tb, hd) -> (B * Tb/ps pages, H, ps, hd) page scatter,
        # identical to paged_prefill's
        def pages(arr, like):
            a = arr.astype(like.dtype)
            a = a.reshape(b, cfg.n_heads, tb // ps, ps, -1)
            return a.transpose(0, 2, 1, 3, 4).reshape(
                b * (tb // ps), cfg.n_heads, ps, -1)
        new_layers.append({
            "k": layer["k"].at[flat_ids].set(pages(k, layer["k"])),
            "v": layer["v"].at[flat_ids].set(pages(v, layer["v"])),
        })
    x = _layer_norm(params["ln_f"], x)
    idx = jnp.broadcast_to((true_len - 1)[:, None, None],
                           (b, 1, cfg.d_model))
    last_x = jnp.take_along_axis(x, idx, axis=1)[:, 0, :]
    return last_x @ params["embed"].T, PagedKVPool(tuple(new_layers))


def decode_read_bytes(pool: PagedKVPool, lengths, table_width: int, *,
                      dense: bool = False) -> int:
    """Host-side accounting: KV bytes ONE decode token step must read
    for attention, summed over slots. Default (`dense=False`) is the
    streamed-kernel figure — K+V for each slot's written pages only,
    `min(floor(pos / page_size) + 1, table_width)` pages at cursor
    `pos` (exactly the pages `paged_attention`'s grid computes, the
    trash-page read of an idle slot included). `dense=True` is the
    dense-gather figure: every slot touches its FULL page-table
    reservation (`S × table_width` pages) regardless of how little was
    written. The ratio of the two is the kernel's traffic win, exported
    per dispatch as dl4j_decode_kv_read_bytes{path="kernel"|"gather"}
    (decode_loop; docs/OBSERVABILITY.md)."""
    layer = pool.layers[0]["k"]
    ps = pool.page_size
    page_bytes = (layer.shape[1] * ps * layer.shape[3]
                  * jnp.dtype(layer.dtype).itemsize)
    if dense:
        pages = len(lengths) * int(table_width)
    else:
        pages = sum(min(int(pos) // ps + 1, int(table_width))
                    for pos in lengths)
    return 2 * len(pool.layers) * page_bytes * int(pages)


def paged_verify_step(params, tokens, pool: PagedKVPool, page_table,
                      lengths, widths, cfg: TransformerConfig,
                      kernel: str = "gather"):
    """The WIDENED decode step speculative verify rides: `tokens` is
    (S, W) — row s's column j is the token whose K/V belongs at cursor
    `lengths[s] + j` (column 0 is the slot's ordinary pending token,
    columns 1..W-1 the drafter's proposals). `widths` (S,) int32 is how
    many columns of each row are real (0 = idle slot; 1 = plain
    non-speculative step riding along). Returns
    (logits (S, W, vocab), updated pool).

    All real positions write K/V through the page table in one
    dispatch (columns past a row's width write to the trash page, same
    contract as `paged_decode_step`'s inactive slots) and every query
    attends causally — column j sees positions <= lengths[s] + j, so
    draft K/V written "in the future" of a query is masked exactly like
    unwritten page-tail garbage. logits[s, j] is therefore the target
    model's next-token distribution after the prefix extended by
    proposals 1..j — the verify/accept rule's ground truth. Rejected
    columns leave garbage at positions past the rolled-back cursor:
    always masked (key position > every later query's cursor is
    impossible — the cursor only moves forward over freshly-written
    positions), then overwritten before ever becoming visible.

    `kernel` mirrors `paged_decode_step`: "gather" runs one widened
    masked-softmax over the dense window; "pallas" reuses the
    single-query streamed kernel once per column (KV reads are
    inherently O(W x written pages) either way — speculation's win is
    amortizing the weight sweep and dispatch, not the KV reads)."""
    if kernel not in ("gather", "pallas"):
        raise ValueError(
            f"kernel must be 'gather' or 'pallas' here (resolve 'auto' "
            f"via attention.paged_pallas.resolve_decode_kernel), "
            f"got {kernel!r}")
    s, w = tokens.shape
    d = cfg.d_model
    hd = d // cfg.n_heads
    ps = pool.page_size
    trash = pool.trash_page
    n_p = page_table.shape[1]
    window = n_p * ps
    pos = lengths[:, None] + jnp.arange(w)[None, :]        # (S, W)
    valid = jnp.arange(w)[None, :] < widths[:, None]       # (S, W)
    # physical destination per (slot, column); invalid columns and
    # cursors at/past the window write to trash (paged_decode_step's
    # exact rule, widened)
    dest = jnp.where(
        valid & (pos // ps < n_p),
        jnp.take_along_axis(page_table, jnp.minimum(pos // ps, n_p - 1),
                            axis=1),
        trash)
    offset = pos % ps
    pos_ids = jnp.minimum(pos, cfg.max_len - 1)
    x = params["embed"][tokens] + params["pos"][pos_ids]   # (S, W, d)
    # per-query causal mask over the logical window: column j sees
    # key positions <= lengths + j
    mask = jnp.arange(window)[None, None, :] <= pos[:, :, None]
    scale = 1.0 / jnp.sqrt(jnp.float32(hd))
    new_layers = []
    for p, layer in zip(params["blocks"], pool.layers):
        h = _layer_norm(p["ln1"], x)
        q = _heads(h, p["Wq"], cfg)                    # (S, H, W, hd)
        k_new = _heads(h, p["Wk"], cfg)
        v_new = _heads(h, p["Wv"], cfg)
        # advanced indices (S, W) land in front: value is (S, W, H, hd)
        ks = layer["k"].at[dest, :, offset, :].set(
            k_new.transpose(0, 2, 1, 3).astype(layer["k"].dtype))
        vs = layer["v"].at[dest, :, offset, :].set(
            v_new.transpose(0, 2, 1, 3).astype(layer["v"].dtype))
        if kernel == "pallas":
            # one streamed single-query pass per column, each at its
            # own cursor — garbage lanes (invalid columns) stay finite
            # and are never read by the host
            cols = []
            for j in range(w):
                lj = jnp.minimum(lengths + j, window - 1)
                cols.append(paged_attention(
                    q[:, :, j, :], ks, vs, page_table, lj,
                    interpret=cfg.interpret))
            att = jnp.stack(cols, axis=2)              # (S, H, W, hd)
            att = att.astype(x.dtype).transpose(0, 2, 1, 3).reshape(
                s, w, d)
        else:
            kg = ks[page_table].transpose(0, 2, 1, 3, 4).reshape(
                s, cfg.n_heads, window, hd)
            vg = vs[page_table].transpose(0, 2, 1, 3, 4).reshape(
                s, cfg.n_heads, window, hd)
            sc = jnp.einsum("shqd,shkd->shqk", q.astype(jnp.float32),
                            kg.astype(jnp.float32)) * scale
            sc = jnp.where(mask[:, None, :, :], sc, NEG_INF)
            wts = jax.nn.softmax(sc, axis=-1)
            att = jnp.einsum("shqk,shkd->shqd", wts,
                             vg.astype(jnp.float32))
            att = att.astype(x.dtype).transpose(0, 2, 1, 3).reshape(
                s, w, d)
        x = x + att @ p["Wo"]
        x = _ffn(p, x)
        new_layers.append({"k": ks, "v": vs})
    x = _layer_norm(params["ln_f"], x)
    logits = x @ params["embed"].T                     # (S, W, vocab)
    return logits, PagedKVPool(tuple(new_layers))


def paged_decode_step(params, tokens, pool: PagedKVPool, page_table,
                      lengths, active, cfg: TransformerConfig,
                      kernel: str = "gather"):
    """One decode step over S slots: embed `tokens` (S,), write each
    active slot's K/V at its own cursor (`lengths`) through the page
    table, attend over the slot's pages, return
    (logits (S, vocab), updated pool).

    Everything ragged is a traced ARRAY, never a shape: page_table
    (S, P) int32, lengths (S,) int32, active (S,) bool — so requests
    join and leave at token boundaries under ONE compiled program for
    the life of the server. Inactive slots write to the trash page and
    their logits are garbage the host ignores; lengths advance on the
    host side only for slots that ran.

    `kernel` picks the attention read: "gather" materializes each
    slot's dense `(S, H, window, hd)` K/V window (O(S × max_len) HBM
    traffic per step); "pallas" streams only the written pages from the
    pool through `attention.paged_pallas.paged_attention` (same masked
    softmax to 1e-5; `cfg.interpret` runs it on CPU). Callers resolve
    "auto" BEFORE jitting with `resolve_decode_kernel` — the knob is a
    compile-time constant, not a traced value."""
    if kernel not in ("gather", "pallas"):
        raise ValueError(
            f"kernel must be 'gather' or 'pallas' here (resolve 'auto' "
            f"via attention.paged_pallas.resolve_decode_kernel), "
            f"got {kernel!r}")
    s = tokens.shape[0]
    d = cfg.d_model
    hd = d // cfg.n_heads
    ps = pool.page_size
    trash = pool.trash_page
    n_p = page_table.shape[1]
    window = n_p * ps
    pos = lengths                                          # (S,)
    rows = jnp.arange(s)
    # physical destination of the incoming token's K/V; a cursor at or
    # past the window (pos // ps == n_p) writes to trash instead of
    # clamping into the slot's LAST real page
    dest = jnp.where(active & (pos // ps < n_p),
                     page_table[rows, jnp.minimum(pos // ps, n_p - 1)],
                     trash)
    offset = pos % ps
    # clamp the position-embedding lookup exactly like paged_prefill:
    # a slot whose cursor reached the window edge must reuse the last
    # embedding, not read past the (max_len, d) table
    pos_ids = jnp.minimum(pos, cfg.max_len - 1)
    x = (params["embed"][tokens] + params["pos"][pos_ids])[:, None, :]
    mask = jnp.arange(window)[None, :] <= pos[:, None]     # (S, window)
    scale = 1.0 / jnp.sqrt(jnp.float32(hd))
    new_layers = []
    for p, layer in zip(params["blocks"], pool.layers):
        h = _layer_norm(p["ln1"], x)
        q = _heads(h, p["Wq"], cfg)                        # (S, H, 1, hd)
        k_new = _heads(h, p["Wk"], cfg)[:, :, 0, :]        # (S, H, hd)
        v_new = _heads(h, p["Wv"], cfg)[:, :, 0, :]
        ks = layer["k"].at[dest, :, offset, :].set(
            k_new.astype(layer["k"].dtype))
        vs = layer["v"].at[dest, :, offset, :].set(
            v_new.astype(layer["v"].dtype))
        if kernel == "pallas":
            # stream the written pages straight from the pool — no
            # dense window; masking/trash/window-edge handled in-kernel
            att = paged_attention(q[:, :, 0, :], ks, vs, page_table,
                                  lengths, interpret=cfg.interpret)
            att = att.astype(x.dtype).reshape(s, 1, d)
        else:
            # gather each slot's pages into its logical window:
            # (S, P, H, ps, hd) -> (S, H, P*ps, hd)
            kg = ks[page_table].transpose(0, 2, 1, 3, 4).reshape(
                s, cfg.n_heads, window, hd)
            vg = vs[page_table].transpose(0, 2, 1, 3, 4).reshape(
                s, cfg.n_heads, window, hd)
            # exact masked softmax in f32 (the contiguous decode_step
            # math; masked lanes underflow to exactly 0, so page-tail
            # garbage contributes exactly 0)
            sc = jnp.einsum("shqd,shkd->shqk", q.astype(jnp.float32),
                            kg.astype(jnp.float32)) * scale
            sc = jnp.where(mask[:, None, None, :], sc, NEG_INF)
            w = jax.nn.softmax(sc, axis=-1)
            att = jnp.einsum("shqk,shkd->shqd", w,
                             vg.astype(jnp.float32))
            att = att.astype(x.dtype).transpose(0, 2, 1, 3).reshape(
                s, 1, d)
        x = x + att @ p["Wo"]
        x = _ffn(p, x)
        new_layers.append({"k": ks, "v": vs})
    x = _layer_norm(params["ln_f"], x)
    logits = x[:, 0, :] @ params["embed"].T
    return logits, PagedKVPool(tuple(new_layers))
