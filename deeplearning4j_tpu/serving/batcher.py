"""Dynamic micro-batcher: coalesce concurrent requests into one batch.

Single-row requests waste a parallel chip; the batcher sits in front of
an engine (or replica set) and merges whatever arrives within a
`max_delay_ms` window — up to `max_batch_size` rows — into ONE forward,
then scatters the output rows back to per-request futures.

Scope: this is the **forward** (`/predict`) path only. Generate traffic
does NOT coalesce here — a decode is thousands of steps, so batching
whole requests would couple their lifetimes (one slow request holds the
batch). `/generate` routes to the slot scheduler instead
(serving/decode_loop.py), which batches at TOKEN granularity: requests
join and leave the shared compiled decode step between steps, which is
why `server.py` hands generate requests to `DecodeLoop.submit` rather
than `MicroBatcher.submit`.

Contract:

- `submit(x)` is thread-safe and returns a `concurrent.futures.Future`
  whose result has the same leading dim as `x` (a 1-D request is
  treated as one row and resolves to a (1, ...) result). Rows map back
  in submit order — coalescing never reorders or mixes rows between
  requests.
- **Per-request error isolation**: a request whose feature shape
  disagrees with its batch-mates fails alone (its future gets the
  ValueError); the rest of the batch still runs. A failure of the
  engine call itself fails only the futures in that batch — the worker
  survives and keeps serving subsequent batches.
- A request that would overflow `max_batch_size` is held for the next
  batch (never split across two forwards), so one future always maps to
  one contiguous row range of one engine call.
- **Admission backpressure**: with `max_queue=N`, a submit that finds N
  requests already waiting raises `OverloadedError` (503 + Retry-After
  on the HTTP surface, docs/FLEET.md) instead of queueing unboundedly —
  shedding at the door beats timing out after the queue.
- **SLO tiers** (`submit(x, tier=)`, docs/SERVING.md "Priority
  tiers"): the coalescing queue is shared (one engine pass serves every
  tier), so tiers bite at ADMISSION — batch sheds first, at the lower
  `batch_max_queue` water mark (default half of `max_queue`) — and the
  shed reply carries the shed tier plus a Retry-After derived from the
  queue depth it actually saw, not a global constant.
- **Deadlines** (docs/SERVING.md "Deadlines"): `submit(x, deadline=)`
  raises `DeadlineExceededError` for an already-expired budget, and the
  worker re-checks at DISPATCH — a request whose budget died while it
  queued fails without ever touching the engine (no compute is spent on
  an answer nobody is waiting for). Pinned by the engine's
  program-cache and the batcher's batch counters in tests.
- **Cancellation**: a future the client abandoned (`fut.cancel()` after
  a result timeout or disconnect) is dropped at dispatch — the standard
  `set_running_or_notify_cancel()` handshake — and counted in
  `dl4j_batcher_cancelled`.
- `close()` stops accepting submits, flushes everything already queued,
  and joins the worker. Also usable as a context manager.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from concurrent.futures import Future
from typing import Callable, NamedTuple, Optional

import numpy as np

from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.serving.errors import (TIER_BATCH,
                                               TIER_INTERACTIVE, TIERS,
                                               Deadline,
                                               DeadlineExceededError,
                                               OverloadedError,
                                               backlog_retry_ms)

__all__ = ["MicroBatcher"]

_CLOSE = object()
_batcher_seq = itertools.count()


class _Request(NamedTuple):
    x: np.ndarray
    future: Future
    deadline: Optional[Deadline] = None
    tier: str = TIER_INTERACTIVE


def _resolve(fut: Future, value=None, exc: Optional[BaseException] = None
             ) -> None:
    """set_result/set_exception tolerating a caller-cancelled future —
    a client giving up (fut.cancel() after a result timeout) must never
    kill the worker thread."""
    try:
        if exc is not None:
            fut.set_exception(exc)
        else:
            fut.set_result(value)
    except Exception:  # InvalidStateError: cancelled/already done
        pass


class MicroBatcher:
    def __init__(self, run_batch: Callable[[np.ndarray], np.ndarray], *,
                 max_batch_size: int = 64, max_delay_ms: float = 2.0,
                 max_queue: Optional[int] = None,
                 batch_max_queue: Optional[int] = None,
                 name: str = "micro-batcher"):
        if max_batch_size < 1:
            raise ValueError(
                f"max_batch_size must be >= 1, got {max_batch_size}")
        if max_delay_ms < 0:
            raise ValueError(
                f"max_delay_ms must be >= 0, got {max_delay_ms}")
        if max_queue is not None and max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        if batch_max_queue is not None and batch_max_queue < 1:
            raise ValueError(
                f"batch_max_queue must be >= 1, got {batch_max_queue}")
        self._run = run_batch
        self.max_batch_size = int(max_batch_size)
        self.max_delay_s = max_delay_ms / 1000.0
        self.max_queue = None if max_queue is None else int(max_queue)
        # the bulk lane's lower water mark on the SHARED queue: batch
        # sheds first, keeping headroom for interactive arrivals
        if batch_max_queue is not None:
            self.batch_max_queue: Optional[int] = int(batch_max_queue)
        elif self.max_queue is not None:
            self.batch_max_queue = max(1, self.max_queue // 2)
        else:
            self.batch_max_queue = None
        self._q: "queue.Queue" = queue.Queue()
        self._closed = False
        self._lock = threading.Lock()
        # counters live in the telemetry registry (labeled per batcher)
        # so /metrics and snapshot() read the same series — no parallel
        # stat mechanism (docs/OBSERVABILITY.md)
        reg = telemetry.get_registry()
        self.label = f"{name}-{next(_batcher_seq)}"
        lab = {"batcher": self.label}
        self._m_submitted = reg.counter(
            "dl4j_batcher_submitted", "requests submitted").labels(**lab)
        self._m_completed = reg.counter(
            "dl4j_batcher_completed", "requests completed").labels(**lab)
        self._m_failed = reg.counter(
            "dl4j_batcher_failed", "requests failed").labels(**lab)
        self._m_batches = reg.counter(
            "dl4j_batcher_batches", "coalesced engine forwards").labels(**lab)
        self._m_rows = reg.counter(
            "dl4j_batcher_rows", "rows shipped in coalesced batches"
        ).labels(**lab)
        self._m_shed = reg.counter(
            "dl4j_batcher_shed",
            "requests rejected at submit because the coalescing queue "
            "was at max_queue").labels(**lab)
        self._m_deadline = reg.counter(
            "dl4j_batcher_deadline_exceeded",
            "requests shed (at submit or at dispatch) because their "
            "deadline budget was already spent").labels(**lab)
        self._m_cancelled = reg.counter(
            "dl4j_batcher_cancelled",
            "abandoned requests (client-cancelled futures) dropped at "
            "dispatch").labels(**lab)
        _tier_req = reg.counter(
            "dl4j_tier_requests",
            "generate requests submitted per SLO tier (interactive "
            "goes ahead at admission; batch rides the weighted-fair "
            "bulk lane)")
        tscope = {"scope": f"batcher:{self.label}"}
        self._m_tier_requests = {
            t: _tier_req.labels(tier=t, **tscope) for t in TIERS}
        _tier_shed = reg.counter(
            "dl4j_tier_shed",
            "generate requests shed at submit per SLO tier (batch "
            "sheds first, at its own lower batch_max_waiting bound)")
        self._m_tier_shed = {
            t: _tier_shed.labels(tier=t, **tscope) for t in TIERS}
        self._m_queue = reg.gauge(
            "dl4j_batcher_queue_depth",
            "requests waiting in the coalescing queue").labels(**lab)
        # weak: the registry outlives every batcher; a dead batcher's
        # queue gauge must read 0, not pin the queue in memory
        import weakref
        qsize = weakref.WeakMethod(self._q.qsize)
        self._m_queue.set_function(lambda: (qsize() or (lambda: 0))())
        self._worker = threading.Thread(target=self._loop, daemon=True,
                                        name=name)
        self._worker.start()

    # registry-backed counter views (historical attribute surface)
    @property
    def submitted(self) -> int:
        return int(self._m_submitted.value)

    @property
    def completed(self) -> int:
        return int(self._m_completed.value)

    @property
    def failed(self) -> int:
        return int(self._m_failed.value)

    @property
    def batches(self) -> int:
        return int(self._m_batches.value)

    @property
    def batched_rows(self) -> int:
        return int(self._m_rows.value)

    # ----------------------------------------------------------- submit
    def submit(self, x, deadline: Optional[Deadline] = None,
               tier: str = TIER_INTERACTIVE) -> Future:
        """Enqueue one request; the future resolves to the engine output
        rows for exactly these input rows. An already-expired `deadline`
        raises DeadlineExceededError here (504 on the HTTP surface) —
        and is re-checked at dispatch, so a budget that dies in the
        queue never reaches the engine either. `tier="batch"` sheds at
        the lower `batch_max_queue` water mark (bulk traffic backs off
        before it can crowd out interactive admission); coalescing
        itself is tier-blind — one engine pass serves every tier."""
        if tier not in TIERS:
            raise ValueError(
                f"unknown tier {tier!r} (expected one of {TIERS})")
        if deadline is not None and deadline.expired:
            self._m_deadline.inc()
            deadline.check("batcher admission")  # raises
        fut: Future = Future()
        arr = np.asarray(x)
        if arr.ndim == 0:
            fut.set_exception(ValueError("scalar request"))
            return fut
        if arr.ndim == 1:
            arr = arr[None, :]
        if arr.shape[0] == 0:
            fut.set_exception(ValueError("empty request"))
            return fut
        with self._lock:
            if self._closed:
                fut.set_exception(RuntimeError("batcher is closed"))
                return fut
            bound = (self.batch_max_queue if tier == TIER_BATCH
                     else self.max_queue)
            depth = self._q.qsize()
            if bound is not None and depth >= bound:
                # shed at the door: raising (not poisoning the future)
                # lets callers that route/queue-manage see the signal
                # before any work is enqueued. The backoff is derived
                # from the depth this tier actually hit — each queued
                # request costs roughly one slice of a coalescing
                # window to drain — and the reply names the shed tier.
                self._m_shed.inc()
                self._m_tier_shed[tier].inc()
                raise OverloadedError(
                    f"batcher queue full for tier {tier!r} "
                    f"({depth} waiting, bound {bound})",
                    retry_after_ms=backlog_retry_ms(
                        depth + 1,
                        max(1.0, self.max_delay_s * 2000.0
                            / self.max_batch_size)),
                    tier=tier)
            self._m_submitted.inc()
            self._m_tier_requests[tier].inc()
            # enqueue under the lock: close() also takes it before
            # putting the sentinel, so no request can land AFTER _CLOSE
            # and strand its future in a dead queue
            self._q.put(_Request(arr, fut, deadline, tier))
        return fut

    # ----------------------------------------------------------- worker
    def _coalesce(self, first: _Request):
        """Collect batch-mates for up to max_delay_s; returns
        (requests, leftover-or-sentinel)."""
        batch = [first]
        rows = first.x.shape[0]
        deadline = time.monotonic() + self.max_delay_s
        while rows < self.max_batch_size:
            timeout = deadline - time.monotonic()
            if timeout <= 0:
                break
            try:
                item = self._q.get(timeout=timeout)
            except queue.Empty:
                break
            if item is _CLOSE:
                return batch, _CLOSE
            if rows + item.x.shape[0] > self.max_batch_size:
                return batch, item  # hold for the next batch, unsplit
            batch.append(item)
            rows += item.x.shape[0]
        return batch, None

    def _run_group(self, batch) -> None:
        # dispatch-time gate: drop abandoned futures (the client gave
        # up — set_running_or_notify_cancel is the std handshake) and
        # fail queue-expired deadlines WITHOUT engine work; both are
        # decided before the batch's reference shape is picked so a
        # dead request never anchors the live ones' validation
        alive = []
        for req in batch:
            if not req.future.set_running_or_notify_cancel():
                self._m_cancelled.inc()
                continue
            if req.deadline is not None and req.deadline.expired:
                self._m_deadline.inc()
                self._m_failed.inc()
                _resolve(req.future, exc=DeadlineExceededError(
                    "deadline exceeded while queued in the batcher",
                    deadline_ms=req.deadline.budget_ms,
                    elapsed_ms=req.deadline.elapsed_ms()))
                continue
            alive.append(req)
        if not alive:
            return
        batch = alive
        # per-request validation against the batch's first request: a
        # mismatched request fails alone, the rest still run
        tail = batch[0].x.shape[1:]
        good, offsets, rows = [], [], 0
        for req in batch:
            if req.x.shape[1:] != tail:
                _resolve(req.future, exc=ValueError(
                    f"request feature shape {req.x.shape[1:]} does not "
                    f"match batch feature shape {tail}"))
                self._m_failed.inc()
                continue
            good.append(req)
            offsets.append(rows)
            rows += req.x.shape[0]
        if not good:
            return
        features = (good[0].x if len(good) == 1
                    else np.concatenate([r.x for r in good]))
        try:
            out = np.asarray(self._run(features))
        except Exception as e:
            # batch-level failure: poison only THIS batch's futures
            for req in good:
                _resolve(req.future, exc=e)
            self._m_failed.inc(len(good))
            return
        self._m_batches.inc()
        self._m_rows.inc(rows)
        self._m_completed.inc(len(good))
        for req, off in zip(good, offsets):
            _resolve(req.future, out[off:off + req.x.shape[0]])

    def _loop(self) -> None:
        pending: Optional[_Request] = None
        while True:
            if pending is not None:
                first, pending = pending, None
            else:
                first = self._q.get()
            if first is _CLOSE:
                return
            batch, leftover = self._coalesce(first)
            self._run_group(batch)
            if leftover is _CLOSE:
                return
            pending = leftover

    # -------------------------------------------------------- lifecycle
    def close(self, timeout: float = 10.0) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            # sentinel goes in under the same lock submit holds, so it
            # is strictly LAST: everything submitted before it flushes
            self._q.put(_CLOSE)
        self._worker.join(timeout=timeout)

    def __enter__(self) -> "MicroBatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------ stats
    def snapshot(self) -> dict:
        batches, rows = self.batches, self.batched_rows
        per_batch = (rows / batches) if batches else 0.0
        return {
            "submitted": self.submitted,
            "completed": self.completed,
            "failed": self.failed,
            "batches": batches,
            "mean_rows_per_batch": round(per_batch, 2),
            "occupancy": round(per_batch / self.max_batch_size, 4),
            "shed": int(self._m_shed.value),
            "deadline_exceeded": int(self._m_deadline.value),
            "cancelled": int(self._m_cancelled.value),
            "queue_depth": self._q.qsize(),
            "max_batch_size": self.max_batch_size,
            "max_queue": self.max_queue,
            "batch_max_queue": self.batch_max_queue,
            "max_delay_ms": self.max_delay_s * 1000.0,
            "tiers": {
                "requests": {t: int(self._m_tier_requests[t].value)
                             for t in TIERS},
                "shed": {t: int(self._m_tier_shed[t].value)
                         for t in TIERS},
            },
        }
