"""Content-addressed prefix index for cross-request KV page sharing.

Chat-shaped traffic re-sends the same leading tokens — system prompts,
few-shot templates, whole multi-turn histories — and without sharing,
every `/generate` request prefills that prefix from scratch into
private pages of the paged KV pool. This module is the host-side index
that turns prefill into O(new tokens): a radix trie keyed on
page-aligned token-id CHUNKS (one chunk = one full page's worth of
token ids), where each node owns exactly one physical pool page whose
K/V holds that chunk, written by some earlier request's prefill.

The index stores only bookkeeping — token tuples and page ids. All
policy (refcounts, copy-on-write forks, when a page may be freed) lives
in `decode_loop.DecodeLoop`, which owns the pool:

- `match(prompt)` walks the trie over the prompt's full chunks and
  returns the longest cached run of page ids (LRU-touching every node
  on the path). Only FULL chunks match — a prefix is reusable only when
  an entire page of identical token ids was written for it.
- `insert(tokens, pages)` adopts a retired request's full prompt pages
  chunk-by-chunk; chunks already present keep their existing page (the
  retiree's duplicate page goes back to the pool), and the walk stops
  at the first page in `skip` (forked pages — their bytes diverged from
  the pure token sequence and must never seed the shared cache).
- `evict_lru(evictable)` removes the least-recently-used LEAF whose
  page the caller's predicate allows (refcount zero) and hands its page
  back for reallocation. Leaf-only eviction keeps every cached path
  gap-free; since admission references parents before children, an
  unreferenced subtree is always consumable leaf-by-leaf. The scan is
  O(nodes) — fine at pool scale (pages are hundreds, not millions).

The trie never touches device memory: sharing pool pages between slots
is pure page-table bookkeeping (`paged_decode_step` gathers through the
per-slot table), so this index adds zero compiled programs.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = ["PrefixIndex"]

_Chunk = Tuple[int, ...]


class _Node:
    __slots__ = ("chunk", "page", "parent", "children", "tick")

    def __init__(self, chunk: _Chunk, page: int,
                 parent: Optional["_Node"]):
        self.chunk = chunk
        self.page = page
        self.parent = parent
        self.children: Dict[_Chunk, "_Node"] = {}
        self.tick = 0


class PrefixIndex:
    """Radix trie over page-aligned token chunks -> pool page ids."""

    def __init__(self, page_size: int):
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.page_size = int(page_size)
        self._roots: Dict[_Chunk, _Node] = {}
        self._by_page: Dict[int, _Node] = {}
        self._tick = 0

    def __len__(self) -> int:
        return len(self._by_page)

    def owns(self, page: int) -> bool:
        """True when this page's K/V is retained by the index (it must
        not be written in place or returned to the free list while the
        node lives)."""
        return int(page) in self._by_page

    def pages(self):
        """View of every page the index retains."""
        return self._by_page.keys()

    def iter_sequences(self):
        """Yield every MAXIMAL cached token sequence (root-to-leaf token
        path, one flat list per leaf), most recently touched leaf
        first. This is the corpus view the prompt-lookup drafter feeds
        on (serving/speculation.NgramDrafter): the trie already retains
        the recent prompt population, so speculative decoding gets its
        n-gram source for free — no second index, no device reads."""
        leaves = [n for n in self._by_page.values() if not n.children]
        leaves.sort(key=lambda n: n.tick, reverse=True)
        for leaf in leaves:
            parts: List[_Chunk] = []
            node: Optional[_Node] = leaf
            while node is not None:
                parts.append(node.chunk)
                node = node.parent
            yield [t for chunk in reversed(parts) for t in chunk]

    def head_paths(self, max_chunks: int = 16):
        """Yield every cached token path (root-to-leaf, most recently
        touched leaf first) truncated to its first `max_chunks` chunks
        — the fleet KV plane's summary corpus (serving/fleetkv.py).
        Affinity fingerprints only ever cover the HEAD of a path, so
        deep generation tails are cut before flattening; duplicates
        from leaves sharing a head collapse in the caller's hash
        dedup. Only retained tokens appear: a request that opted out
        of the prefix cache never seeded the trie, so nothing about
        it can surface here."""
        leaves = [n for n in self._by_page.values() if not n.children]
        leaves.sort(key=lambda n: n.tick, reverse=True)
        for leaf in leaves:
            parts: List[_Chunk] = []
            node: Optional[_Node] = leaf
            while node is not None:
                parts.append(node.chunk)
                node = node.parent
            head = list(reversed(parts))[:max_chunks]
            yield [t for chunk in head for t in chunk]

    def _chunks(self, tokens: Sequence[int]) -> List[_Chunk]:
        ps = self.page_size
        return [tuple(int(t) for t in tokens[j * ps:(j + 1) * ps])
                for j in range(len(tokens) // ps)]

    # ------------------------------------------------------- lookup
    def match(self, prompt: Sequence[int]) -> List[int]:
        """Longest cached prefix of `prompt` as a run of page ids, one
        per matched FULL chunk, LRU-touching the whole path."""
        self._tick += 1
        out: List[int] = []
        children = self._roots
        for chunk in self._chunks(prompt):
            node = children.get(chunk)
            if node is None:
                break
            node.tick = self._tick
            out.append(node.page)
            children = node.children
        return out

    # ------------------------------------------------------- insert
    def insert(self, tokens: Sequence[int], pages: Sequence[int],
               skip=()) -> int:
        """Adopt `pages[j]` for chunk j of `tokens` wherever the trie
        has no entry yet; returns how many pages were adopted. Existing
        chunks keep their page (the caller frees its duplicate via the
        normal refcount release). Stops at the first chunk whose page
        is in `skip` or already owned — adopting it would alias one
        physical page under two nodes."""
        self._tick += 1
        adopted = 0
        children = self._roots
        parent: Optional[_Node] = None
        for j, chunk in enumerate(self._chunks(tokens)):
            if j >= len(pages):
                break
            node = children.get(chunk)
            if node is None:
                page = int(pages[j])
                if page in skip or page in self._by_page:
                    break
                node = _Node(chunk, page, parent)
                children[chunk] = node
                self._by_page[page] = node
                adopted += 1
            node.tick = self._tick
            parent = node
            children = node.children
        return adopted

    # ------------------------------------------------------- evict
    def evict_lru(self, evictable: Callable[[int], bool]
                  ) -> Optional[int]:
        """Drop the least-recently-used LEAF whose page satisfies
        `evictable` (the loop passes refcount == 0); returns the freed
        page id, or None when nothing can go."""
        best: Optional[_Node] = None
        for node in self._by_page.values():
            if node.children:
                continue
            if not evictable(node.page):
                continue
            if best is None or node.tick < best.tick:
                best = node
        if best is None:
            return None
        if best.parent is None:
            del self._roots[best.chunk]
        else:
            del best.parent.children[best.chunk]
        del self._by_page[best.page]
        return best.page

    def snapshot(self) -> dict:
        return {"nodes": len(self._by_page),
                "roots": len(self._roots)}
