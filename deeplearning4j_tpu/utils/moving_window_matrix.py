"""Sliding sub-matrix extraction (reference core/util/
MovingWindowMatrix.java:38-120 — all windowRowSize x windowColumnSize
sub-matrices of a matrix, optionally with three extra 90-degree rotations
of each window).

Vectorized: one stride-tricks view + reshape produces every window in a
single O(1)-copy operation instead of the reference's per-offset slicing
loop.
"""

from __future__ import annotations

from typing import List

import numpy as np


class MovingWindowMatrix:
    def __init__(self, to_slice, window_row_size: int,
                 window_column_size: int, add_rotate: bool = False):
        self.matrix = np.asarray(to_slice)
        if self.matrix.ndim != 2:
            raise ValueError(f"Expected a matrix, got ndim={self.matrix.ndim}")
        r, c = self.matrix.shape
        if window_row_size > r or window_column_size > c:
            raise ValueError(
                f"Window ({window_row_size}, {window_column_size}) exceeds "
                f"matrix shape {self.matrix.shape}")
        self.window_row_size = window_row_size
        self.window_column_size = window_column_size
        self.add_rotate = add_rotate

    def windows(self, flattened: bool = False) -> List[np.ndarray]:
        """Every contiguous window, row-major by top-left offset; with
        add_rotate, each window is followed by its 3 successive 90-degree
        rotations (reference windows(boolean) :88)."""
        wr, wc = self.window_row_size, self.window_column_size
        view = np.lib.stride_tricks.sliding_window_view(
            self.matrix, (wr, wc))
        wins = view.reshape(-1, wr, wc)
        out: List[np.ndarray] = []
        for w in wins:
            out.append(w.copy())
            if self.add_rotate:
                rot = w
                for _ in range(3):
                    rot = np.rot90(rot)
                    out.append(rot.copy())
        if flattened:
            out = [w.ravel() for w in out]
        return out
