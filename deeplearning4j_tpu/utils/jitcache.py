"""Compiled-program counting for jitted functions.

The recompile-regression counters (train_step_cache_size,
predict_step_cache_size, InferenceEngine.program_cache_size) all probe
jax's private per-function program cache; one helper so the next jax
rename is a one-line fix instead of a hunt."""

from __future__ import annotations

__all__ = ["jit_cache_size"]


def jit_cache_size(jitted) -> int:
    """Number of XLA programs compiled for `jitted` (a jax.jit result).
    Returns -1 when the private jax API drifted — callers report that as
    "counter unavailable" rather than a fake 0."""
    try:
        return int(jitted._cache_size())
    except AttributeError:  # pragma: no cover — jax internals moved
        return -1
