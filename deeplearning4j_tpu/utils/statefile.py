"""Durable control-plane journal: one crash-atomic JSON state file.

The checkpoint layer (checkpoint/format.py) made PARAMETERS survive any
crash with one idiom — write a temp file, fsync it, publish with an
atomic ``os.replace`` so a reader only ever sees the previous committed
state or the new one, never a torn write. This module applies the same
idiom to the CONTROL PLANE's own state: the training supervisor and the
serving fleet journal their membership (child pid/pgid/start-time,
generation, replica endpoints, incarnation) through a ``StateFile`` at
every transition, so a restarted incarnation can re-adopt the live
children its predecessor left behind (docs/FAULT_TOLERANCE.md "Who
watches the watcher").

Crash-atomicity contract:

- ``write()`` serializes to ``<path>.tmp``, fsyncs, then ``os.replace``s
  onto ``<path>``. A crash before the rename leaves the PREVIOUS
  committed state readable; a crash after it leaves the new one. There
  is no third outcome on a POSIX filesystem.
- ``read()`` returns the committed dict, or ``None`` when the file is
  missing — or unreadable (external corruption): a torn journal must
  degrade to the next rung of the failure ladder (elastic resume /
  fresh spawn), never crash the restarted control plane. ``torn`` is
  True after a read that found bytes it could not parse.
- Writers inject faults through the chaos layer: each ``StateFile``
  carries a named injection point (``supervisor.journal`` /
  ``fleet.journal``) hit once before the temp write (``op="write"``)
  and once before the commit rename (``op="rename"``) — the
  crash-at-every-ordinal drills in tests/test_controlplane.py.

Telemetry (docs/OBSERVABILITY.md): ``dl4j_controlplane_journal_writes``,
``dl4j_controlplane_journal_write_seconds`` (whole operation) and
``dl4j_controlplane_journal_commit_seconds`` (fsync + rename) — all
labelled by ``plane``.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Dict, Optional

from deeplearning4j_tpu.testing import chaos

__all__ = ["StateFile", "controlplane_metrics"]

log = logging.getLogger(__name__)


def controlplane_metrics(plane: str, name: str, incarnation_fn,
                         kinds) -> tuple:
    """The `dl4j_controlplane_*` series both control planes register —
    ONE definition so metric names, help text, and label-name sets
    (`plane`, `name`; `kind` on adoptions) can never drift between the
    supervisor and the fleet. Returns (restarts_counter,
    {kind: adoptions_counter}); the incarnation gauge reads
    `incarnation_fn` at scrape (pass a weakref-safe callable)."""
    from deeplearning4j_tpu import telemetry

    reg = telemetry.get_registry()
    cp = {"plane": plane, "name": name}
    restarts = reg.counter(
        "dl4j_controlplane_restarts",
        "control-plane incarnations that started on top of a prior "
        "journal").labels(**cp)
    adoptions = {
        kind: reg.counter(
            "dl4j_controlplane_adoptions",
            "journaled/announced children processed by a restarted "
            "control plane, by outcome").labels(kind=kind, **cp)
        for kind in kinds}
    reg.gauge(
        "dl4j_controlplane_incarnation",
        "control-plane incarnation number (0 = never restarted over "
        "a journal)").labels(**cp).set_function(incarnation_fn)
    return restarts, adoptions


class StateFile:
    """One crash-atomic JSON state file (the control-plane journal)."""

    def __init__(self, path: str, *, point: Optional[str] = None,
                 plane: Optional[str] = None):
        self.path = str(path)
        #: chaos injection point name (e.g. "supervisor.journal"); None
        #: disables fault injection for this file
        self.point = point
        self.plane = plane or (point.split(".", 1)[0] if point
                               else "statefile")
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        #: True when the last read() found a file it could not parse —
        #: distinguishes "no journal" (fresh start) from "torn journal"
        #: (fall back, and treat unknown children as adopt-or-kill)
        self.torn = False
        reg = None
        try:
            from deeplearning4j_tpu import telemetry

            reg = telemetry.get_registry()
        except Exception:  # telemetry must never gate durability
            pass
        lab = {"plane": self.plane}
        self._m_writes = reg.counter(
            "dl4j_controlplane_journal_writes",
            "control-plane journal commits").labels(**lab) \
            if reg else None
        self._m_write_s = reg.histogram(
            "dl4j_controlplane_journal_write_seconds",
            "journal write wall time (serialize + commit)").labels(
                **lab) if reg else None
        self._m_commit_s = reg.histogram(
            "dl4j_controlplane_journal_commit_seconds",
            "journal commit portion (fsync + atomic rename)").labels(
                **lab) if reg else None

    # ---------------------------------------------------------------- write
    def write(self, state: Dict[str, Any]) -> str:
        """Commit `state` atomically. Raises on IO/injected faults — the
        caller decides whether a failed journal write is fatal (the
        control planes log and continue on the previous committed
        state; losing a journal write can only make a restart fall back
        one ladder rung, never corrupt it)."""
        t0 = time.perf_counter()
        if self.point is not None:
            chaos.hit(self.point, op="write")
        tmp = self.path + ".tmp"
        data = json.dumps(state, sort_keys=True)
        with open(tmp, "w") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        t_commit = time.perf_counter()
        try:
            if self.point is not None:
                chaos.hit(self.point, op="rename")
            os.replace(tmp, self.path)
        except BaseException:
            # an aborted commit must not leave a stale tmp that a later
            # write would fsync-over confusingly
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        now = time.perf_counter()
        if self._m_writes is not None:
            self._m_writes.inc()
            self._m_write_s.observe(now - t0)
            self._m_commit_s.observe(now - t_commit)
        return self.path

    def try_write(self, state: Dict[str, Any]) -> bool:
        """`write()` with the control planes' shared failure policy:
        log and continue on the previous committed state. Losing a
        journal write can only make a restart fall back one ladder
        rung (it adopts slightly older membership and the pid
        fingerprints reject whatever changed) — it must never take the
        running control plane down."""
        try:
            self.write(state)
            return True
        except Exception:
            log.exception(
                "journal write to %s failed (continuing on the "
                "previous committed state)", self.path)
            return False

    # ----------------------------------------------------------------- read
    def read(self) -> Optional[Dict[str, Any]]:
        """The committed state, or None (missing OR torn — check
        ``self.torn`` to tell them apart)."""
        self.torn = False
        try:
            with open(self.path) as f:
                raw = f.read()
        except FileNotFoundError:
            return None
        except OSError as e:
            log.warning("journal %s unreadable: %s", self.path, e)
            self.torn = True
            return None
        try:
            state = json.loads(raw)
        except ValueError:
            log.warning("journal %s is torn (unparsable); falling back",
                        self.path)
            self.torn = True
            return None
        if not isinstance(state, dict):
            self.torn = True
            return None
        return state

    # ---------------------------------------------------------------- clear
    def clear(self) -> None:
        """Remove the journal (a cleanly-finished run hands nothing to
        the next incarnation)."""
        for path in (self.path, self.path + ".tmp"):
            try:
                os.unlink(path)
            except OSError:
                pass

    def exists(self) -> bool:
        return os.path.exists(self.path)
