"""Archive extraction (reference core/util/ArchiveUtils.java — unzip /
untar / gunzip into a destination directory), with path-traversal
protection the reference lacked."""

from __future__ import annotations

import gzip
import os
import shutil
import tarfile
import zipfile


def _check_within(dest: str, target: str) -> None:
    dest_abs = os.path.abspath(dest)
    target_abs = os.path.abspath(target)
    if not (target_abs + os.sep).startswith(dest_abs + os.sep) \
            and target_abs != dest_abs:
        raise ValueError(f"Archive member escapes destination: {target}")


def unzip_file_to(file: str, dest: str) -> None:
    os.makedirs(dest, exist_ok=True)
    if file.endswith(".zip"):
        with zipfile.ZipFile(file) as z:
            for name in z.namelist():
                _check_within(dest, os.path.join(dest, name))
            z.extractall(dest)
    elif file.endswith((".tar", ".tar.gz", ".tgz")):
        mode = "r" if file.endswith(".tar") else "r:gz"
        with tarfile.open(file, mode) as t:
            for member in t.getmembers():
                _check_within(dest, os.path.join(dest, member.name))
            # filter="data" additionally rejects symlink escapes (a symlink
            # member pointing outside dest + a member written through it
            # would pass the name check alone), absolute names and device
            # files.
            t.extractall(dest, filter="data")
    elif file.endswith(".gz"):
        out = os.path.join(dest, os.path.basename(file)[:-3])
        with gzip.open(file, "rb") as src, open(out, "wb") as dst:
            shutil.copyfileobj(src, dst)
    else:
        raise ValueError(f"Unknown archive format: {file}")
