"""Image file -> array loading (reference core/util/ImageLoader.java —
asRowVector/asMatrix with optional resize; the LFW pipeline's decoder).

Uses PIL for decoding; arrays come back float32 in [0, 255] like the
reference's BufferedImage RGB extraction.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


class ImageLoader:
    def __init__(self, height: Optional[int] = None,
                 width: Optional[int] = None, grayscale: bool = True):
        self.height = height
        self.width = width
        self.grayscale = grayscale

    def _load(self, path) -> "np.ndarray":
        from PIL import Image

        with Image.open(path) as img:
            img = img.convert("L" if self.grayscale else "RGB")
            if self.height and self.width:
                img = img.resize((self.width, self.height))
            return np.asarray(img, np.float32)

    def as_matrix(self, path) -> np.ndarray:
        """(H, W) grayscale or (H, W, 3) RGB float32 (asMatrix parity)."""
        return self._load(path)

    def as_row_vector(self, path) -> np.ndarray:
        """Flattened image (asRowVector parity)."""
        return self._load(path).ravel()

    @property
    def shape(self) -> Tuple[int, ...]:
        if not (self.height and self.width):
            raise ValueError("shape requires fixed height/width")
        return ((self.height, self.width) if self.grayscale
                else (self.height, self.width, 3))
