"""Spawned-process-group management shared by every subsystem that
launches sibling processes (the serving fleet's `ReplicaSpawner`, the
training supervisor's `WorkerSpawner`).

Two pieces of pid/pgid-recycling-sensitive logic live here ONCE:

- **Orphan sweep**: every spawn runs in its own session/process group
  (`start_new_session=True`) and registers here; a single atexit hook
  SIGKILLs whatever the owner never reaped, so a crash-exiting parent
  cannot leak live children holding ports. The sweep uses
  ``killpg(proc.pid)`` directly — never ``os.getpgid()``, which fails
  once the leader is reaped even while grandchildren keep the group
  (and their ports) alive; killpg works as long as ANY member lives.
- **Group stop** (`stop_process_group`): the group sweep runs BEFORE
  the leader is reaped — the un-reaped leader (alive or zombie) pins
  pid == pgid, so the sweep can never hit a recycled pid. After a
  reap, an emptied group's id is free for reuse and a blind killpg
  could SIGKILL an unrelated process group — so an already-reaped
  leader is only waited on, never group-swept.
"""

from __future__ import annotations

import atexit
import os
import signal
import subprocess
import threading

__all__ = ["register_spawned", "unregister_spawned",
           "kill_spawned_orphans", "stop_process_group",
           "SPAWNED_PROCS"]

#: spawned session-leader processes still alive (shared registry)
SPAWNED_PROCS: set = set()
_lock = threading.Lock()
_atexit_armed = False


def register_spawned(proc: subprocess.Popen) -> None:
    global _atexit_armed
    with _lock:
        SPAWNED_PROCS.add(proc)
        if not _atexit_armed:
            atexit.register(kill_spawned_orphans)
            _atexit_armed = True


def unregister_spawned(proc: subprocess.Popen) -> None:
    with _lock:
        SPAWNED_PROCS.discard(proc)


def kill_spawned_orphans() -> None:
    """SIGKILL every registered group (what atexit runs)."""
    with _lock:
        procs = list(SPAWNED_PROCS)
        SPAWNED_PROCS.clear()
    for proc in procs:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (OSError, ProcessLookupError):
            if proc.poll() is None:
                try:
                    proc.kill()
                except OSError:
                    pass


def stop_process_group(proc: subprocess.Popen, timeout: float = 10.0,
                       term_first: bool = True) -> None:
    """Terminate a spawned process and its whole group, then reap and
    unregister it. ``term_first=False`` goes straight to SIGKILL (for
    hung/SIGSTOP'd members that will never honor SIGTERM)."""
    if proc.poll() is None:
        sig = signal.SIGTERM if term_first else signal.SIGKILL
        try:
            os.killpg(proc.pid, sig)
        except (OSError, ProcessLookupError):
            proc.send_signal(sig)
        try:
            proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (OSError, ProcessLookupError):
                proc.kill()
            proc.wait(timeout=timeout)
    else:
        proc.wait()  # reaped or zombie: collect; group id is NOT swept
    unregister_spawned(proc)
