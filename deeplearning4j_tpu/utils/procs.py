"""Spawned-process-group management shared by every subsystem that
launches sibling processes (the serving fleet's `ReplicaSpawner`, the
training supervisor's `WorkerSpawner`).

The pid/pgid-recycling-sensitive logic lives here ONCE:

- **Orphan sweep**: every spawn runs in its own session/process group
  (`start_new_session=True`) and registers here; a single atexit hook
  SIGKILLs whatever the owner never reaped, so a crash-exiting parent
  cannot leak live children holding ports. The sweep uses
  ``killpg(proc.pid)`` directly — never ``os.getpgid()``, which fails
  once the leader is reaped even while grandchildren keep the group
  (and their ports) alive; killpg works as long as ANY member lives.
- **Group stop** (`stop_process_group`): the group sweep runs BEFORE
  the leader is reaped — the un-reaped leader (alive or zombie) pins
  pid == pgid, so the sweep can never hit a recycled pid. After a
  reap, an emptied group's id is free for reuse and a blind killpg
  could SIGKILL an unrelated process group — so an already-reaped
  leader is only waited on, never group-swept.
- **Incarnation handoff** (`release_spawned` + `AdoptedProc`): a
  crash-safe control plane (utils/statefile.py journal) hands its live
  children to its NEXT incarnation instead of sweeping them — the
  exiting incarnation `release_spawned`s them (scoping the atexit
  sweep to processes the CURRENT incarnation still owns), and the
  restarted one re-adopts each journaled child as an `AdoptedProc`.
  An adopted child is NOT our waitpid-able child (it re-parented to
  init when its first parent died), so every signal/poll verifies
  **pid + start-time** (`pid_matches`) — a recycled pid must never be
  signalled, swept, or mistaken for a surviving worker.
"""

from __future__ import annotations

import atexit
import os
import signal
import subprocess
import threading
import time
from typing import Optional, Tuple

__all__ = ["register_spawned", "unregister_spawned", "release_spawned",
           "kill_spawned_orphans", "stop_process_group",
           "proc_start_time", "pid_matches", "classify_pid",
           "AdoptedProc", "SPAWNED_PROCS"]

#: spawned session-leader processes still alive (shared registry)
SPAWNED_PROCS: set = set()
_lock = threading.Lock()
_atexit_armed = False


def register_spawned(proc) -> None:
    global _atexit_armed
    with _lock:
        SPAWNED_PROCS.add(proc)
        if not _atexit_armed:
            atexit.register(kill_spawned_orphans)
            _atexit_armed = True


def unregister_spawned(proc) -> None:
    with _lock:
        SPAWNED_PROCS.discard(proc)


def release_spawned(proc) -> None:
    """Hand a live child to the NEXT control-plane incarnation: remove
    it from the atexit sweep WITHOUT stopping it. The caller must have
    journaled (pid, start_time) so the successor can re-adopt it —
    an unjournaled release is a leak."""
    unregister_spawned(proc)


def kill_spawned_orphans() -> None:
    """SIGKILL every registered group (what atexit runs). Only
    processes the current incarnation still OWNS are here — released
    (handed-off) children were unregistered and survive."""
    with _lock:
        procs = list(SPAWNED_PROCS)
        SPAWNED_PROCS.clear()
    for proc in procs:
        if isinstance(proc, AdoptedProc) and proc.poll() is not None:
            continue  # dead or recycled: a blind killpg could hit a stranger
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (OSError, ProcessLookupError):
            if proc.poll() is None:
                try:
                    proc.kill()
                except OSError:
                    pass


# ------------------------------------------------------ pid verification
def _proc_stat(pid: int) -> Optional[Tuple[str, int]]:
    """(state, starttime) from /proc/<pid>/stat, or None when the pid
    is gone or /proc is unavailable. The comm field may contain spaces
    and parens — parse from the LAST ')'."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            raw = f.read().decode("ascii", errors="replace")
    except OSError:
        return None
    try:
        rest = raw[raw.rindex(")") + 2:].split()
        # rest[0] is field 3 (state); field 22 (starttime) is rest[19]
        return rest[0], int(rest[19])
    except (ValueError, IndexError):
        return None


def proc_start_time(pid: int) -> Optional[int]:
    """Kernel start time (clock ticks since boot) of `pid`, or None.
    Journaled next to the pid so a restart can tell a surviving child
    from a recycled pid wearing its number."""
    stat = _proc_stat(pid)
    return stat[1] if stat is not None else None


def pid_matches(pid: int, start_time: Optional[int]) -> bool:
    """True iff `pid` names a LIVE process that is the same incarnation
    the journal recorded: alive (and not a zombie) AND, when a start
    time was journaled, carrying that exact start time. A pid alone is
    never proof — pids recycle."""
    if pid is None or pid <= 0:
        return False
    stat = _proc_stat(pid)
    if stat is None:
        # /proc unavailable (non-Linux): fall back to a signal-0 probe,
        # but only when there is no fingerprint to contradict
        if start_time is not None:
            return False
        try:
            os.kill(pid, 0)
            return True
        except ProcessLookupError:
            return False
        except PermissionError:
            return True
        except OSError:
            return False
    state, actual_start = stat
    if state in ("Z", "X", "x"):
        return False  # a zombie is a dead process wearing its pid
    if start_time is None:
        return True
    return int(start_time) == actual_start


def classify_pid(pid, start_time) -> str:
    """Adoption verdict for one journaled child — the ONE
    classification both control planes (supervisor and fleet) apply to
    every entry on restart:

    - ``"adopted"``: alive and wearing the journaled fingerprint —
      safe to re-adopt.
    - ``"recycled"``: alive but the start time disagrees — a stranger
      wearing the number; never signalled, only replaced.
    - ``"dead"``: nobody home (or an unusable pid).
    """
    if not pid:
        return "dead"
    pid = int(pid)
    if pid_matches(pid, start_time):
        return "adopted"
    return "recycled" if pid_matches(pid, None) else "dead"


class AdoptedProc:
    """Popen-shaped handle for a re-adopted child of a PREVIOUS
    control-plane incarnation.

    Not our waitpid-able child — when the first parent died the kernel
    re-parented it to init — so ``poll()`` is a /proc liveness check
    against the journaled (pid, start_time) fingerprint, ``wait()``
    polls, and every signal verifies the fingerprint first so a
    recycled pid is never touched. ``pid == pgid`` still holds (the
    child was spawned as its own session leader), so the shared
    group-kill discipline (`stop_process_group`) works unchanged."""

    #: returncode reported once the process is observed gone — the real
    #: exit status died with the first parent, so this is a sentinel
    UNKNOWN_RC = -257

    def __init__(self, pid: int, start_time: Optional[int] = None):
        self.pid = int(pid)
        self.start_time = (int(start_time) if start_time is not None
                           else proc_start_time(self.pid))
        self.returncode: Optional[int] = None
        self.adopted = True

    def poll(self) -> Optional[int]:
        if self.returncode is not None:
            return self.returncode
        if pid_matches(self.pid, self.start_time):
            return None
        self.returncode = self.UNKNOWN_RC
        return self.returncode

    def wait(self, timeout: Optional[float] = None) -> int:
        deadline = None if timeout is None else (
            time.monotonic() + timeout)
        while self.poll() is None:
            if deadline is not None and time.monotonic() >= deadline:
                raise subprocess.TimeoutExpired(
                    cmd=f"adopted-pid-{self.pid}", timeout=timeout)
            time.sleep(0.02)
        return self.returncode

    def send_signal(self, sig: int) -> None:
        if self.poll() is None:  # fingerprint-verified before any kill
            os.kill(self.pid, sig)

    def terminate(self) -> None:
        self.send_signal(signal.SIGTERM)

    def kill(self) -> None:
        self.send_signal(signal.SIGKILL)

    def __repr__(self) -> str:
        return (f"AdoptedProc(pid={self.pid}, "
                f"start_time={self.start_time}, rc={self.returncode})")


def stop_process_group(proc, timeout: float = 10.0,
                       term_first: bool = True) -> None:
    """Terminate a spawned process and its whole group, then reap and
    unregister it. ``term_first=False`` goes straight to SIGKILL (for
    hung/SIGSTOP'd members that will never honor SIGTERM). Accepts a
    Popen or an `AdoptedProc` — for an adopted handle, poll() is the
    fingerprint check, so a recycled pid is never group-killed."""
    if proc.poll() is None:
        sig = signal.SIGTERM if term_first else signal.SIGKILL
        try:
            os.killpg(proc.pid, sig)
        except (OSError, ProcessLookupError):
            proc.send_signal(sig)
        try:
            proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (OSError, ProcessLookupError):
                proc.kill()
            proc.wait(timeout=timeout)
    else:
        proc.wait()  # reaped or zombie: collect; group id is NOT swept
    unregister_spawned(proc)
