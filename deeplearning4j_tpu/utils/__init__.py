"""Utility subsystem (reference core/util/, 27 files ~5.6k LoC — the
used-by-something subset): Viterbi sequence smoothing, MathUtils,
disk-spilling queue, pickle-free serialization, moving-window matrix
extraction, image loading, archive extraction; plus the control-plane
primitives grown beyond parity — spawned-process-group management with
incarnation handoff (`procs`) and the crash-atomic state journal
(`statefile.StateFile`, docs/FAULT_TOLERANCE.md "Who watches the
watcher")."""

from deeplearning4j_tpu.utils.statefile import StateFile  # noqa: F401

from deeplearning4j_tpu.utils.viterbi import Viterbi  # noqa: F401
from deeplearning4j_tpu.utils.disk_based_queue import (  # noqa: F401
    DiskBasedQueue,
)
from deeplearning4j_tpu.utils.serialization import (  # noqa: F401
    from_bytes,
    read_object,
    save_object,
    to_bytes,
)
from deeplearning4j_tpu.utils.moving_window_matrix import (  # noqa: F401
    MovingWindowMatrix,
)
from deeplearning4j_tpu.utils.image_loader import ImageLoader  # noqa: F401
from deeplearning4j_tpu.utils.archive import unzip_file_to  # noqa: F401
from deeplearning4j_tpu.utils import math_utils  # noqa: F401
