"""Numerical sanitizers + input validation.

Parity: the reference's scattered numerical guards —
`LinAlgExceptions.assertValidNum` on backprop deltas
(core/nn/multilayer/MultiLayerNetwork.java:550,:572), the NaN scrub
`BooleanIndexing.applyWhere(output, isNan, EPS)`
(core/nn/layers/OutputLayer.java:75,:89), and the shape asserts
throughout (e.g. MultiLayerNetwork.java:889) — promoted into one module
(SURVEY §5 names this the TPU build's "shape/dtype validation layer").

TPU-native design: `scrub_nan` is a jittable jnp op that fuses into the
surrounding XLA program; `assert_valid_num` is a HOST-side check for
eager/debug paths (calling it on a traced value would force a sync —
inside jit use `debug_nans()` instead, which turns on XLA's nan-checking
mode); shape validation happens before trace time so errors carry layer
context instead of a dot_general shape dump.
"""

from __future__ import annotations

import contextlib
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

EPS = 1e-6

__all__ = ["EPS", "assert_valid_num", "scrub_nan", "debug_nans",
           "validate_batch"]


def assert_valid_num(arr, name: str = "array") -> None:
    """Raise ValueError if `arr` contains NaN/Inf (reference
    LinAlgExceptions.assertValidNum). Host-side: forces the value, so use
    only on eager/debug paths, not inside jit."""
    a = np.asarray(arr)
    if not np.all(np.isfinite(a)):
        n_nan = int(np.isnan(a).sum())
        n_inf = int(np.isinf(a).sum())
        raise ValueError(
            f"{name} contains non-finite values ({n_nan} NaN, {n_inf} Inf "
            f"of {a.size})")


def scrub_nan(x: jnp.ndarray, eps: float = EPS) -> jnp.ndarray:
    """Replace NaN with `eps` (reference OutputLayer.java:75,:89 NaN
    scrub). Jittable; fuses into the surrounding program."""
    return jnp.where(jnp.isnan(x), jnp.asarray(eps, dtype=x.dtype), x)


@contextlib.contextmanager
def debug_nans(enable: bool = True):
    """Toggle jax_debug_nans for a scope: every jitted computation
    re-checks outputs for NaN and re-runs un-jitted to pinpoint the
    primitive that produced it. The in-jit equivalent of the reference's
    assertValidNum-on-every-delta, at real debug cost — wrap only the
    step you are hunting."""
    prev = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", bool(enable))
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", prev)


def validate_batch(x, labels=None, *, n_in: Optional[int] = None,
                   n_out: Optional[int] = None,
                   context: str = "fit") -> None:
    """Pre-trace shape validation with layer context (reference shape
    asserts, MultiLayerNetwork.java:889). Raises ValueError before XLA
    ever sees the arrays, so the message names the config field instead
    of a dot_general contraction mismatch."""
    if x.ndim < 2:
        raise ValueError(
            f"{context}: features must be at least 2-D (batch, features), "
            f"got shape {tuple(x.shape)}")
    if n_in and x.shape[-1] != n_in:
        raise ValueError(
            f"{context}: features have {x.shape[-1]} columns but the "
            f"first layer's n_in is {n_in}")
    if labels is not None:
        if labels.ndim != 2:
            raise ValueError(
                f"{context}: labels must be 2-D one-hot (batch, classes), "
                f"got shape {tuple(labels.shape)}")
        if labels.shape[0] != x.shape[0]:
            raise ValueError(
                f"{context}: {x.shape[0]} examples but "
                f"{labels.shape[0]} label rows")
        if n_out and labels.shape[-1] != n_out:
            raise ValueError(
                f"{context}: labels have {labels.shape[-1]} columns but "
                f"the output layer's n_out is {n_out}")
