"""Array-ecosystem interchange for DataSets.

Parity: reference `spark/util/MLLibUtil.java` — INDArray <-> MLlib
Vector/Matrix and DataSet <-> LabeledPoint conversions, the glue that
let reference models ride another ecosystem's data structures. The
TPU-native equivalents target the ecosystems on this stack: numpy (the
host interchange format), torch CPU tensors (the image ships torch),
jax device arrays, and the (label, features) "labeled point" row form
(MLLibUtil.toLabeledPoint:129: label = argmax of the one-hot row).

Everything is copy-free where the backends allow it (numpy <-> torch
share memory via from_numpy/asarray; jax always copies host<->device).
"""

from __future__ import annotations

from typing import Iterable, List, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.api import DataSet


# ------------------------------------------------------------------ numpy
def to_numpy(ds: DataSet) -> Tuple[np.ndarray, np.ndarray]:
    """(features, labels) host arrays (device arrays are fetched)."""
    return np.asarray(ds.features), np.asarray(ds.labels)


def from_numpy(features, labels) -> DataSet:
    f = np.asarray(features)
    y = np.asarray(labels)
    if f.shape[0] != y.shape[0]:
        raise ValueError(f"features rows {f.shape[0]} != labels rows "
                         f"{y.shape[0]}")
    return DataSet(f, y)


# ------------------------------------------------------------------- jax
def to_jax(ds: DataSet):
    """Device-resident (features, labels)."""
    import jax.numpy as jnp

    return jnp.asarray(ds.features), jnp.asarray(ds.labels)


# ------------------------------------------------------------------ torch
def to_torch(ds: DataSet):
    """(features, labels) torch CPU tensors. Sharing is BEST-EFFORT:
    contiguous host-numpy arrays are wrapped zero-copy
    (torch.from_numpy), while non-contiguous or device-backed arrays
    are copied first — mutations through the tensors only reach the
    DataSet in the zero-copy case."""
    import torch

    f, y = to_numpy(ds)
    return (torch.from_numpy(np.ascontiguousarray(f)),
            torch.from_numpy(np.ascontiguousarray(y)))


def from_torch(features, labels) -> DataSet:
    """DataSet from torch tensors (detached, moved to CPU)."""
    return from_numpy(features.detach().cpu().numpy(),
                      labels.detach().cpu().numpy())


# ---------------------------------------------------------- labeled points
def to_labeled_points(ds: DataSet) -> List[Tuple[int, np.ndarray]]:
    """One (label_index, feature_vector) row per example — the MLlib
    LabeledPoint form (label = argmax of the one-hot labels row,
    MLLibUtil.toLabeledPoint:129-138)."""
    f, y = to_numpy(ds)
    if y.ndim != 2:
        raise ValueError("labels must be one-hot (N, classes)")
    idx = y.argmax(axis=1)
    return [(int(lab), f[i]) for i, lab in enumerate(idx)]


def from_labeled_points(points: Iterable[Tuple[int, Sequence[float]]],
                        num_labels: int) -> DataSet:
    """Rebuild a DataSet from (label_index, features) rows
    (MLLibUtil.fromLabeledPoint:146-170: one-hot at the label index)."""
    labels, feats = [], []
    for lab, vec in points:
        lab = int(lab)
        if not 0 <= lab < num_labels:
            raise ValueError(f"label {lab} outside 0..{num_labels - 1}")
        labels.append(lab)
        feats.append(np.asarray(vec, np.float32))
    if not feats:
        raise ValueError("no labeled points given")
    f = np.stack(feats)
    y = np.eye(num_labels, dtype=np.float32)[labels]
    return DataSet(f, y)


__all__ = ["to_numpy", "from_numpy", "to_jax", "to_torch", "from_torch",
           "to_labeled_points", "from_labeled_points"]
