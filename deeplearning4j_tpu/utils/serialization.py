"""Object save/load helpers (reference core/util/SerializationUtils.java —
java-serialization save/read for models and datasets).

Pickle-free: the npz+JSON tree codec from scaleout/checkpoint.py handles
numpy/JAX arrays, NamedTuples registered there, and JSON-able containers.
Reading a file from shared storage can raise, never execute code.
"""

from __future__ import annotations

from typing import Any

from deeplearning4j_tpu.scaleout.checkpoint import dump_payload, load_payload


def to_bytes(obj: Any) -> bytes:
    return dump_payload({"obj": obj})


def from_bytes(data: bytes) -> Any:
    return load_payload(data)["obj"]


def save_object(obj: Any, path: str) -> str:
    """reference SerializationUtils.saveObject(Serializable, File)."""
    with open(path, "wb") as f:
        f.write(to_bytes(obj))
    return path


def read_object(path: str) -> Any:
    """reference SerializationUtils.readObject(File)."""
    with open(path, "rb") as f:
        return from_bytes(f.read())
