"""Shared stdlib HTTP-server lifecycle helper.

Both embedded servers (plot/render_server.py, serving/server.py) follow
the same pattern: a ThreadingHTTPServer on a daemon thread, bound to
port 0 by default so tests never collide on a fixed port, and a close()
that actually releases the listening socket (`shutdown` alone leaves the
fd open until GC — the classic leaked-socket flake).
"""

from __future__ import annotations

import threading
from http.server import ThreadingHTTPServer


class ServerHandle:
    """A running HTTP server: (server, thread, port) + graceful close().

    Supports 2-tuple unpacking `server, port = handle` for callers of the
    historical serve_coords contract.
    """

    def __init__(self, server: ThreadingHTTPServer,
                 thread: threading.Thread):
        self.server = server
        self.thread = thread
        self.port = int(server.server_address[1])
        self.host = server.server_address[0]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self, timeout: float = 5.0) -> None:
        """Stop serving, release the socket, join the serve thread."""
        self.server.shutdown()
        self.server.server_close()
        self.thread.join(timeout=timeout)

    def __iter__(self):
        return iter((self.server, self.port))

    def __enter__(self) -> "ServerHandle":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def start_http_server(handler_cls, host: str = "127.0.0.1",
                      port: int = 0) -> ServerHandle:
    """Bind (port 0 = auto-assign), serve on a daemon thread, return the
    handle. The caller owns close()."""
    server = ThreadingHTTPServer((host, port), handler_cls)
    thread = threading.Thread(target=server.serve_forever, daemon=True,
                              name=f"httpd:{server.server_address[1]}")
    thread.start()
    return ServerHandle(server, thread)
