"""Viterbi decoder for smoothing observed label sequences.

Parity: reference core/util/Viterbi.java:31-192 — a fixed two-parameter
markov chain over the label states: emission log-prob is log(pCorrect)
when a state matches the observed label and log((1-pCorrect)/(states-1))
otherwise; transition log-prob is log(metaStability) for staying in the
same state and log((1-metaStability)/(states-1)) for switching. `decode`
accepts either an outcome-index sequence or a binary (one-hot) label
matrix and returns (best path log-prob, decoded state sequence).

The reference's backpointer matrix was never filled (Viterbi.java:77-105
computes `pointers` but only writes zeros) and its probability formulas
dropped parentheses (`1 - pCorrect / states - 1`); both are alpha-era
bugs, deliberately not reproduced — this is the intended algorithm as a
single jitted lax.scan forward pass + reverse backtrace.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnums=(1,))
def _viterbi_decode(observed: jnp.ndarray, states: int,
                    log_p_correct: float, log_p_incorrect: float,
                    log_stay: float, log_switch: float):
    """The 2-parameter smoothing chain, lowered onto the general-table
    decoder below: uniform init, stay/switch transition matrix, and
    match/mismatch emissions materialized per frame."""
    trans = jnp.full((states, states), log_switch).at[
        jnp.arange(states), jnp.arange(states)].set(log_stay)
    init = jnp.full((states,), -math.log(states))
    emits = jnp.where(observed[:, None] == jnp.arange(states)[None, :],
                      log_p_correct, log_p_incorrect)
    return _viterbi_general(init, trans, emits)


@jax.jit
def _viterbi_general(log_init: jnp.ndarray, log_trans: jnp.ndarray,
                     log_emits: jnp.ndarray):
    """General-HMM max-product decode: `log_init` (S,), `log_trans`
    (S, S) row->col, `log_emits` (T, S) per-frame emission log-probs.
    Same scan + backtrace machinery as the 2-parameter smoothing chain
    above, with full tables — the form a trained tagger needs."""

    def step(v_prev, emit):
        scores = v_prev[:, None] + log_trans
        best_prev = jnp.argmax(scores, axis=0)
        v = jnp.max(scores, axis=0) + emit
        return v, best_prev

    v0 = log_init + log_emits[0]
    v_final, pointers = jax.lax.scan(step, v0, log_emits[1:])
    last = jnp.argmax(v_final)

    def back(state, ptr_row):
        return ptr_row[state], ptr_row[state]

    _, rest = jax.lax.scan(back, last, pointers, reverse=True)
    return v_final[last], jnp.concatenate([rest, jnp.array([last])])


def _viterbi_np(log_init, log_trans, log_emits):
    """Numpy twin of _viterbi_general for host-side callers: the jitted
    scan recompiles per (frames, states) shape, and for small tables
    (PoS tagging natural sentences of every length) the per-length XLA
    compile dwarfs the decode itself."""
    T, S = log_emits.shape
    v = log_init + log_emits[0]
    pointers = np.empty((T - 1, S), np.int64)
    for t in range(1, T):
        scores = v[:, None] + log_trans
        pointers[t - 1] = scores.argmax(axis=0)
        v = scores.max(axis=0) + log_emits[t]
    path = np.empty(T, np.int64)
    path[-1] = int(v.argmax())
    for t in range(T - 2, -1, -1):
        path[t] = pointers[t, path[t + 1]]
    return float(v.max()), path


def viterbi_path(log_init, log_trans, log_emits,
                 backend: str = "numpy") -> Tuple[float, np.ndarray]:
    """Decode the most likely state path for a general HMM.
    Returns (best path log-prob, state index sequence).

    backend='numpy' (default) runs the host loop — right for small
    tables at many distinct lengths (each length would trigger a fresh
    XLA compile); backend='jax' uses the jitted scan — right for long
    fixed-shape streams."""
    log_emits_np = np.asarray(log_emits, np.float64)
    if log_emits_np.ndim != 2 or log_emits_np.shape[0] == 0:
        raise ValueError("log_emits must be (frames, states), frames >= 1")
    if backend == "numpy":
        return _viterbi_np(np.asarray(log_init, np.float64),
                           np.asarray(log_trans, np.float64), log_emits_np)
    if backend != "jax":
        raise ValueError(f"unknown backend {backend!r}")
    logp, path = _viterbi_general(jnp.asarray(log_init),
                                  jnp.asarray(log_trans),
                                  jnp.asarray(log_emits))
    return float(logp), np.asarray(path)


class Viterbi:
    """See module docstring; constructor mirrors Viterbi(possibleLabels)."""

    def __init__(self, possible_labels, meta_stability: float = 0.9,
                 p_correct: float = 0.99):
        self.possible_labels = np.asarray(possible_labels).ravel()
        self.states = int(self.possible_labels.shape[0])
        if self.states < 2:
            raise ValueError("Viterbi needs at least 2 states")
        self.meta_stability = meta_stability
        self.p_correct = p_correct

    def decode(self, labels,
               binary_label_matrix: bool = True) -> Tuple[float, np.ndarray]:
        """Returns (log-prob of the best path, decoded outcome sequence).

        `labels`: (frames, states) one-hot matrix when binary_label_matrix
        (reference toOutcomesFromBinaryLabelMatrix via argmax) else a
        1-D outcome-index sequence.
        """
        labels = np.asarray(labels)
        if labels.ndim == 2 and binary_label_matrix:
            observed = labels.argmax(axis=-1)
        else:
            observed = labels.ravel().astype(np.int64)
        if observed.shape[0] == 0:
            raise ValueError("Cannot decode an empty sequence")
        n = self.states
        logp, path = _viterbi_decode(
            jnp.asarray(observed), n,
            math.log(self.p_correct),
            math.log((1.0 - self.p_correct) / (n - 1)),
            math.log(self.meta_stability),
            math.log((1.0 - self.meta_stability) / (n - 1)))
        return float(logp), np.asarray(path)
