"""String table utilities: fingerprint keying, clustering, dedup.

Parity: reference core/util —
- `FingerPrintKeyer` (FingerPrintKeyer.java:33-120): OpenRefine-style
  fingerprint — trim, lowercase, strip punctuation/control chars, split
  on whitespace, sort + uniquify fragments, rejoin, asciify.
- `StringCluster` (StringCluster.java:36-94): fingerprint → {variant:
  count} clusters, `getClusters` sorted largest-first.
- `StringGrid` (StringGrid.java:50-748): a row-major table of strings
  with CSV-ish IO and column surgery (select/filter/sort/split/merge/
  fill-down/dedupe-by-cluster/similarity filtering). The reference's
  `dedupeByCluster` (:291) stops at printing candidate clusters; here
  dedup actually rewrites each variant to its cluster's most frequent
  form.

These are host-side data-cleaning helpers feeding the NLP pipeline —
pure Python by design (no device work to map to TPU).
"""

from __future__ import annotations

import re
import unicodedata
from collections import defaultdict
from typing import Dict, Iterable, List, Optional, Sequence

from deeplearning4j_tpu.utils.math_utils import string_similarity

__all__ = ["FingerPrintKeyer", "StringCluster", "StringGrid", "NONE"]

NONE = "NONE"  # reference StringGrid.NONE :57

_PUNCT_CTRL = re.compile(r"[^\w\s]|[\x00-\x08\x0a-\x1f\x7f]|_")


class FingerPrintKeyer:
    """reference FingerPrintKeyer.java:38-58."""

    def key(self, s: str) -> str:
        if s is None:
            raise ValueError("Fingerprint keyer accepts a single string")
        s = s.strip().lower()
        s = _PUNCT_CTRL.sub("", s)
        frags = sorted(set(s.split()))
        return self._asciify(" ".join(frags))

    @staticmethod
    def _asciify(s: str) -> str:
        """Strip diacritics to ASCII equivalents (reference asciify :60)."""
        decomposed = unicodedata.normalize("NFKD", s)
        return "".join(c for c in decomposed
                       if not unicodedata.combining(c))


class StringCluster:
    """Cluster strings by fingerprint (reference StringCluster.java:36):
    'Two words', 'TWO words' and 'words two' share one cluster. Maps
    fingerprint → {original string: count}."""

    def __init__(self, strings: Iterable[str]):
        keyer = FingerPrintKeyer()
        self.clusters: Dict[str, Dict[str, int]] = defaultdict(dict)
        for s in strings:
            m = self.clusters[keyer.key(s)]
            m[s] = m.get(s, 0) + 1

    def __getitem__(self, fingerprint: str) -> Dict[str, int]:
        return self.clusters.get(fingerprint, {})

    def __len__(self) -> int:
        return len(self.clusters)

    def get_clusters(self) -> List[Dict[str, int]]:
        """Clusters sorted largest-first (reference getClusters :74 with
        SizeComparator)."""
        return sorted(self.clusters.values(), key=len, reverse=True)

    def canonical(self, s: str) -> str:
        """Most frequent variant in s's cluster (ties: lexicographically
        first, matching the reference's TreeMap ordering)."""
        m = self[FingerPrintKeyer().key(s)]
        if not m:
            return s
        return min(m.items(), key=lambda kv: (-kv[1], kv[0]))[0]


class StringGrid:
    """Row-major string table (reference StringGrid.java:50)."""

    def __init__(self, sep: str, data: Optional[Iterable[str]] = None,
                 num_columns: Optional[int] = None):
        self.sep = sep
        self.rows: List[List[str]] = []
        if data is not None:
            for line in data:
                line = line.rstrip("\n")
                if not line:
                    continue
                self.append_line(line)
            if self.rows:
                num_columns = len(self.rows[0])
        self.num_columns = num_columns or 0

    # ------------------------------------------------------------------ io
    @classmethod
    def from_file(cls, path: str, sep: str) -> "StringGrid":
        """reference fromFile :90."""
        with open(path, encoding="utf-8") as f:
            return cls(sep, f)

    def append_line(self, line: str) -> None:
        row = line.split(self.sep)
        if self.rows and len(row) != len(self.rows[0]):
            raise ValueError(
                f"row has {len(row)} columns, expected {len(self.rows[0])}")
        self.rows.append(row)

    def to_lines(self) -> List[str]:
        """reference toLines :445."""
        return [self.sep.join(r) for r in self.rows]

    def write_lines_to(self, path: str) -> None:
        """reference writeLinesTo :498."""
        with open(path, "w", encoding="utf-8") as f:
            f.write("\n".join(self.to_lines()) + "\n")

    # ------------------------------------------------------------- shape
    def __len__(self) -> int:
        return len(self.rows)

    def get_row(self, i: int) -> List[str]:
        return self.rows[i]

    def get_column(self, column: int) -> List[str]:
        """reference getColumn :670."""
        return [r[column] for r in self.rows]

    def head(self, num: int) -> "StringGrid":
        """First `num` rows (reference head :166 printed; returning is
        more useful)."""
        g = StringGrid(self.sep, num_columns=self.num_columns)
        g.rows = [list(r) for r in self.rows[:num]]
        return g

    def add_row(self, row: Sequence[str]) -> None:
        self.rows.append(list(row))

    def add_column(self, column: Sequence[str]) -> None:
        """reference addColumn :591."""
        if len(column) != len(self.rows):
            raise ValueError("column length != row count")
        for r, v in zip(self.rows, column):
            r.append(v)
        self.num_columns += 1

    # ------------------------------------------------------- row surgery
    def remove_rows_with_empty_column(self, column: int,
                                      missing_value: str = "") -> None:
        """reference removeRowsWithEmptyColumn :156/:202."""
        self.rows = [r for r in self.rows if r[column] != missing_value]

    def remove_columns(self, *columns: int) -> None:
        """reference removeColumns :181."""
        drop = set(columns)
        self.rows = [[v for i, v in enumerate(r) if i not in drop]
                     for r in self.rows]
        self.num_columns -= len(drop)

    def filter_rows_by_column(self, column: int,
                              values: Iterable[str]) -> None:
        """Keep only rows whose column value is in `values` (reference
        filterRowsByColumn :423)."""
        keep = set(values)
        self.rows = [r for r in self.rows if r[column] in keep]

    def select(self, column: int, value: str) -> "StringGrid":
        """reference select :510."""
        g = StringGrid(self.sep, num_columns=self.num_columns)
        g.rows = [list(r) for r in self.rows if r[column] == value]
        return g

    def sort_by(self, column: int) -> None:
        """reference sortBy :434."""
        self.rows.sort(key=lambda r: r[column])

    def fill_down(self, value: str, column: int) -> None:
        """reference fillDown :503."""
        for r in self.rows:
            r[column] = value

    def swap(self, column1: int, column2: int) -> None:
        """reference swap :460."""
        for r in self.rows:
            r[column1], r[column2] = r[column2], r[column1]

    def merge(self, column1: int, column2: int) -> None:
        """Join two columns into column1 and drop column2
        (reference merge :469)."""
        for r in self.rows:
            r[column1] = r[column1] + r[column2]
        self.remove_columns(column2)

    def split(self, column: int, sep_by: str) -> None:
        """Split a column in place into multiple columns
        (reference split :522)."""
        widths = {len(r[column].split(sep_by)) for r in self.rows}
        if len(widths) != 1:
            raise ValueError("column splits into varying widths")
        for r in self.rows:
            parts = r[column].split(sep_by)
            r[column:column + 1] = parts
        self.num_columns += widths.pop() - 1

    def prepend_to_each(self, prefix: str, column: int) -> None:
        """reference prependToEach :578."""
        for r in self.rows:
            r[column] = prefix + r[column]

    def append_to_each(self, suffix: str, column: int) -> None:
        """reference appendToEach :585."""
        for r in self.rows:
            r[column] = r[column] + suffix

    # ----------------------------------------------------------- queries
    def map_by_primary_key(self, column: int) -> Dict[str, List[List[str]]]:
        """reference mapByPrimaryKey :650."""
        out: Dict[str, List[List[str]]] = defaultdict(list)
        for r in self.rows:
            out[r[column]].append(r)
        return dict(out)

    def get_rows_with_duplicate_values_in_column(self, column: int
                                                 ) -> "StringGrid":
        """reference getRowsWithDuplicateValuesInColumn :689."""
        counts: Dict[str, int] = defaultdict(int)
        for r in self.rows:
            counts[r[column]] += 1
        g = StringGrid(self.sep, num_columns=self.num_columns)
        g.rows = [list(r) for r in self.rows if counts[r[column]] > 1]
        return g

    def get_all_with_similarity(self, threshold: float, first_column: int,
                                second_column: int) -> "StringGrid":
        """Rows whose two columns are at least `threshold` similar by
        shared-bigram similarity (reference getAllWithSimilarity :485 →
        MathUtils.stringSimilarity)."""
        g = StringGrid(self.sep, num_columns=self.num_columns)
        g.rows = [list(r) for r in self.rows
                  if string_similarity(r[first_column],
                                       r[second_column]) >= threshold]
        return g

    def filter_by_similarity(self, threshold: float, first_column: int,
                             second_column: int) -> None:
        """Drop rows below the similarity threshold (reference
        filterBySimilarity :566)."""
        self.rows = [r for r in self.rows
                     if string_similarity(r[first_column],
                                          r[second_column]) >= threshold]

    # ---------------------------------------------------------- clustering
    def cluster_column(self, column: int) -> StringCluster:
        """reference clusterColumn :277."""
        return StringCluster(self.get_column(column))

    def dedupe_by_cluster(self, column: int) -> None:
        """Rewrite each value to its fingerprint cluster's most frequent
        variant (reference dedupeByCluster :291 — which identified the
        clusters but never applied the rewrite; completed here)."""
        cluster = self.cluster_column(column)
        for r in self.rows:
            r[column] = cluster.canonical(r[column])

    def dedupe_by_cluster_all(self) -> None:
        """reference dedupeByClusterAll :282."""
        for c in range(self.num_columns):
            self.dedupe_by_cluster(c)
