"""Math helpers (reference core/util/MathUtils.java, 1,291 LoC — the
used-by-something subset, vectorized over numpy instead of per-element
Java loops). Information-theory helpers (entropy/information/idf/tfidf)
feed the NLP stack; the regression/statistics helpers feed evaluation."""

from __future__ import annotations

import math
from typing import Sequence

import numpy as np

SMALL = 1e-6
LOG2 = math.log(2)


# ------------------------------------------------------------- scalar utils
def normalize(val: float, min_: float, max_: float) -> float:
    """Squash val in [min, max] to [0, 1] (MathUtils.normalize :52)."""
    if max_ < min_:
        raise ValueError("max must be >= min")
    if max_ == min_:
        return 0.0
    return (val - min_) / (max_ - min_)


def clamp(value: int, min_: int, max_: int) -> int:
    return max(min_, min(value, max_))


def discretize(value: float, min_: float, max_: float, bin_count: int) -> int:
    """Bin index of value in [min, max] split into bin_count bins (:80)."""
    if bin_count <= 0:
        raise ValueError("bin_count must be positive")
    return int(clamp(int(normalize(value, min_, max_) * bin_count),
                     0, bin_count - 1))


def next_pow_2(v: int) -> int:
    """Smallest power of two >= v (MathUtils.nextPowOf2 :91)."""
    if v <= 0:
        return 1
    return 1 << (int(v) - 1).bit_length()


def binomial(rng: np.random.RandomState, n: int, p: float) -> int:
    return int(rng.binomial(n, p))


def uniform(rng: np.random.RandomState, min_: float, max_: float) -> float:
    return float(rng.uniform(min_, max_))


def sigmoid(x: float) -> float:
    return float(1.0 / (1.0 + np.exp(-np.asarray(x, np.float64))))


def kronecker_delta(i: float, j: float) -> int:
    return 1 if i == j else 0


def factorial(n: float) -> float:
    return float(math.factorial(int(n)))


def permutation(n: float, r: float) -> float:
    return factorial(n) / factorial(n - r)


def combination(n: float, r: float) -> float:
    return factorial(n) / (factorial(r) * factorial(n - r))


def hypotenuse(a: float, b: float) -> float:
    return math.hypot(a, b)


def prob_to_log_odds(prob: float) -> float:
    if prob <= 0 or prob >= 1:
        raise ValueError("probability must be in (0, 1)")
    return math.log(prob / (1 - prob))


def prob_round(value: float, rng: np.random.RandomState) -> int:
    """Stochastic rounding: round up with prob = fractional part (:982)."""
    base = math.floor(value)
    return int(base + (1 if rng.rand() < value - base else 0))


def round_double(value: float, after_decimal_point: int) -> float:
    return round(value, after_decimal_point)


# --------------------------------------------------------------- vector ops
def vector_length(vector: Sequence[float]) -> float:
    """Squared euclidean norm — the reference returns sum of squares
    (MathUtils.vectorLength :235)."""
    v = np.asarray(vector, np.float64)
    return float(np.sum(v * v))


def sum_of_squares(vector: Sequence[float]) -> float:
    return float(np.sum(np.square(np.asarray(vector, np.float64))))


def sum_(nums: Sequence[float]) -> float:
    return float(np.sum(np.asarray(nums, np.float64)))


def times(nums: Sequence[float]) -> float:
    return float(np.prod(np.asarray(nums, np.float64)))


def sum_of_products(*nums: Sequence[float]) -> float:
    arrs = np.asarray(nums, np.float64)
    return float(np.sum(np.prod(arrs, axis=0)))


def variance(vector: Sequence[float]) -> float:
    """Sum of squared mean deviations / (n - 1) (:488)."""
    v = np.asarray(vector, np.float64)
    if v.size < 2:
        return 0.0
    return float(np.sum((v - v.mean()) ** 2) / (v.size - 1))


def min_(doubles: Sequence[float]) -> float:
    return float(np.min(np.asarray(doubles, np.float64)))


def max_(doubles: Sequence[float]) -> float:
    return float(np.max(np.asarray(doubles, np.float64)))


def max_index(doubles: Sequence[float]) -> int:
    return int(np.argmax(np.asarray(doubles, np.float64)))


def normalize_to_one(doubles: Sequence[float]) -> np.ndarray:
    v = np.asarray(doubles, np.float64)
    return v / v.sum()


def logs2probs(a: Sequence[float]) -> np.ndarray:
    """exp(a - max) renormalized (MathUtils.logs2probs :827)."""
    v = np.asarray(a, np.float64)
    p = np.exp(v - v.max())
    return p / p.sum()


# ------------------------------------------------------- information theory
def log2(a: float) -> float:
    return math.log(a) / LOG2


def entropy(vector: Sequence[float]) -> float:
    """Shannon entropy in nats of an (unnormalized) count vector — the
    reference sums -x*log(x) directly (MathUtils.entropy :740)."""
    v = np.asarray(vector, np.float64)
    v = v[v > 0]
    return float(-np.sum(v * np.log(v)))


def information(probabilities: Sequence[float]) -> float:
    """Expected self-information in bits (MathUtils.information :847)."""
    p = np.asarray(probabilities, np.float64)
    p = p[p > 0]
    return float(np.sum(p * np.log(p) / LOG2))


def idf(total_docs: float, num_times_word_appeared: float) -> float:
    """Inverse document frequency (MathUtils.idf :255)."""
    if total_docs <= 0:
        return 0.0
    return math.log10(total_docs / (1.0 + num_times_word_appeared))


def tf(count: int) -> float:
    """Log-scaled term frequency (MathUtils.tf :264)."""
    return math.log10(1 + count)


def tfidf(tf_: float, idf_: float) -> float:
    return tf_ * idf_


def string_similarity(*strings: str) -> float:
    """Shared-character-bigram similarity (MathUtils.stringSimilarity
    :203): |common pairs| * 2 / total pairs."""
    if not strings:
        return 0.0

    def pairs(s: str):
        return [s[i:i + 2] for i in range(len(s) - 1)]

    all_pairs = [pairs(s) for s in strings]
    union = sum(len(p) for p in all_pairs)
    if union == 0:
        return 1.0 if len(set(strings)) == 1 else 0.0
    first = list(all_pairs[0])
    inter = 0
    for other in all_pairs[1:]:
        other = list(other)
        for p in first:
            if p in other:
                inter += 1
                other.remove(p)
    return inter * 2.0 / union


# ----------------------------------------------------- regression/statistics
def correlation(residuals: Sequence[float], target: Sequence[float]) -> float:
    """R^2-style coefficient of determination (MathUtils.correlation :147 —
    ssReg / ssTotal)."""
    ss_total_ = ss_total(residuals, target)
    return ss_reg(residuals, target) / ss_total_ if ss_total_ else 0.0


def ss_reg(residuals: Sequence[float], target: Sequence[float]) -> float:
    """Sum of squares of (target mean - residual) (:172)."""
    r = np.asarray(residuals, np.float64)
    mean = np.mean(np.asarray(target, np.float64))
    return float(np.sum((mean - r) ** 2))


def ss_error(predicted: Sequence[float], target: Sequence[float]) -> float:
    p = np.asarray(predicted, np.float64)
    t = np.asarray(target, np.float64)
    return float(np.sum((t - p) ** 2))


def ss_total(residuals: Sequence[float], target: Sequence[float]) -> float:
    t = np.asarray(target, np.float64)
    return float(np.sum((t - t.mean()) ** 2))


def squared_loss(x: Sequence[float], y: Sequence[float], w0: float,
                 w1: float) -> float:
    xv = np.asarray(x, np.float64)
    yv = np.asarray(y, np.float64)
    return float(np.sum((yv - (w1 * xv + w0)) ** 2))


def w_1(x: Sequence[float], y: Sequence[float], n: int) -> float:
    """OLS slope (MathUtils.w_1 :403)."""
    xv = np.asarray(x, np.float64)[:n]
    yv = np.asarray(y, np.float64)[:n]
    denom = n * np.sum(xv * xv) - np.sum(xv) ** 2
    return float((n * np.sum(xv * yv) - np.sum(xv) * np.sum(yv)) / denom)


def w_0(x: Sequence[float], y: Sequence[float], n: int) -> float:
    """OLS intercept (MathUtils.w_0 :407)."""
    yv = np.asarray(y, np.float64)[:n]
    xv = np.asarray(x, np.float64)[:n]
    return float(yv.mean() - w_1(x, y, n) * xv.mean())


def error_for(actual: float, prediction: float) -> float:
    return actual - prediction


def root_means_squared_error(real: Sequence[float],
                             predicted: Sequence[float]) -> float:
    r = np.asarray(real, np.float64)
    p = np.asarray(predicted, np.float64)
    return float(np.sqrt(np.mean((r - p) ** 2)))


def determination_coefficient(y1: Sequence[float], y2: Sequence[float],
                              n: int) -> float:
    a = np.asarray(y1, np.float64)[:n]
    b = np.asarray(y2, np.float64)[:n]
    c = np.corrcoef(a, b)[0, 1]
    return float(c * c)


def adjusted_r_squared(r_squared: float, num_regressors: int,
                       num_data_points: int) -> float:
    denom = num_data_points - num_regressors - 1
    if denom <= 0:
        return float("nan")
    return 1 - (1 - r_squared) * (num_data_points - 1) / denom
