"""FIFO queue that spills every element to disk.

Parity: reference core/util/DiskBasedQueue.java:38-203 — each element is
serialized to its own file under a scratch directory; the in-memory state
is only the ordered list of file paths, so arbitrarily long queues hold
O(1) payload in RAM. Used to stage datasets/updates bigger than memory.

Elements are serialized with the same npz+JSON codec as checkpoints
(scaleout/checkpoint.py) — numpy/JAX arrays and JSON-able containers, no
pickle, so a queue directory on shared storage can't execute code on read.
"""

from __future__ import annotations

import os
import shutil
import tempfile
import threading
import uuid
from collections import deque
from typing import Any, Iterator, Optional

from deeplearning4j_tpu.scaleout.checkpoint import dump_payload, load_payload


class DiskBasedQueue:
    def __init__(self, path: Optional[str] = None):
        self.dir = path or tempfile.mkdtemp(prefix="dl4j_tpu_queue_")
        os.makedirs(self.dir, exist_ok=True)
        self._paths: deque = deque()
        self._lock = threading.Lock()

    # ------------------------------------------------------------- queue api
    def add(self, item: Any) -> bool:
        return self.offer(item)

    def offer(self, item: Any) -> bool:
        data = dump_payload({"item": item})
        path = os.path.join(self.dir, f"{uuid.uuid4().hex}.npz")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
        with self._lock:
            self._paths.append(path)
        return True

    def poll(self) -> Optional[Any]:
        """Remove and return the head, or None when empty."""
        with self._lock:
            if not self._paths:
                return None
            path = self._paths.popleft()
        with open(path, "rb") as f:
            item = load_payload(f.read())["item"]
        os.unlink(path)
        return item

    def remove(self) -> Any:
        item = self.poll()
        if item is None:
            raise IndexError("remove() on empty DiskBasedQueue")
        return item

    def peek(self) -> Optional[Any]:
        with self._lock:
            if not self._paths:
                return None
            path = self._paths[0]
        with open(path, "rb") as f:
            return load_payload(f.read())["item"]

    def element(self) -> Any:
        item = self.peek()
        if item is None:
            raise IndexError("element() on empty DiskBasedQueue")
        return item

    def size(self) -> int:
        with self._lock:
            return len(self._paths)

    def __len__(self) -> int:
        return self.size()

    def is_empty(self) -> bool:
        return self.size() == 0

    def add_all(self, items) -> bool:
        for item in items:
            self.offer(item)
        return True

    def __iter__(self) -> Iterator[Any]:
        """Drain iterator: yields and removes head-first."""
        while True:
            item = self.poll()
            if item is None:
                return
            yield item

    def clear(self) -> None:
        with self._lock:
            paths = list(self._paths)
            self._paths.clear()
        for p in paths:
            try:
                os.unlink(p)
            except OSError:
                pass

    def close(self) -> None:
        self.clear()
        shutil.rmtree(self.dir, ignore_errors=True)

    def __enter__(self) -> "DiskBasedQueue":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
