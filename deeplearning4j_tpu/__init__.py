"""deeplearning4j_tpu — a TPU-native deep-learning framework.

Capability parity with early Deeplearning4j (reference surveyed in SURVEY.md):
layer-based NN core, pluggable batch optimizers, JSON-serializable configuration,
dataset pipeline, evaluation, t-SNE, NLP stack, and data-parallel distributed
training — rebuilt idiomatically for TPU: JAX/XLA autodiff in place of
hand-written backprop, `jax.sharding.Mesh` + collectives in place of
Akka/Hazelcast/Spark parameter averaging, and a native (C++) host runtime for IO.
"""

__version__ = "0.1.0"

from deeplearning4j_tpu.config import (  # noqa: F401
    NeuralNetConfiguration,
    MultiLayerConfiguration,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork  # noqa: F401
from deeplearning4j_tpu.eval.evaluation import Evaluation  # noqa: F401
