"""Whole-network configuration with JSON round-trip.

Parity: reference core/nn/conf/MultiLayerConfiguration.java:29-41 (hiddenLayerSizes,
per-layer conf list, pretrain flag, per-layer `OutputPreProcessor` map,
toJson:141 / fromJson:155). Preprocessors serialize by registry name so the
JSON stays self-contained (the reference used Jackson class-name binding).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List

from deeplearning4j_tpu.config.neural_net_configuration import NeuralNetConfiguration

# Registry: name -> zero/kw-arg factory for input/output preprocessors
# (populated by deeplearning4j_tpu.nn.preprocessors at import time).
PREPROCESSOR_REGISTRY: Dict[str, Any] = {}


def register_preprocessor(name: str):
    def deco(cls):
        PREPROCESSOR_REGISTRY[name] = cls
        cls.registry_name = name
        return cls

    return deco


@dataclass
class MultiLayerConfiguration:
    confs: List[NeuralNetConfiguration] = field(default_factory=list)
    hidden_layer_sizes: List[int] = field(default_factory=list)
    pretrain: bool = True
    backprop: bool = True
    use_drop_connect: bool = False
    damping_factor: float = 10.0
    #: layer index -> preprocessor applied to that layer's input
    input_preprocessors: Dict[int, Any] = field(default_factory=dict)
    #: layer index -> preprocessor applied to that layer's output
    output_preprocessors: Dict[int, Any] = field(default_factory=dict)

    @property
    def n_layers(self) -> int:
        return len(self.confs)

    def conf(self, i: int) -> NeuralNetConfiguration:
        return self.confs[i]

    # ----------------------------------------------------------- JSON wire
    def to_dict(self) -> Dict[str, Any]:
        def pp_map(d):
            return {
                str(i): {"name": p.registry_name, "args": p.serializable_args()}
                for i, p in d.items()
            }

        return {
            "confs": [c.to_dict() for c in self.confs],
            "hidden_layer_sizes": list(self.hidden_layer_sizes),
            "pretrain": self.pretrain,
            "backprop": self.backprop,
            "use_drop_connect": self.use_drop_connect,
            "damping_factor": self.damping_factor,
            "input_preprocessors": pp_map(self.input_preprocessors),
            "output_preprocessors": pp_map(self.output_preprocessors),
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "MultiLayerConfiguration":
        def pp_map(m):
            out = {}
            for i, spec in (m or {}).items():
                factory = PREPROCESSOR_REGISTRY[spec["name"]]
                out[int(i)] = factory(**spec.get("args", {}))
            return out

        return cls(
            confs=[NeuralNetConfiguration.from_dict(c) for c in d["confs"]],
            hidden_layer_sizes=list(d.get("hidden_layer_sizes", [])),
            pretrain=d.get("pretrain", True),
            backprop=d.get("backprop", True),
            use_drop_connect=d.get("use_drop_connect", False),
            damping_factor=d.get("damping_factor", 10.0),
            input_preprocessors=pp_map(d.get("input_preprocessors")),
            output_preprocessors=pp_map(d.get("output_preprocessors")),
        )

    @classmethod
    def from_json(cls, s: str) -> "MultiLayerConfiguration":
        return cls.from_dict(json.loads(s))
