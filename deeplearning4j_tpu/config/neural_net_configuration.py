"""Per-layer hyperparameter configuration with JSON round-trip.

Parity: reference core/nn/conf/NeuralNetConfiguration.java (~40 fields, fluent
`Builder` at :939, Jackson toJson/fromJson at :837/:859). The JSON form is the
wire format: distributed runtimes ship configs to workers as JSON strings
(reference akka BaseMultiLayerNetworkWorkPerformer.java:37, spark
IterativeReduceFlatMap.java:60) and the canonical checkpoint is
(config JSON, packed param vector) (MultiLayerNetwork.java:91).

TPU-native deltas: `seed` + explicit JAX PRNG keys replace the serialized Java
`rng`/`dist` objects; `dtype`/`compute_dtype` added for bf16 MXU paths.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


class OptimizationAlgorithm:
    """Parity: reference core/nn/api/OptimizationAlgorithm.java."""

    GRADIENT_DESCENT = "gradient_descent"
    CONJUGATE_GRADIENT = "conjugate_gradient"
    HESSIAN_FREE = "hessian_free"
    LBFGS = "lbfgs"
    ITERATION_GRADIENT_DESCENT = "iteration_gradient_descent"


@dataclass
class NeuralNetConfiguration:
    # --- learning-rate / regularization (GradientAdjustment.java:66-113) ---
    lr: float = 1e-1
    momentum: float = 0.5
    #: iteration -> momentum, the reference's `momentumAfter` schedule
    momentum_after: Dict[int, float] = field(default_factory=dict)
    l2: float = 0.0
    use_regularization: bool = False
    use_adagrad: bool = True
    constrain_gradient_to_unit_norm: bool = False
    # --- stochasticity ---
    dropout: float = 0.0
    use_drop_connect: bool = False
    #: denoising-AE corruption level (BasePretrainNetwork.getCorruptedInput)
    corruption_level: float = 0.3
    sparsity: float = 0.0
    #: contrastive-divergence steps (RBM CD-k)
    k: int = 1
    #: causal masking for attention layers (beyond-reference capability)
    causal: bool = False
    #: attention heads (self_attention layer; n_out must divide by it)
    n_heads: int = 1
    # --- architecture ---
    layer: str = "dense"  # layer type name, resolved via nn.layers registry
    n_in: int = 0
    n_out: int = 0
    activation_function: str = "sigmoid"
    weight_init: str = "vi"
    dist: Optional[Dict[str, Any]] = None
    #: RBM unit types: binary | gaussian | softmax | linear / rectified
    visible_unit: str = "binary"
    hidden_unit: str = "binary"
    # --- convolution (ConvolutionDownSampleLayer / ConvolutionParamInitializer) ---
    filter_size: Optional[List[int]] = None  # [h, w]
    stride: Optional[List[int]] = None  # pool stride [h, w]
    num_feature_maps: int = 1
    num_in_feature_maps: int = 1
    # --- training loop ---
    optimization_algo: str = OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT
    loss_function: str = "reconstruction_crossentropy"
    num_iterations: int = 100
    batch_size: int = 100
    minimize: bool = True
    num_line_search_iterations: int = 5
    # --- rng / dtypes ---
    seed: int = 123
    dtype: str = "float32"  # parameter dtype
    compute_dtype: str = "float32"  # matmul dtype; "bfloat16" for MXU speed
    # --- bookkeeping (reference `variables` list: param names registered
    #     by ParamInitializers) ---
    variables: List[str] = field(default_factory=list)

    # ------------------------------------------------------------------ API
    def variable(self, name: str) -> None:
        """Register a named parameter (reference addVariable)."""
        if name not in self.variables:
            self.variables.append(name)

    def momentum_for_iteration(self, iteration: int) -> float:
        """Resolve the momentum schedule (reference GradientAdjustment.java:79)."""
        m = self.momentum
        for after, value in sorted(self.momentum_after.items()):
            if iteration >= int(after):
                m = value
        return m

    def copy(self, **overrides) -> "NeuralNetConfiguration":
        new = dataclasses.replace(self)
        # dataclasses.replace keeps shared mutable fields; deep-copy them
        new.momentum_after = dict(self.momentum_after)
        new.variables = list(self.variables)
        new.filter_size = list(self.filter_size) if self.filter_size else None
        new.stride = list(self.stride) if self.stride else None
        new.dist = dict(self.dist) if self.dist else None
        for k, v in overrides.items():
            if not hasattr(new, k):
                raise AttributeError(f"No config field {k!r}")
            setattr(new, k, v)
        return new

    # ----------------------------------------------------------- JSON wire
    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["momentum_after"] = {str(k): v for k, v in self.momentum_after.items()}
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "NeuralNetConfiguration":
        d = dict(d)
        if "momentum_after" in d and d["momentum_after"] is not None:
            d["momentum_after"] = {int(k): v for k, v in d["momentum_after"].items()}
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"Unknown NeuralNetConfiguration fields: {sorted(unknown)}")
        return cls(**d)

    @classmethod
    def from_json(cls, s: str) -> "NeuralNetConfiguration":
        return cls.from_dict(json.loads(s))

    # ------------------------------------------------------------- builder
    @classmethod
    def builder(cls) -> "NeuralNetConfigurationBuilder":
        return NeuralNetConfigurationBuilder()


class NeuralNetConfigurationBuilder:
    """Fluent builder, parity with NeuralNetConfiguration.Builder (:939).

    Methods are snake_case field setters; `list(n)` hands off to the
    ListBuilder for stacked configs (reference `Builder.list(int)` :769).
    """

    def __init__(self):
        self._conf = NeuralNetConfiguration()

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        if not hasattr(self._conf, name):
            raise AttributeError(f"No config field {name!r}")

        def setter(value):
            setattr(self._conf, name, value)
            return self

        return setter

    def list(self, n_layers: int) -> "ListBuilder":
        return ListBuilder(self._conf, n_layers)

    def build(self) -> NeuralNetConfiguration:
        return self._conf.copy()


class ListBuilder:
    """Builds a MultiLayerConfiguration from a base conf + per-layer overrides.

    Parity: reference NeuralNetConfiguration.ListBuilder.override(ConfOverride)
    (:769,:804-806) — each layer starts as a copy of the base conf and an
    override callback or kwargs dict mutates it.
    """

    def __init__(self, base: NeuralNetConfiguration, n_layers: int):
        self._base = base
        self._n = n_layers
        self._overrides: List[Any] = []
        self._hidden_layer_sizes: List[int] = []
        self._pretrain = True
        self._backprop = True
        self._input_preprocessors: Dict[int, Any] = {}

    def hidden_layer_sizes(self, sizes: List[int]) -> "ListBuilder":
        self._hidden_layer_sizes = list(sizes)
        return self

    def pretrain(self, flag: bool) -> "ListBuilder":
        self._pretrain = flag
        return self

    def backprop(self, flag: bool) -> "ListBuilder":
        self._backprop = flag
        return self

    def override(self, layer_index: int = -1, fn=None, **kwargs) -> "ListBuilder":
        """Override layer `layer_index` (or all if -1) with kwargs or callback."""
        self._overrides.append((layer_index, fn, kwargs))
        return self

    def input_preprocessor(self, layer_index: int, preprocessor) -> "ListBuilder":
        self._input_preprocessors[layer_index] = preprocessor
        return self

    def build(self):
        from deeplearning4j_tpu.config.multi_layer_configuration import (
            MultiLayerConfiguration,
        )

        confs = []
        for i in range(self._n):
            conf = self._base.copy()
            for idx, fn, kwargs in self._overrides:
                if idx in (-1, i):
                    for k, v in kwargs.items():
                        setattr(conf, k, v)
                    if fn is not None:
                        fn(i, conf)
            confs.append(conf)
        return MultiLayerConfiguration(
            confs=confs,
            hidden_layer_sizes=self._hidden_layer_sizes,
            pretrain=self._pretrain,
            backprop=self._backprop,
            input_preprocessors=self._input_preprocessors,
        )
