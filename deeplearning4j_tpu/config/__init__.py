from deeplearning4j_tpu.config.neural_net_configuration import (  # noqa: F401
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.config.multi_layer_configuration import (  # noqa: F401
    MultiLayerConfiguration,
)
