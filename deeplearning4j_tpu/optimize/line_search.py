"""Backtracking line search.

Parity: reference core/optimize/solvers/BackTrackLineSearch.java:142 —
Armijo-condition backtracking along a search direction with step shrinking,
used by the GRADIENT_DESCENT / CONJUGATE_GRADIENT / LBFGS solvers.

TPU-native: the whole search is a `lax.while_loop` over flat parameter
vectors, so it compiles into the surrounding jit instead of bouncing to host
per function evaluation.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp

ALF = 1e-4  # Armijo sufficient-decrease constant (reference ALF)
STEP_MIN = 1e-10


class LineSearchResult(NamedTuple):
    step: jnp.ndarray  # chosen step size (0.0 if no improvement found)
    score: jnp.ndarray  # score at the accepted point


def backtrack_line_search(
    loss_flat: Callable[[jnp.ndarray], jnp.ndarray],
    x: jnp.ndarray,
    score0: jnp.ndarray,
    grad0: jnp.ndarray,
    direction: jnp.ndarray,
    initial_step: float = 1.0,
    max_iterations: int = 5,
    max_step: float = 100.0,
) -> LineSearchResult:
    """Find step `a` so that loss(x + a*d) sufficiently decreases.

    `direction` should be a descent direction (slope = <grad0, d> < 0); if it
    is not, the search immediately returns step 0 like the reference's slope
    check.
    """
    dnorm = jnp.linalg.norm(direction)
    # Truncate overly long steps (reference: scale direction to maxStep)
    direction = jnp.where(dnorm > max_step, direction * (max_step / (dnorm + 1e-12)),
                          direction)
    slope = jnp.vdot(grad0, direction)

    def cond(state):
        a, score, it, done = state
        return jnp.logical_and(jnp.logical_not(done), it < max_iterations)

    def body(state):
        a, _, it, _ = state
        new_score = loss_flat(x + a * direction)
        ok = new_score <= score0 + ALF * a * slope
        ok = jnp.logical_and(ok, jnp.isfinite(new_score))
        next_a = jnp.where(ok, a, a * 0.5)
        done = jnp.logical_or(ok, next_a < STEP_MIN)
        return (next_a, jnp.where(ok, new_score, score0), it + 1, done)

    a0 = jnp.asarray(initial_step, x.dtype)
    a, score, _, done = jax.lax.while_loop(
        cond, body, (a0, score0, jnp.asarray(0), jnp.asarray(False)))
    # If the loop exhausted without satisfying Armijo, report zero step.
    ok = jnp.logical_and(done, slope < 0)
    return LineSearchResult(step=jnp.where(ok, a, 0.0),
                            score=jnp.where(ok, score, score0))
