"""Iteration listeners.

Parity: reference core/optimize/api/IterationListener.java (hook invoked from
BaseOptimizer.java:168-170), ScoreIterationListener (listeners/
ScoreIterationListener.java:41), ComposableIterationListener.

Beyond parity (SURVEY §5 tracing/profiling): the reference had nothing past
SLF4J score logging; the TPU equivalents are `StepTimeListener` (wall-clock
step-time metrics with summary stats) and `ProfilerListener` (toggles a
jax.profiler trace for a window of iterations so steps can be inspected in
xprof/TensorBoard).

Telemetry: the listeners keep their public API but also publish into the
process-global registry (deeplearning4j_tpu/telemetry) — scores land on
the `dl4j_train_loss` gauge and StepTimeListener's deltas in the
`dl4j_train_step_seconds{source="listener"}` histogram — so anything a
listener records shows up in a /metrics scrape without a second code
path (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import logging
import statistics
import time
from typing import Iterable, Optional

from deeplearning4j_tpu import telemetry

log = logging.getLogger(__name__)

_M_LOSS = telemetry.gauge(
    "dl4j_train_loss", "last host-synced training score")
_M_STEP_S = telemetry.histogram("dl4j_train_step_seconds")
_M_ITER = telemetry.counter(
    "dl4j_listener_iterations", "iteration_done listener dispatches")


class IterationListener:
    def iteration_done(self, model, iteration: int, score: float) -> None:
        raise NotImplementedError


class ScoreIterationListener(IterationListener):
    def __init__(self, print_every: int = 10):
        self.print_every = max(1, print_every)

    def iteration_done(self, model, iteration: int, score: float) -> None:
        _M_ITER.inc()
        _M_LOSS.set(score)
        if iteration % self.print_every == 0:
            log.info("Score at iteration %d is %s", iteration, score)


class ComposableIterationListener(IterationListener):
    def __init__(self, listeners: Iterable[IterationListener]):
        self.listeners = list(listeners)

    def iteration_done(self, model, iteration: int, score: float) -> None:
        for listener in self.listeners:
            listener.iteration_done(model, iteration, score)


class CollectScoresListener(IterationListener):
    """Test/diagnostic helper: records every (iteration, score)."""

    def __init__(self):
        self.scores = []

    def iteration_done(self, model, iteration: int, score: float) -> None:
        score = float(score)
        _M_ITER.inc()
        _M_LOSS.set(score)
        self.scores.append((iteration, score))


class StepTimeListener(IterationListener):
    """Wall-clock time between consecutive iterations.

    The reference's listener tier stops at score printing
    (ScoreIterationListener.java:41); on TPU the first-class observability
    signal is step time — it is what the dispatch/compile/HBM story shows up
    in. Times are measured listener-to-listener, so they include everything
    in a step (grad, update, host sync), not just device compute.
    """

    def __init__(self, log_every: int = 0):
        self.log_every = log_every
        self.step_times: list = []
        self._last: Optional[float] = None

    def iteration_done(self, model, iteration: int, score: float) -> None:
        now = time.perf_counter()
        if self._last is not None:
            dt = now - self._last
            self.step_times.append(dt)
            _M_STEP_S.labels(source="listener").observe(dt)
            if self.log_every and len(self.step_times) % self.log_every == 0:
                log.info("step %d: %.3f ms", iteration, dt * 1e3)
        self._last = now

    def reset(self) -> None:
        self.step_times.clear()
        self._last = None

    def optimization_done(self, model) -> None:
        """Solver hook: the gap between two optimize() runs (batch prep,
        next phase's compile) is not a step — don't time across it."""
        self._last = None

    def summary(self) -> dict:
        """{count, mean_ms, median_ms, p90_ms, max_ms} over recorded steps."""
        if not self.step_times:
            return {"count": 0}
        ms = sorted(t * 1e3 for t in self.step_times)
        return {
            "count": len(ms),
            "mean_ms": statistics.fmean(ms),
            "median_ms": statistics.median(ms),
            "p90_ms": ms[min(len(ms) - 1, int(0.9 * len(ms)))],
            "max_ms": ms[-1],
        }


class GuardianListener(IterationListener):
    """Base for listeners that want guardian events (skips, rollbacks,
    autosaves, preemption flushes, aborts — optimize/guardian.py). Any
    listener exposing `guardian_event` is notified; subclassing this is
    just the convenient way to get the no-op `iteration_done`."""

    def iteration_done(self, model, iteration: int, score: float) -> None:
        pass

    def guardian_event(self, model, event) -> None:
        raise NotImplementedError


class CollectGuardianEvents(GuardianListener):
    """Test/diagnostic helper: records every GuardianEvent."""

    def __init__(self):
        self.events = []

    def guardian_event(self, model, event) -> None:
        self.events.append(event)

    def kinds(self) -> list:
        return [e.kind for e in self.events]


class ProfilerListener(IterationListener):
    """Toggle a jax.profiler trace over iterations [start, stop).

    Writes an xprof-compatible trace to `log_dir` covering the chosen
    iteration window (skipping iteration 0 by default — that is where
    compilation lands and it would swamp the steady-state trace). Because
    the listener hook fires AFTER each iteration, the trace is started once
    iteration `start - 1` has completed, so device work for iterations
    [start, stop) is captured. If optimization terminates before the window
    closes, `optimization_done` stops the trace deterministically.
    """

    def __init__(self, log_dir: str, start: int = 1, stop: int = 4):
        if stop <= start:
            raise ValueError(f"stop ({stop}) must be > start ({start})")
        if start < 1:
            raise ValueError("start must be >= 1 (the hook fires after "
                             "each iteration; iteration 0 cannot be traced)")
        self.log_dir = log_dir
        self.start = start
        self.stop = stop
        self._active = False

    def iteration_done(self, model, iteration: int, score: float) -> None:
        import jax

        if (not self._active and self.start - 1 <= iteration < self.stop - 1):
            jax.profiler.start_trace(self.log_dir)
            self._active = True
        elif self._active and iteration >= self.stop - 1:
            self._stop_trace()

    def optimization_done(self, model) -> None:
        """Solver hook: close an open trace when the loop ends early."""
        if self._active:
            self._stop_trace()

    def _stop_trace(self) -> None:
        import jax

        try:
            jax.profiler.stop_trace()
        finally:
            self._active = False

    def __del__(self):
        if getattr(self, "_active", False):
            try:
                self._stop_trace()
            except Exception:
                pass
