"""Iteration listeners.

Parity: reference core/optimize/api/IterationListener.java (hook invoked from
BaseOptimizer.java:168-170), ScoreIterationListener (listeners/
ScoreIterationListener.java:41), ComposableIterationListener.
"""

from __future__ import annotations

import logging
from typing import Iterable

log = logging.getLogger(__name__)


class IterationListener:
    def iteration_done(self, model, iteration: int, score: float) -> None:
        raise NotImplementedError


class ScoreIterationListener(IterationListener):
    def __init__(self, print_every: int = 10):
        self.print_every = max(1, print_every)

    def iteration_done(self, model, iteration: int, score: float) -> None:
        if iteration % self.print_every == 0:
            log.info("Score at iteration %d is %s", iteration, score)


class ComposableIterationListener(IterationListener):
    def __init__(self, listeners: Iterable[IterationListener]):
        self.listeners = list(listeners)

    def iteration_done(self, model, iteration: int, score: float) -> None:
        for listener in self.listeners:
            listener.iteration_done(model, iteration, score)


class CollectScoresListener(IterationListener):
    """Test/diagnostic helper: records every (iteration, score)."""

    def __init__(self):
        self.scores = []

    def iteration_done(self, model, iteration: int, score: float) -> None:
        self.scores.append((iteration, float(score)))
