"""Solver facade: pick an optimizer from conf.optimization_algo.

Parity: reference core/optimize/Solver.java:37-60 (`Solver.Builder`, the
algorithm switch in `getOptimizer`).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax.numpy as jnp

from deeplearning4j_tpu.config.neural_net_configuration import OptimizationAlgorithm
from deeplearning4j_tpu.optimize.solvers import (
    BaseOptimizer,
    ConjugateGradient,
    GradientAscent,
    IterationGradientDescent,
    LBFGS,
    StochasticHessianFree,
)

_ALGOS = {
    OptimizationAlgorithm.GRADIENT_DESCENT: GradientAscent,
    OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT: IterationGradientDescent,
    OptimizationAlgorithm.CONJUGATE_GRADIENT: ConjugateGradient,
    OptimizationAlgorithm.LBFGS: LBFGS,
    OptimizationAlgorithm.HESSIAN_FREE: StochasticHessianFree,
}


class Solver:
    def __init__(self, conf, loss: Callable[[jnp.ndarray], jnp.ndarray],
                 listeners: Optional[Sequence] = None,
                 terminations: Optional[Sequence] = None,
                 model=None, **optimizer_kwargs):
        self.conf = conf
        self.loss = loss
        self.listeners = listeners
        self.terminations = terminations
        self.model = model
        self.optimizer_kwargs = optimizer_kwargs
        self._optimizer: Optional[BaseOptimizer] = None

    def get_optimizer(self) -> BaseOptimizer:
        # one optimizer instance per Solver: its jitted step compiles once
        # and is reused across optimize() calls (mini-batches)
        if self._optimizer is None:
            algo = self.conf.optimization_algo.lower()
            try:
                cls = _ALGOS[algo]
            except KeyError:
                raise ValueError(
                    f"Unknown optimization algorithm {algo!r}; "
                    f"known: {sorted(_ALGOS)}"
                ) from None
            self._optimizer = cls(
                self.conf, self.loss, listeners=self.listeners,
                terminations=self.terminations, model=self.model,
                **self.optimizer_kwargs)
        return self._optimizer

    def optimize(self, params, *data, rng_key=None, sync: bool = True):
        return self.get_optimizer().optimize(params, *data, rng_key=rng_key,
                                             sync=sync)
