from deeplearning4j_tpu.optimize.solver import Solver  # noqa: F401
from deeplearning4j_tpu.optimize.updater import GradientUpdater  # noqa: F401
from deeplearning4j_tpu.optimize.listeners import (  # noqa: F401
    IterationListener,
    ScoreIterationListener,
    ComposableIterationListener,
    CollectScoresListener,
    CollectGuardianEvents,
    GuardianListener,
    StepTimeListener,
    ProfilerListener,
)
from deeplearning4j_tpu.optimize.guardian import (  # noqa: F401
    GuardianAbort,
    GuardianPolicy,
    TrainingPreempted,
)
