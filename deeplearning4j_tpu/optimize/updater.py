"""Per-variable gradient adjustment.

Parity: reference core/optimize/GradientAdjustment.updateGradientAccordingToParams
(GradientAdjustment.java:66-113): AdaGrad-or-plain-lr scaling, momentum with an
iteration-indexed schedule, optional unit-norm constraint.

Two deliberate deltas: (a) the reference divides the final update by the
batch size because its losses are sums; our losses (ops.losses) are
per-example means, which makes that division a no-op-equivalent on the
plain-lr branch (sum/batch == mean) — but NOT on the AdaGrad branch:
AdaGrad normalizes the gradient by its own accumulated scale, so sum-vs-mean
cancels and the reference's ÷batchSize is a REAL 1/B step-size factor that
must be reproduced (without it, batch-512 training takes 512× the
reference's step and diverges). Callers therefore pass `batch_size` into
`update()` on the adagrad path; (b) the reference's L2 term lives in the
LOSS here (MultiLayerNetwork.loss_fn / pretrain losses), not in the
updater, so every solver path — including the loss-only line-search
family — sees the same regularized objective exactly once.

Implemented as a pure (state, grads) -> (updates, state) transform over
pytrees so it jits and shards; state is {hist, velocity} mirroring ND4J's
AdaGrad historicalGradient and the momentum buffer.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple

import jax
import jax.numpy as jnp

ADAGRAD_EPS = 1e-6


class UpdaterState(NamedTuple):
    hist: Any  # adagrad accumulator, same pytree as params
    velocity: Any  # momentum buffer
    iteration: jnp.ndarray  # scalar int32


class GradientUpdater:
    """Builds jit-friendly update transforms from a NeuralNetConfiguration."""

    def __init__(self, conf, divide_by_batch: bool = False):
        self.conf = conf
        self.divide_by_batch = divide_by_batch

    def init(self, params) -> UpdaterState:
        # hist and velocity must be DISTINCT buffers: the train step
        # donates the state tree, and XLA rejects donating one buffer
        # through two aliasing leaves
        return UpdaterState(
            hist=jax.tree_util.tree_map(jnp.zeros_like, params),
            velocity=jax.tree_util.tree_map(jnp.zeros_like, params),
            iteration=jnp.zeros((), jnp.int32))

    def _momentum_at(self, iteration):
        """Piecewise-constant momentum schedule (GradientAdjustment.java:79)."""
        c = self.conf
        m = jnp.asarray(c.momentum, jnp.float32)
        for after, value in sorted(c.momentum_after.items()):
            m = jnp.where(iteration >= after, value, m)
        return m

    def update(self, grads, state: UpdaterState, params,
               batch_size=1):
        """Returns (updates, new_state); apply as params -= updates (minimize).

        `batch_size` may be a Python int (static — the historical path) or
        a traced int32 scalar (the device-feed pipeline passes the REAL
        example count of a shape-bucketed batch so the ÷batchSize factor
        ignores masked padding rows without recompiling per count)."""
        c = self.conf
        it = state.iteration

        if c.use_adagrad:
            hist = jax.tree_util.tree_map(
                lambda h, g: h + jnp.square(g), state.hist, grads)
            scaled = jax.tree_util.tree_map(
                lambda g, h: c.lr * g / (jnp.sqrt(h) + ADAGRAD_EPS),
                grads, hist)
        else:
            hist = state.hist
            scaled = jax.tree_util.tree_map(lambda g: c.lr * g, grads)

        m = self._momentum_at(it)
        velocity = jax.tree_util.tree_map(
            lambda v, g: m * v + g, state.velocity, scaled)
        updates = velocity

        if c.constrain_gradient_to_unit_norm:
            flat, _ = jax.flatten_util.ravel_pytree(updates)
            norm = jnp.linalg.norm(flat) + 1e-12
            updates = jax.tree_util.tree_map(lambda u: u / norm, updates)

        # reference GradientAdjustment ends with gradient.divi(batchSize);
        # with mean losses that only changes the adagrad branch (see module
        # docstring) — divide there, or wherever explicitly requested
        if c.use_adagrad or self.divide_by_batch:
            if isinstance(batch_size, (int, float)):
                if batch_size > 1:
                    updates = jax.tree_util.tree_map(
                        lambda u: u / batch_size, updates)
            else:  # traced count: divide per-leaf in the leaf's dtype so
                # bf16 compute nets don't get silently promoted to f32
                bs = jnp.maximum(batch_size, 1)
                updates = jax.tree_util.tree_map(
                    lambda u: u / bs.astype(u.dtype), updates)

        return updates, UpdaterState(hist=hist, velocity=velocity,
                                     iteration=it + 1)


class NetworkGradientUpdater:
    """Per-layer GradientAdjustment over a {layer index -> param table} pytree.

    The reference adjusts gradients per layer with THAT layer's conf
    (BaseOptimizer/GradientAdjustment run inside each layer's solver), so
    per-layer overrides like `ListBuilder.override(0, lr=...)` must be honored
    on the whole-network backprop path too. Each layer gets its own
    GradientUpdater; state is {layer index -> UpdaterState}.
    """

    def __init__(self, confs_by_key: Dict[str, object],
                 divide_by_batch: bool = False):
        self.updaters = {k: GradientUpdater(c, divide_by_batch)
                         for k, c in confs_by_key.items()}

    @classmethod
    def for_network(cls, network) -> "NetworkGradientUpdater":
        return cls({str(i): layer.conf
                    for i, layer in enumerate(network.layers)})

    def init(self, params) -> Dict[str, UpdaterState]:
        return {k: upd.init(params[k]) for k, upd in self.updaters.items()}

    def update(self, grads, state, params, batch_size: int = 1):
        updates, new_state = {}, {}
        for k, upd in self.updaters.items():
            updates[k], new_state[k] = upd.update(grads[k], state[k],
                                                  params[k], batch_size)
        return updates, new_state
