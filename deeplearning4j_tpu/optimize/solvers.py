"""Batch optimizers.

Parity: reference core/optimize/solvers/ — `BaseOptimizer.optimize` main loop
(BaseOptimizer.java:128-195: gradientAndScore -> termination checks ->
line-search step -> listeners -> re-score), `IterationGradientDescent`,
`GradientAscent` (line-search gradient descent), `ConjugateGradient`
(Polak-Ribiere), `LBFGS` (two-loop recursion), `StochasticHessianFree`
(CG-minimized curvature, StochasticHessianFree.java:87-184).

TPU-native design: optimizers work on the FLAT parameter vector
(jax.flatten_util.ravel_pytree — the same representation as the reference's
params()/setParameters pack/unpack, MultiLayerNetwork.java:784/:831) with a
jitted value_and_grad; hand-written backprop and the hand-written R-op
(MultiLayerNetwork.backPropGradientR :1475) are replaced by jax.grad and
jvp-based Hessian/Gauss-Newton vector products.
"""

from __future__ import annotations

import logging
from functools import partial
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

from deeplearning4j_tpu.optimize.line_search import backtrack_line_search
from deeplearning4j_tpu.optimize.listeners import IterationListener
from deeplearning4j_tpu.optimize.terminations import (
    EpsTermination,
    Norm2Termination,
    TerminationCondition,
    ZeroDirection,
)
from deeplearning4j_tpu.optimize.updater import GradientUpdater

log = logging.getLogger(__name__)


class BaseOptimizer:
    """Shared loop: iterate `step` until num_iterations or termination.

    `loss` is a pure fn (flat_params -> scalar score); subclasses implement
    `make_step` returning a jitted update on flat vectors.
    """

    def __init__(
        self,
        conf,
        loss: Callable[[jnp.ndarray], jnp.ndarray],
        listeners: Optional[Sequence[IterationListener]] = None,
        terminations: Optional[Sequence[TerminationCondition]] = None,
        model=None,
        rng_key: Optional[jax.Array] = None,
    ):
        self.conf = conf
        self.listeners: List[IterationListener] = list(listeners or [])
        self.terminations = list(
            terminations
            if terminations is not None
            else [EpsTermination(), ZeroDirection()]
        )
        self.model = model
        self.rng_key = rng_key
        self._step = None  # jitted step, compiled once per optimizer
        # Stochastic losses (CD Gibbs chains, denoising corruption, dropout)
        # take (x, key, *data) and get a FRESH key each iteration (fold_in
        # of the iteration index); deterministic losses take (x, *data) and
        # the key arg is ignored. The key AND the data batch are traced
        # arguments, so varying them never retriggers compilation — one
        # optimizer instance serves every mini-batch of a phase
        # (reference BaseOptimizer is likewise reused by its Solver).
        if rng_key is not None:
            self.loss = loss
        else:
            self.loss = lambda x, key, *data: loss(x, *data)

    # subclasses: raw traceable (x, state, key, *data) ->
    # (x, state, score, grad_norm); make_step/make_loop wrap it
    def _step_fn(self):
        raise NotImplementedError

    #: argnums make_step donates (IGD donates params+state buffers)
    _donate: tuple = ()

    def make_step(self):
        return jax.jit(self._step_fn(), donate_argnums=self._donate)

    def init_state(self, x):
        return ()

    # ---------------------------------------------- device-side fast loop
    #: optimizers whose _step_fn is a pure traced function (all five
    #: solvers here) run their WHOLE iteration loop as one compiled
    #: lax.while_loop when (a) no per-iteration listeners are attached
    #: and (b) every termination condition is one of the jittable
    #: reference trio. On the tunneled chip the eager loop costs a host
    #: round trip PER ITERATION (the float(score) sync), which dominates
    #: multi-iteration pretraining.
    _JITTABLE_TERMS = (EpsTermination, ZeroDirection, Norm2Termination)

    def _device_loop_eligible(self) -> bool:
        return (not self.listeners
                and all(isinstance(t, self._JITTABLE_TERMS)
                        for t in self.terminations))

    def _terminate_traced(self, new_score, old_score, gnorm):
        """The reference termination trio as traced predicates — same
        math as terminations.py, on device."""
        conds = []
        for t in self.terminations:
            if isinstance(t, EpsTermination):
                finite = jnp.isfinite(new_score) & jnp.isfinite(old_score)
                denom = (jnp.abs(old_score) + jnp.abs(new_score)
                         + t.tolerance)
                conds.append(finite & (
                    2.0 * jnp.abs(new_score - old_score) / denom < t.eps))
            elif isinstance(t, ZeroDirection):
                conds.append(gnorm == 0.0)
            elif isinstance(t, Norm2Termination):
                conds.append(gnorm < t.gradient_tolerance)
        out = jnp.asarray(False)
        for c in conds:
            out = out | c
        return out

    def make_loop(self, n_iters: int):
        """The whole optimize() loop as ONE compiled while_loop — identical
        iteration math and termination checks to the eager path (same
        per-iteration fold_in keys, same check-after-step schedule), minus
        the per-iteration host sync. Works for every solver whose step is
        a pure traced function (all five here)."""
        step = self._step_fn()
        terminate = self._terminate_traced

        @partial(jax.jit, donate_argnums=(0,))
        def run(x, base_key, *data):
            inf = jnp.float32(jnp.inf)

            def cond(carry):
                i, x, state, score, old, gnorm = carry
                # the eager loop checks terminations AFTER each step;
                # checking before the NEXT step is the same schedule —
                # guard i == 0 so the init sentinels never terminate
                return (i < n_iters) & ((i == 0)
                                        | ~terminate(score, old, gnorm))

            def body(carry):
                i, x, state, score, old, gnorm = carry
                new_x, new_state, new_score, new_gnorm = step(
                    x, state, jax.random.fold_in(base_key, i), *data)
                return (i + 1, new_x, new_state,
                        new_score.astype(jnp.float32), score,
                        new_gnorm.astype(jnp.float32))

            init = (jnp.int32(0), x, self.init_state(x), inf, inf,
                    jnp.float32(0.0))
            _, x, _, score, _, _ = jax.lax.while_loop(cond, body, init)
            return x, score

        return run

    def _has_device_loop(self) -> bool:
        # old-style subclasses that override make_step without providing
        # a raw _step_fn can't build the traced loop — fall back to eager
        return type(self)._step_fn is not BaseOptimizer._step_fn

    def optimize(self, params, *data, rng_key=None, sync: bool = True):
        """Run the loop; params is a pytree; returns (params, final_score).
        `data` arrays are forwarded to the loss as traced arguments;
        `rng_key` overrides the construction-time key (fresh stochasticity
        per mini-batch without recompiling).

        `sync` controls the return type of `final_score` when the device
        loop is taken (no listeners + jittable terminations +
        num_iterations > 1): the default True syncs it to a Python float,
        so the return type never varies by path; sync=False returns the
        live float32 DEVICE scalar and skips the host round-trip — that
        per-optimize sync is the whole cost of layer-wise pretraining
        through a tunneled chip, so hot internal callers pass
        sync=False and float() only when they actually read the score."""
        x, unravel = ravel_pytree(params)
        # the jitted step/loop DONATE the params buffer; for single-leaf
        # pytrees ravel_pytree returns the caller's array itself, so
        # donate would delete it out from under the caller — hand the
        # optimizer its own copy (one device op per optimize() call)
        x = jnp.array(x, copy=True)
        if rng_key is None:
            rng_key = self.rng_key
        base_key = (rng_key if rng_key is not None
                    else jax.random.PRNGKey(0))
        if (self._has_device_loop() and self._device_loop_eligible()
                and self.conf.num_iterations > 1):
            # cache keyed on what optimize() itself reads per call
            # (iteration count + termination config): mutating those
            # between calls must recompile, not reuse the stale loop.
            # Hyperparameters (lr, momentum, history, ...) are baked at
            # first compile on BOTH paths — the cached eager self._step
            # closes over them the same way — so they are not keyed.
            loop_key = (self.conf.num_iterations,
                        tuple((type(t).__name__,
                               tuple(sorted(vars(t).items())))
                              for t in self.terminations))
            if getattr(self, "_loop_key", None) != loop_key:
                self._loop = self.make_loop(self.conf.num_iterations)
                self._loop_key = loop_key
            x, score = self._loop(x, base_key, *data)
            for listener in self.listeners:  # empty by eligibility, but
                done = getattr(listener, "optimization_done", None)
                if done is not None:  # keep the contract future-proof
                    done(self.model)
            return unravel(x), (float(score) if sync else score)
        if self._step is None:
            self._step = self.make_step()
        step = self._step
        state = self.init_state(x)
        old_score = float("inf")
        score = None
        for i in range(self.conf.num_iterations):
            x, state, score_arr, gnorm_arr = step(
                x, state, jax.random.fold_in(base_key, i), *data)
            score, gnorm = float(score_arr), float(gnorm_arr)
            for listener in self.listeners:
                listener.iteration_done(self.model, i, score)
            if any(t.terminate(score, old_score, gnorm) for t in self.terminations):
                log.debug("Terminated at iteration %d (score=%s)", i, score)
                break
            old_score = score
        for listener in self.listeners:
            # end-of-optimization hook (beyond-parity: lets stateful
            # listeners like ProfilerListener finalize deterministically
            # even when a termination condition cuts the loop short)
            done = getattr(listener, "optimization_done", None)
            if done is not None:
                done(self.model)
        return unravel(x), score


class IterationGradientDescent(BaseOptimizer):
    """Plain SGD with GradientAdjustment semantics (reference
    IterationGradientDescent + GradientAdjustment.java:66-113)."""

    # donate x/state: outputs alias their HBM instead of reallocating
    # per iteration (same win as MultiLayerNetwork._get_train_step);
    # optimize() rebinds both from the outputs every iteration
    _donate = (0, 1)

    def init_state(self, x):
        updater = GradientUpdater(self.conf)
        return updater.init(x)

    def _step_fn(self):
        updater = GradientUpdater(self.conf)
        sign = 1.0 if self.conf.minimize else -1.0

        def step(x, state, key, *data):
            score, g = jax.value_and_grad(self.loss)(x, key, *data)
            # data[0] (when present) is the mini-batch: its leading dim is
            # the reference's ÷batchSize denominator (adagrad branch)
            bs = data[0].shape[0] if data and hasattr(data[0], "shape") \
                and getattr(data[0], "ndim", 0) >= 1 else 1
            updates, state = updater.update(g, state, x, bs)
            return x - sign * updates, state, score, jnp.linalg.norm(g)

        return step


class GradientAscent(BaseOptimizer):
    """Line-search steepest descent (reference GradientAscent solver: the
    GRADIENT_DESCENT algorithm — normalized gradient direction + backtracking
    line search)."""

    def _step_fn(self):
        max_iters = self.conf.num_line_search_iterations

        def step(x, state, key, *data):
            score, g = jax.value_and_grad(self.loss)(x, key, *data)
            gnorm = jnp.linalg.norm(g)
            d = -g / (gnorm + 1e-12)
            res = backtrack_line_search(
                lambda xx: self.loss(xx, key, *data),
                x, score, g, d,
                initial_step=self.conf.lr,
                max_iterations=max_iters)
            return x + res.step * d, state, res.score, gnorm

        return step


class ConjugateGradient(BaseOptimizer):
    """Nonlinear CG, Polak-Ribiere+ (reference ConjugateGradient solver)."""

    def init_state(self, x):
        return (jnp.zeros_like(x), jnp.zeros_like(x), jnp.asarray(True))

    def _step_fn(self):
        max_iters = self.conf.num_line_search_iterations

        def step(x, state, key, *data):
            g_prev, d_prev, first = state
            score, g = jax.value_and_grad(self.loss)(x, key, *data)
            gnorm = jnp.linalg.norm(g)
            denom = jnp.vdot(g_prev, g_prev)
            beta = jnp.where(
                jnp.logical_or(first, denom < 1e-20),
                0.0,
                jnp.maximum(0.0, jnp.vdot(g, g - g_prev) / denom),
            )
            d = -g + beta * d_prev
            # Restart with steepest descent when d is not a descent direction
            descent = jnp.vdot(g, d) < 0
            d = jnp.where(descent, d, -g)
            res = backtrack_line_search(lambda xx: self.loss(xx, key, *data),
                                        x, score, g,
                                        d / (jnp.linalg.norm(d) + 1e-12),
                                        initial_step=1.0,
                                        max_iterations=max_iters)
            dn = d / (jnp.linalg.norm(d) + 1e-12)
            return (x + res.step * dn, (g, d, jnp.asarray(False)),
                    res.score, gnorm)

        return step


class LBFGS(BaseOptimizer):
    """Limited-memory BFGS with two-loop recursion (reference LBFGS solver).

    History is a fixed-size ring buffer of (s, y) pairs held in device arrays
    so the whole step jits (no Python-list history, unlike the reference's
    LinkedList-based implementation).
    """

    def __init__(self, *args, history: int = 10, **kwargs):
        super().__init__(*args, **kwargs)
        self.history = history

    def init_state(self, x):
        m, n = self.history, x.shape[0]
        return (
            jnp.zeros((m, n), x.dtype),  # S
            jnp.zeros((m, n), x.dtype),  # Y
            jnp.zeros((m,), x.dtype),  # rho
            jnp.asarray(0, jnp.int32),  # count
            x,  # x_prev
            jnp.zeros_like(x),  # g_prev
        )

    def _step_fn(self):
        m = self.history
        max_ls = self.conf.num_line_search_iterations

        def step(x, state, key, *data):
            S, Y, rho, count, x_prev, g_prev = state
            score, g = jax.value_and_grad(self.loss)(x, key, *data)
            gnorm = jnp.linalg.norm(g)

            # Update history with (s, y) from the last accepted step
            s = x - x_prev
            y = g - g_prev
            sy = jnp.vdot(s, y)
            valid = jnp.logical_and(count > 0, sy > 1e-10)

            def push(args):
                S, Y, rho = args
                S = jnp.roll(S, -1, axis=0).at[-1].set(s)
                Y = jnp.roll(Y, -1, axis=0).at[-1].set(y)
                rho = jnp.roll(rho, -1).at[-1].set(1.0 / sy)
                return S, Y, rho

            S, Y, rho = jax.lax.cond(valid, push, lambda a: a, (S, Y, rho))
            hist_len = jnp.minimum(count, m)

            # Two-loop recursion (newest entry is row m-1)
            def bwd(i, carry):
                q, alphas = carry
                idx = m - 1 - i
                use = i < hist_len
                a = jnp.where(use, rho[idx] * jnp.vdot(S[idx], q), 0.0)
                q = q - a * Y[idx]
                return q, alphas.at[idx].set(a)

            q, alphas = jax.lax.fori_loop(0, m, bwd, (g, jnp.zeros((m,), x.dtype)))
            gamma = jnp.where(valid, sy / (jnp.vdot(y, y) + 1e-12), 1.0)
            r = gamma * q

            def fwd(i, r):
                use = i < hist_len
                idx = m - jnp.minimum(hist_len, m) + i  # oldest valid -> newest
                b = jnp.where(use, rho[idx] * jnp.vdot(Y[idx], r), 0.0)
                return r + jnp.where(use, (alphas[idx] - b), 0.0) * S[idx]

            r = jax.lax.fori_loop(0, m, fwd, r)
            d = -r
            descent = jnp.vdot(g, d) < 0
            d = jnp.where(descent, d, -g)
            res = backtrack_line_search(lambda xx: self.loss(xx, key, *data),
                                        x, score, g, d,
                                        initial_step=1.0,
                                        max_iterations=max_ls)
            new_x = x + res.step * d
            new_count = jnp.where(valid, count + 1, count + 1)
            return new_x, (S, Y, rho, new_count, x, g), res.score, gnorm

        return step


class StochasticHessianFree(BaseOptimizer):
    """Hessian-free (truncated-Newton) optimization.

    Parity: reference StochasticHessianFree.java:87-184 — CG-minimize the local
    quadratic model with a curvature-vector product and Levenberg-Marquardt
    damping adjustment. The reference hand-codes an R-op Gauss-Newton product
    through MultiLayerNetwork (feedForwardR :1438 / backPropGradientR :1475);
    here the curvature product is a jvp-of-grad Hessian-vector product (or a
    caller-supplied Gauss-Newton product) — jax.jvp over jax.grad composes to
    the same mathematical object without hand-derivation.
    """

    def __init__(self, *args, matvec: Optional[Callable] = None,
                 cg_iterations: int = 30, initial_lambda: float = 1.0,
                 **kwargs):
        super().__init__(*args, **kwargs)
        self._user_matvec = matvec
        self.cg_iterations = cg_iterations
        self.initial_lambda = initial_lambda

    def init_state(self, x):
        return jnp.asarray(self.initial_lambda, x.dtype)

    def _step_fn(self):
        loss = self.loss
        cg_iters = self.cg_iterations
        user_matvec = self._user_matvec

        def hvp(x, v, key, *data):
            if user_matvec is not None:
                return user_matvec(x, v)
            return jax.jvp(jax.grad(lambda xx: loss(xx, key, *data)),
                           (x,), (v,))[1]

        def step(x, lam, key, *data):
            score, g = jax.value_and_grad(loss)(x, key, *data)
            gnorm = jnp.linalg.norm(g)

            def Av(v):
                return hvp(x, v, key, *data) + lam * v

            # Plain CG on A delta = -g (reference conjGradient :87)
            b = -g

            def cg_body(i, state):
                d, r, p = state
                Ap = Av(p)
                pAp = jnp.vdot(p, Ap)
                alpha = jnp.where(pAp > 1e-20, jnp.vdot(r, r) / pAp, 0.0)
                d_new = d + alpha * p
                r_new = r - alpha * Ap
                beta = jnp.where(jnp.vdot(r, r) > 1e-20,
                                 jnp.vdot(r_new, r_new) / jnp.vdot(r, r), 0.0)
                return (d_new, r_new, r_new + beta * p)

            zeros = jnp.zeros_like(x)
            delta, _, _ = jax.lax.fori_loop(0, cg_iters, cg_body,
                                            (zeros, b, b))

            # Backtrack over the CG solution (reference cgBackTrack :184)
            new_score = loss(x + delta, key, *data)

            def shrink_cond(s):
                scale, ns, it = s
                return jnp.logical_and(ns > score, it < 10)

            def shrink_body(s):
                scale, _, it = s
                scale = scale * 0.5
                return (scale, loss(x + scale * delta, key, *data), it + 1)

            scale, new_score, _ = jax.lax.while_loop(
                shrink_cond, shrink_body,
                (jnp.asarray(1.0, x.dtype), new_score, jnp.asarray(0)))

            # Levenberg-Marquardt damping update via reduction ratio
            pred = -(jnp.vdot(g, scale * delta)
                     + 0.5 * jnp.vdot(scale * delta, Av(scale * delta)))
            rho = jnp.where(pred > 1e-20, (score - new_score) / pred, 0.0)
            lam = jnp.where(rho > 0.75, lam * 2.0 / 3.0,
                            jnp.where(rho < 0.25, lam * 1.5, lam))
            improved = new_score < score
            x_new = jnp.where(improved, x + scale * delta, x)
            return x_new, lam, jnp.where(improved, new_score, score), gnorm

        return step
