"""Training guardian: in-step fault defense, rollback, autosave, preemption.

The scaleout runtime already survives master crashes and worker kills
(scaleout/runtime.py + tests/test_resume_drill.py); this module gives the
HOT path — the jitted train steps in `MultiLayerNetwork` and the
DP/ZeRO-1/TP trainers — the same degrade-gracefully contract, in three
tiers:

1. **On-device guarded commit** (`all_finite` + `commit` + `advance`):
   the jitted step reduces an all-leaves-finite predicate over the
   gradients and the loss ON DEVICE and applies the update through
   `jnp.where(ok, new, old)` — a non-finite step is skipped (params,
   updater state and the updater's iteration counter all keep their old
   buffers) and a device-side skip counter increments. No host sync is
   involved: the predicate is a handful of elementwise+reduce ops fused
   into the existing program (<2% step overhead, bench.py `guardian`).
   Under the GSPMD trainers every replica runs the same global program
   over the same all-reduced gradients, so the commit/skip decision is
   replica-consistent by construction — the weight-update-sharding
   property of Xu et al., arXiv:2004.13336 (PAPERS.md), where a step
   must commit everywhere or nowhere. For explicit-collective contexts
   (`shard_map`/`pmap`) `all_finite(axis_name=...)` psums the
   not-finite indicator across the axis so all replicas agree.

2. **Host-side escalation ladder** (`GuardianPolicy` / `GuardianSession`):
   a rolling last-good (params, updater-state) snapshot is kept ON
   DEVICE (async `jnp.copy`, no host round trip) every `snapshot_every`
   steps; every `check_every` steps the session syncs two scalars (skip
   counter, score) and walks the ladder:

       skip step  ->  rollback to last-good + LR backoff  ->  abort

   Persistent skips (>= `max_skips_per_window` within one check window)
   or a score blow-up (`DivergenceCondition`, optimize/terminations.py)
   restore the snapshot and multiply the guarded step's traced
   `lr_scale` by `lr_backoff` (no recompile — the scale is a traced
   scalar). After `max_rollbacks` rollbacks the session raises
   `GuardianAbort` carrying a diagnostic report and the last-good state.

3. **Autosave + preemption flush** (`TrainingGuard`): `checkpoint_every=`
   on `fit`/`fit_scan`/the trainers saves a full resumable checkpoint
   (params, updater state, iterator cursor) through the rotating
   `DefaultModelSaver` — or, pass a
   `checkpoint.ShardedModelSaver` and the autosave goes through the
   ASYNC sharded writer: the step loop pays only the device→host
   snapshot while serialize+IO overlap training, and the guard flushes
   pending writes on exit (docs/CHECKPOINTS.md). A SIGTERM handler
   (TPU-VM preemption notice) defers to the next step boundary, flushes
   a final checkpoint (synchronously — the process is dying) and
   raises `TrainingPreempted` with the checkpoint path.

Guardian events (skips, rollbacks, saves, aborts) surface through any
listener with a `guardian_event(model, event)` hook — see
`optimize.listeners.GuardianListener` / `CollectGuardianEvents`.
Semantics and overhead numbers: docs/FAULT_TOLERANCE.md.
"""

from __future__ import annotations

import logging
import signal as _signal
import threading
from collections import deque
from typing import Any, Callable, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.optimize.terminations import DivergenceCondition

# NOTE: scaleout.checkpoint is imported lazily (TrainingGuard.__init__) —
# scaleout's package init reaches back through nn/optimize, so a module-
# level import here would be circular.

log = logging.getLogger(__name__)

# every guardian event flows through TrainingGuard._emit, so one counter
# covers skips/rollbacks/aborts/autosaves/preemptions; the known kinds
# are pre-seeded at 0 so a scrape sees the series before the first fault
_M_EVENTS = telemetry.counter(
    "dl4j_guardian_events", "guardian escalation/autosave events by kind")
for _kind in ("skip", "rollback", "abort", "autosave", "preempt"):
    _M_EVENTS.labels(kind=_kind)

__all__ = [
    "GuardianState", "guardian_state", "all_finite", "commit", "advance",
    "apply_lr_scale", "GuardianEvent", "GuardianAbort", "TrainingPreempted",
    "GuardianPolicy", "GuardianSession", "TrainingGuard", "make_guard",
]


# ===================================================================== device
class GuardianState(NamedTuple):
    """Traced per-run guardian carry: lives on device, rides through the
    jitted step like updater state. `skipped` counts non-committed steps;
    `lr_scale` rescales committed updates (rollback backoff) without
    recompiling."""

    skipped: jnp.ndarray  # scalar int32
    lr_scale: jnp.ndarray  # scalar float32


def guardian_state(lr_scale: float = 1.0) -> GuardianState:
    return GuardianState(skipped=jnp.zeros((), jnp.int32),
                         lr_scale=jnp.asarray(lr_scale, jnp.float32))


def all_finite(score, *trees, axis_name: Optional[str] = None):
    """All-leaves-finite predicate, reduced on device: True iff `score`
    and every array leaf of `trees` contain only finite values.

    Inside the GSPMD trainers the gradients are already globally
    all-reduced, so the scalar is identical on every replica and the
    commit/skip decision needs no further agreement. Inside explicit
    per-replica code (shard_map/pmap bodies) pass `axis_name`: the
    not-finite indicator is psum'd over the axis, so one replica's NaN
    vetoes the commit everywhere — all replicas commit or skip together.
    """
    ok = jnp.all(jnp.isfinite(score)) if score is not None \
        else jnp.asarray(True)
    for tree in trees:
        for leaf in jax.tree_util.tree_leaves(tree):
            ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(leaf)))
    if axis_name is not None:
        bad = jax.lax.psum(jnp.logical_not(ok).astype(jnp.int32), axis_name)
        ok = bad == 0
    return ok


def commit(ok, old, new):
    """Per-leaf guarded select: `new` where the step is clean, `old`
    (the un-updated buffers) where it must be skipped. Works on any
    pytree pair with matching structure (params, updater state, flat
    optimizer vectors)."""
    return jax.tree_util.tree_map(lambda o, n: jnp.where(ok, n, o), old, new)


def advance(gstate: GuardianState, ok) -> GuardianState:
    """Advance the skip counter: +1 when the step was NOT committed."""
    return GuardianState(
        skipped=gstate.skipped + jnp.logical_not(ok).astype(jnp.int32),
        lr_scale=gstate.lr_scale)


def apply_lr_scale(updates, gstate: GuardianState):
    """Rescale the final updates by the guardian's backoff factor, in
    each leaf's dtype (bf16 nets must not silently promote to f32)."""
    return jax.tree_util.tree_map(
        lambda u: u * gstate.lr_scale.astype(u.dtype), updates)


def guarded_update(params, upd_state, updates, new_state,
                   gstate: GuardianState, score, grads,
                   axis_name: Optional[str] = None):
    """The whole guarded commit in one place — every pytree-shaped step
    body (network step, scan body, DP/TP trainer step) calls this so the
    predicate/commit/backoff semantics cannot drift between them.
    Returns (params, upd_state, gstate): the lr-scaled update applied
    where the step is clean, the untouched old buffers where it must be
    skipped, and the skip counter advanced. (The ZeRO-1 trainer carries
    FLAT vectors + its own iteration scalar and implements the same
    sequence on them.)"""
    ok = all_finite(score, grads, axis_name=axis_name)
    new_params = jax.tree_util.tree_map(
        lambda p, u: p - u, params, apply_lr_scale(updates, gstate))
    params = commit(ok, params, new_params)
    upd_state = commit(ok, upd_state, new_state)
    return params, upd_state, advance(gstate, ok)


def _device_copy(tree):
    """Async device-side copy of a pytree — snapshot/rollback primitive.
    Fresh buffers, so the originals may be donated to later steps."""
    return jax.tree_util.tree_map(jnp.copy, tree)


# ====================================================================== events
class GuardianEvent(NamedTuple):
    """kind: skip | rollback | abort | autosave | preempt. `step` is the
    guardian's step count at emit time; `info` carries kind-specific
    detail (counts, scores, checkpoint path)."""

    kind: str
    step: int
    info: dict


class GuardianAbort(RuntimeError):
    """The escalation ladder ran out of rollbacks. `report` is the
    diagnostic dict (steps, skips, rollbacks, scores, lr scale);
    `last_good` is the last-good (device) state tuple the network was
    restored to before raising."""

    def __init__(self, report: dict, last_good=None):
        super().__init__(f"guardian abort after {report.get('rollbacks')} "
                         f"rollbacks: {report}")
        self.report = report
        self.last_good = last_good


class TrainingPreempted(RuntimeError):
    """SIGTERM (or an explicit `request_preemption`) arrived mid-fit; a
    final checkpoint was flushed to `path` at batch `position` before
    raising."""

    def __init__(self, path: Optional[str], position: int):
        super().__init__(
            f"training preempted at batch {position}; "
            f"checkpoint flushed to {path!r}")
        self.path = path
        self.position = position


# ====================================================================== policy
class GuardianPolicy:
    """Host-side guardian configuration (one policy may serve many runs;
    per-run state lives in the `GuardianSession` a `TrainingGuard`
    builds from it).

    Parameters
    ----------
    check_every : sync the skip counter + score every N guarded train
        steps, i.e. batches — a guarded fit_scan observes once per epoch
        but advances the counter by that epoch's batch count (two scalar
        D2H reads — the ONLY host syncs the guardian adds).
    snapshot_every : refresh the on-device last-good snapshot every N
        steps (only at healthy check boundaries).
    max_skips_per_window : skipped steps within one check window that
        escalate from skip to rollback.
    lr_backoff : multiply the guarded step's lr_scale by this on every
        rollback.
    max_rollbacks : rollbacks after which the session raises
        `GuardianAbort`.
    divergence : a `TerminationCondition` judging (new_score,
        best_recent_score); default `DivergenceCondition()`. Checked only
        in windows with zero skips (a skipped step's score is untrusted).
    divergence_window : rolling score window the best-recent is drawn
        from.
    listeners : objects with `guardian_event(model, event)`; the owning
        network's listeners with that hook are notified too.
    """

    def __init__(self, check_every: int = 10, snapshot_every: int = 50,
                 max_skips_per_window: int = 3, lr_backoff: float = 0.5,
                 max_rollbacks: int = 3, divergence=None,
                 divergence_window: int = 20,
                 listeners: Sequence = ()):
        if check_every < 1:
            raise ValueError(f"check_every must be >= 1, got {check_every}")
        if snapshot_every < 1:
            raise ValueError(
                f"snapshot_every must be >= 1, got {snapshot_every}")
        if not 0.0 < lr_backoff <= 1.0:
            raise ValueError(f"lr_backoff must be in (0, 1], got {lr_backoff}")
        if max_skips_per_window < 1:
            # 0 would make every healthy window (delta == 0) roll back
            raise ValueError(
                f"max_skips_per_window must be >= 1, got "
                f"{max_skips_per_window}")
        if max_rollbacks < 0:
            raise ValueError(
                f"max_rollbacks must be >= 0, got {max_rollbacks}")
        self.check_every = check_every
        self.snapshot_every = snapshot_every
        self.max_skips_per_window = max_skips_per_window
        self.lr_backoff = lr_backoff
        self.max_rollbacks = max_rollbacks
        self.divergence = divergence if divergence is not None \
            else DivergenceCondition()
        self.divergence_window = divergence_window
        self.listeners = list(listeners)

    def session(self, emit: Callable[[str, int, dict], None]
                ) -> "GuardianSession":
        return GuardianSession(self, emit)


class GuardianSession:
    """Per-run escalation-ladder state: device gstate, last-good
    snapshot, rolling score window, rollback budget."""

    def __init__(self, policy: GuardianPolicy,
                 emit: Callable[[str, int, dict], None]):
        self.policy = policy
        self._emit = emit
        self.gstate = guardian_state()
        self._snapshot = None
        self._snap_step = 0
        self._step = 0
        self._last_check = 0
        self._skipped_prev = 0
        self._scores: deque = deque(maxlen=policy.divergence_window)
        self._last_score: Optional[float] = None
        self.rollbacks = 0

    @property
    def armed(self) -> bool:
        return self._snapshot is not None

    def arm(self, live) -> None:
        """Capture the current state tuple as the last-good snapshot.
        Fit loops call this once, BEFORE the first guarded step."""
        self._snapshot = _device_copy(live)
        self._snap_step = self._step

    def observe(self, live, gstate: GuardianState, score, steps: int = 1
                ) -> Tuple[Any, bool]:
        """Called after every guarded step with the live state tuple
        (any tuple of device pytrees — (params, upd_state) for the
        network, (params, hist, vel, it) for ZeRO-1). Returns
        (live, rolled_back); `live` is replaced by a copy of the
        last-good snapshot on rollback. Host-syncs two scalars at
        `check_every` boundaries only; raises `GuardianAbort` when the
        rollback budget is exhausted.

        `steps` is how many guarded train steps this observation covers
        — 1 for the per-batch fit loops, n_batches for a guarded
        fit_scan epoch — so the policy's cadences stay denominated in
        BATCHES regardless of how coarsely the host observes."""
        self._step += steps
        self.gstate = gstate
        p = self.policy
        if self._step - self._last_check < p.check_every:
            return live, False
        window = self._step - self._last_check
        self._last_check = self._step
        # the skip threshold is configured per check_every batches; a
        # coarse observer (fit_scan: one observe per epoch) covers a
        # wider window, so scale the threshold to keep the tolerated
        # fault RATE identical across observation granularities
        max_skips = p.max_skips_per_window * max(
            1, round(window / p.check_every))
        skipped = int(gstate.skipped)  # the two guardian host syncs
        s = float(score)
        delta = skipped - self._skipped_prev
        self._skipped_prev = skipped
        diverged = False
        if delta == 0:
            # a clean window: the score is trustworthy
            self._last_score = s
            best = min(self._scores) if self._scores else None
            if best is not None:
                diverged = p.divergence.terminate(s, best, 0.0)
            if not diverged:
                self._scores.append(s)
        if delta >= max_skips or diverged:
            reason = ("divergence" if diverged
                      else f"{delta} skips in one window")
            return self._rollback(reason, {"score": s, "skipped": skipped})
        if delta:
            self._emit("skip", self._step,
                       {"skipped_in_window": delta, "total_skipped": skipped})
        elif self._step - self._snap_step >= p.snapshot_every:
            # refresh only at HEALTHY boundaries (zero skips): a window
            # with sub-threshold skips may already sit inside the faulty
            # region, and rollback must land BEFORE the trouble started
            self._snapshot = _device_copy(live)
            self._snap_step = self._step
        return live, False

    def _rollback(self, reason: str, detail: dict) -> Tuple[Any, bool]:
        self.rollbacks += 1
        if self.rollbacks > self.policy.max_rollbacks:
            report = self.stats()
            report["reason"] = reason
            last_good = _device_copy(self._snapshot)
            self._emit("abort", self._step, report)
            raise GuardianAbort(report, last_good=last_good)
        self.gstate = GuardianState(
            skipped=self.gstate.skipped,
            lr_scale=self.gstate.lr_scale * self.policy.lr_backoff)
        self._scores.clear()
        self._emit("rollback", self._step,
                   {"reason": reason, "rollback": self.rollbacks,
                    "to_step": self._snap_step,
                    "lr_scale": float(self.gstate.lr_scale), **detail})
        return _device_copy(self._snapshot), True

    def stats(self) -> dict:
        """Diagnostic summary (used in abort reports and autosave
        metadata). Syncs the skip counter."""
        return {
            "steps": self._step,
            "skipped": int(self.gstate.skipped),
            "rollbacks": self.rollbacks,
            "lr_scale": float(self.gstate.lr_scale),
            "last_score": self._last_score,
            "best_recent_score": min(self._scores) if self._scores else None,
        }


# ================================================================ fit driver
class TrainingGuard:
    """Per-fit host driver composing the three guardian tiers for one
    training run: the guarded-session ladder, `checkpoint_every`
    autosave, and the SIGTERM/preemption flush. Built via `make_guard`;
    used as a context manager around the fit loop (installs/restores
    signal handlers)."""

    signals = (_signal.SIGTERM,)

    def __init__(self, network, policy: Optional[GuardianPolicy] = None,
                 checkpoint_every: Optional[int] = None, saver=None,
                 save_fn: Optional[Callable] = None,
                 start_position: int = 0, start_epoch: int = 0,
                 start_epoch_batch: int = 0):
        if checkpoint_every is not None and checkpoint_every < 1:
            raise ValueError(
                f"checkpoint_every must be >= 1, got {checkpoint_every}")
        from deeplearning4j_tpu.scaleout import checkpoint as _ckpt
        _ckpt.register_namedtuple(GuardianState)
        self.network = network
        self.policy = policy
        self.checkpoint_every = checkpoint_every
        if saver is None and checkpoint_every:
            saver = _ckpt.DefaultModelSaver()  # reference default path
        self.saver = saver
        self._save_fn = save_fn
        self.session = policy.session(self._emit) if policy else None
        #: TOTAL batches consumed — the checkpoint cursor. A resumed fit
        #: seeds it (and the epoch) from the restored checkpoint so new
        #: autosaves continue the step numbering.
        self.position = int(start_position)
        self.epoch = int(start_epoch)  # 0-based; fit loops call begin_epoch
        #: batches consumed within the current epoch. Seeded on a
        #: mid-epoch resume (the feed was fast-forwarded past
        #: `start_epoch_batch` batches) so the NEXT checkpoint's
        #: epoch_batch stays truthful — a second resume must not
        #: fast-forward short and double-train.
        self.epoch_position = int(start_epoch_batch)
        self._epochs_begun = 0
        self._preempt = threading.Event()
        self._prev_handlers: dict = {}

    # ------------------------------------------------------------ lifecycle
    def __enter__(self) -> "TrainingGuard":
        if self.saver is not None:
            try:
                for sig in self.signals:
                    self._prev_handlers[sig] = _signal.signal(
                        sig, self._on_signal)
            except ValueError:
                # not the main thread: signal delivery is the main
                # thread's job; request_preemption() still works
                self._prev_handlers.clear()
        return self

    def __exit__(self, *exc) -> None:
        for sig, prev in self._prev_handlers.items():
            _signal.signal(sig, prev)
        self._prev_handlers.clear()
        # async savers (checkpoint.ShardedModelSaver): the fit loop only
        # paid the snapshot per autosave — make every pending write
        # durable before fit() returns. On an exceptional exit, still
        # try, but never mask the in-flight exception with a flush error.
        flush = getattr(self.saver, "flush", None)
        if flush is not None:
            if exc and exc[0] is not None:
                try:
                    flush()
                except Exception:
                    log.exception(
                        "checkpoint flush failed during exceptional exit")
            else:
                flush()

    def _on_signal(self, signum, frame) -> None:
        # defer: the flush must happen at a step boundary, not inside a
        # dispatched device computation
        self._preempt.set()

    def request_preemption(self) -> None:
        """Programmatic preemption notice (tests, cluster agents,
        non-main threads where no handler could be installed)."""
        self._preempt.set()

    # -------------------------------------------------------------- session
    @property
    def guarded(self) -> bool:
        return self.session is not None

    @property
    def gstate(self) -> GuardianState:
        return self.session.gstate

    def arm_once(self, live) -> None:
        if self.session is not None and not self.session.armed:
            self.session.arm(live)

    def post_step(self, live, gstate: GuardianState, score, steps: int = 1
                  ) -> Tuple[Any, bool]:
        return self.session.observe(live, gstate, score, steps=steps)

    # ---------------------------------------------------- autosave/preempt
    def begin_epoch(self) -> None:
        """Fit loops call this at each epoch start so checkpoints carry a
        WITHIN-epoch cursor alongside the total: `iterator_position` is
        the total batches consumed (the flat-stream resume index the
        drills use), while metadata epoch/epoch_batch position a
        re-iterable source mid-epoch (`DeviceFeed.fast_forward`)."""
        if self._epochs_begun:  # NOT `if self.position`: a resumed fit
            self.epoch += 1     # starts mid-epoch with a nonzero cursor
            self.epoch_position = 0
        # first begin_epoch keeps a seeded start_epoch_batch: the
        # resumed fit's first (partial) epoch is already mid-stream
        self._epochs_begun += 1

    def tick(self) -> None:
        """Call once per consumed batch (fit_scan: per epoch), AFTER the
        network (or the save_fn's captured state) reflects the step.
        Flushes autosaves and, on a pending preemption, a final
        checkpoint before raising `TrainingPreempted`."""
        self.position += 1
        self.epoch_position += 1
        if self._preempt.is_set():
            path = self._save("preempt") if self.saver is not None else None
            raise TrainingPreempted(path, self.position)
        if (self.checkpoint_every and self.saver is not None
                and self.position % self.checkpoint_every == 0):
            self._save("autosave")

    def _save(self, kind: str) -> str:
        meta = {"guardian": self.session.stats()} if self.session else {}
        meta["epoch"] = self.epoch
        meta["epoch_batch"] = self.epoch_position
        # save_fns use this to avoid cross-process collectives on the
        # preemption path (SIGTERM delivery is skewed across hosts)
        meta["save_kind"] = kind
        if self._save_fn is not None:
            path = self._save_fn(self.saver, self.position, meta)
        else:
            path = self.saver.save(self.network,
                                   iterator_position=self.position,
                                   metadata=meta)
        self._emit(kind, self.position, {"path": path})
        return path

    # --------------------------------------------------------------- events
    def _emit(self, kind: str, step: int, info: Optional[dict] = None
              ) -> None:
        event = GuardianEvent(kind, step, dict(info or {}))
        _M_EVENTS.labels(kind=kind).inc()
        level = (logging.WARNING if kind in ("rollback", "abort", "preempt")
                 else logging.INFO)
        log.log(level, "guardian %s at step %d: %s", kind, step, event.info)
        targets = list(self.policy.listeners) if self.policy else []
        targets += [lst for lst in getattr(self.network, "listeners", [])
                    if hasattr(lst, "guardian_event") and lst not in targets]
        for t in targets:
            t.guardian_event(self.network, event)


def make_guard(network, guardian=None, checkpoint_every: Optional[int] = None,
               saver=None, save_fn: Optional[Callable] = None,
               start_position: int = 0, start_epoch: int = 0,
               start_epoch_batch: int = 0
               ) -> Optional[TrainingGuard]:
    """Build the per-fit TrainingGuard, or None when every guardian
    feature is off — callers keep the historical code path bit-for-bit.

    `guardian` is a GuardianPolicy, or True for defaults. A `saver`
    without `checkpoint_every` arms the preemption flush only.
    `start_position`/`start_epoch` seed the cursor for a resumed fit."""
    if guardian is None and not checkpoint_every and saver is None:
        return None
    policy = GuardianPolicy() if guardian is True else guardian
    return TrainingGuard(network, policy, checkpoint_every, saver, save_fn,
                         start_position=start_position,
                         start_epoch=start_epoch,
                         start_epoch_batch=start_epoch_batch)
