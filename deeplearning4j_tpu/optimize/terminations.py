"""Termination conditions.

Parity: reference core/optimize/terminations/ — `EpsTermination` (relative
score change below eps), `ZeroDirection` (zero gradient direction),
`Norm2Termination` (gradient L2 norm below tolerance), checked each iteration
in BaseOptimizer.optimize (BaseOptimizer.java:176-186).
"""

from __future__ import annotations

import math


class TerminationCondition:
    def terminate(self, new_score: float, old_score: float, grad_norm: float) -> bool:
        raise NotImplementedError


class EpsTermination(TerminationCondition):
    def __init__(self, eps: float = 1e-4, tolerance: float = 1e-8):
        self.eps = eps
        self.tolerance = tolerance

    def terminate(self, new_score, old_score, grad_norm) -> bool:
        if not (math.isfinite(new_score) and math.isfinite(old_score)):
            return False
        denom = abs(old_score) + abs(new_score) + self.tolerance
        return 2.0 * abs(new_score - old_score) / denom < self.eps


class ZeroDirection(TerminationCondition):
    def terminate(self, new_score, old_score, grad_norm) -> bool:
        return grad_norm == 0.0


class Norm2Termination(TerminationCondition):
    def __init__(self, gradient_tolerance: float = 1e-8):
        self.gradient_tolerance = gradient_tolerance

    def terminate(self, new_score, old_score, grad_norm) -> bool:
        return grad_norm < self.gradient_tolerance


class DivergenceCondition(TerminationCondition):
    """EpsTermination's inverse: fires when the score has blown UP —
    the training guardian's rollback trigger (optimize/guardian.py).

    `terminate(new_score, best_score, grad_norm)` is True when
    `new_score` is non-finite, or exceeds `best_score` (the best recent
    score the caller tracks) by more than `factor` times its magnitude
    (same |score|+tolerance normalization as EpsTermination, so a score
    hovering near zero doesn't trip on noise)."""

    def __init__(self, factor: float = 3.0, tolerance: float = 1e-8):
        if factor <= 0:
            raise ValueError(f"factor must be > 0, got {factor}")
        self.factor = factor
        self.tolerance = tolerance

    def terminate(self, new_score, old_score, grad_norm) -> bool:
        if not math.isfinite(new_score):
            return True
        if not math.isfinite(old_score):
            return False
        return (new_score - old_score) > self.factor * (abs(old_score)
                                                        + self.tolerance)
