"""Termination conditions.

Parity: reference core/optimize/terminations/ — `EpsTermination` (relative
score change below eps), `ZeroDirection` (zero gradient direction),
`Norm2Termination` (gradient L2 norm below tolerance), checked each iteration
in BaseOptimizer.optimize (BaseOptimizer.java:176-186).
"""

from __future__ import annotations

import math


class TerminationCondition:
    def terminate(self, new_score: float, old_score: float, grad_norm: float) -> bool:
        raise NotImplementedError


class EpsTermination(TerminationCondition):
    def __init__(self, eps: float = 1e-4, tolerance: float = 1e-8):
        self.eps = eps
        self.tolerance = tolerance

    def terminate(self, new_score, old_score, grad_norm) -> bool:
        if not (math.isfinite(new_score) and math.isfinite(old_score)):
            return False
        denom = abs(old_score) + abs(new_score) + self.tolerance
        return 2.0 * abs(new_score - old_score) / denom < self.eps


class ZeroDirection(TerminationCondition):
    def terminate(self, new_score, old_score, grad_norm) -> bool:
        return grad_norm == 0.0


class Norm2Termination(TerminationCondition):
    def __init__(self, gradient_tolerance: float = 1e-8):
        self.gradient_tolerance = gradient_tolerance

    def terminate(self, new_score, old_score, grad_norm) -> bool:
        return grad_norm < self.gradient_tolerance
