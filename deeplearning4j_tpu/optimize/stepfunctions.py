"""Step functions: how a search direction is applied to parameters.

Parity: reference core/optimize/stepfunctions/ ×5 (DefaultStepFunction,
NegativeDefaultStepFunction, GradientStepFunction, NegativeGradientStepFunction,
StepFunction iface). Pure pytree ops.
"""

from __future__ import annotations

import jax


def default_step(params, direction, step: float = 1.0):
    """params + step * direction (reference DefaultStepFunction)."""
    return jax.tree_util.tree_map(lambda p, d: p + step * d, params, direction)


def negative_default_step(params, direction, step: float = 1.0):
    return jax.tree_util.tree_map(lambda p, d: p - step * d, params, direction)


def gradient_step(params, direction, step: float = 1.0):
    """params + direction, ignoring step (reference GradientStepFunction)."""
    return jax.tree_util.tree_map(lambda p, d: p + d, params, direction)


def negative_gradient_step(params, direction, step: float = 1.0):
    return jax.tree_util.tree_map(lambda p, d: p - d, params, direction)


STEP_FUNCTIONS = {
    "default": default_step,
    "negative_default": negative_default_step,
    "gradient": gradient_step,
    "negative_gradient": negative_gradient_step,
}
