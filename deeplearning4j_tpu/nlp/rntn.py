"""Recursive Neural Tensor Network (sentiment-style classification over
binary parse trees).

Parity: reference nlp/models/rntn/RNTN.java:68 (1,345 LoC) —
`forwardPropagateTree` (:717: preterminal vector = f(wordvec); binary
vector = f(W·[l;r;1] + [l;r]ᵀ·T·[l;r])), `backpropDerivativesAndError`
(:577-684: class-weighted softmax cross-entropy at every labeled node,
deltas recursed down through W and the tensor), per-category-pair
parameter maps (binaryTransform/binaryINd4j/binaryClassification/
unaryClassification), AdaGrad with periodic reset (adagradResetFrequency),
and the four regularization costs (regTransformMatrix, regTransformINDArray,
regClassification, regWordVector). Builder surface mirrors RNTN.Builder.

TPU-first design (NOT a translation):
- The reference walks each tree with recursive Java + hand-derived
  gradients. Here a tree batch is lowered to padded post-order index
  arrays (nlp/tree.py `encode_trees`), the forward is ONE `lax.scan` over
  node slots (children are always earlier slots), and gradients come from
  `jax.grad` through the scan — no hand backprop.
- Per-category-pair matrices become *stacked* parameter arrays indexed by
  a category id per node (a gather on device), so the non-simplified
  model jits exactly like the simplified one (which is just n_cat == 1).
- The whole (loss, grad, AdaGrad update) is a single jitted train step;
  trees train as a batch via vmap instead of the reference's actor-based
  per-tree parallelism.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.tree import EncodedTrees, Tree, encode_trees
from deeplearning4j_tpu.ops.activations import apply_activation

log = logging.getLogger(__name__)

ADAGRAD_EPS = 1e-6
UNK = "UNK"


def _append_one(v):
    return jnp.concatenate([v, jnp.ones(v.shape[:-1] + (1,), v.dtype)], -1)


def _transform_offset(d: int):
    """[I/2, I/2, 0] near-averaging init offset for the binary transform
    (reference randomTransformMatrix): the initial composition of children
    [l; r; 1] is their average."""
    return jnp.concatenate(
        [jnp.eye(d), jnp.eye(d), jnp.zeros((d, 1))], axis=1) / 2.0


class RNTN:
    """See module docstring. Numbers default to the reference's
    (RNTN.java:70-100): 25 hidden units, 3 output classes, tanh, tensors
    on, combined classification, simplified (shared-parameter) model."""

    def __init__(self, *, num_hidden: int = 25, num_outs: int = 3,
                 use_tensors: bool = True, combine_classification: bool = True,
                 simplified_model: bool = True,
                 activation_function: str = "tanh",
                 lr: float = 0.01,
                 scaling_for_init: float = 1.0,
                 adagrad_reset_frequency: int = 1,
                 reg_transform_matrix: float = 0.001,
                 reg_transform_tensor: float = 0.001,
                 reg_classification: float = 0.0001,
                 reg_word_vector: float = 0.0001,
                 class_weights: Optional[Dict[int, float]] = None,
                 feature_vectors: Optional[Dict[str, np.ndarray]] = None,
                 lower_case_feature_names: bool = False,
                 seed: int = 123):
        self.num_hidden = num_hidden
        self.num_outs = num_outs
        self.use_tensors = use_tensors
        self.combine_classification = combine_classification
        self.simplified_model = simplified_model
        self.activation_function = activation_function
        self.lr = lr
        self.scaling_for_init = scaling_for_init
        self.adagrad_reset_frequency = adagrad_reset_frequency
        self.reg_transform_matrix = reg_transform_matrix
        self.reg_transform_tensor = reg_transform_tensor
        self.reg_classification = reg_classification
        self.reg_word_vector = reg_word_vector
        self.class_weights = dict(class_weights or {})
        self.lower_case_feature_names = lower_case_feature_names
        self._feature_vectors_init = feature_vectors
        self.key = jax.random.PRNGKey(seed)

        self.word_index: Dict[str, int] = {}
        self.cat_index: Optional[Dict[tuple, int]] = None
        self.ccat_index: Optional[Dict[str, int]] = None
        self._params = None
        self._adagrad_hist = None
        self._step = None
        self.value = 0.0  # last training loss (reference `value`)

    # ------------------------------------------------------------- builder
    class Builder:
        """Fluent builder mirroring reference RNTN.Builder."""

        def __init__(self):
            self._kw = {}

        def __getattr__(self, name):
            def setter(value):
                self._kw[name] = value
                return self

            return setter

        def build(self) -> "RNTN":
            return RNTN(**self._kw)

    @classmethod
    def builder(cls) -> "RNTN.Builder":
        return cls.Builder()

    # ------------------------------------------------------------ vocab/init
    def _norm_word(self, w: str) -> str:
        return w.lower() if self.lower_case_feature_names else w

    def _build_vocab(self, trees: List[Tree]) -> None:
        if not self.word_index:
            self.word_index = {UNK: 0}
        for t in trees:
            for tok in t.tokens():
                tok = self._norm_word(tok)
                if tok not in self.word_index:
                    self.word_index[tok] = len(self.word_index)

    def _build_categories(self, trees: List[Tree]) -> None:
        """Non-simplified model: assign parameter indices per category pair
        (reference binaryTransform keyed by (leftCategory, rightCategory))."""
        if self.simplified_model:
            return
        self.cat_index = self.cat_index or {}
        self.ccat_index = self.ccat_index or {}

        def visit(node: Tree):
            if node.is_leaf():
                return
            self.ccat_index.setdefault(node.label, len(self.ccat_index))
            if not node.is_preterminal():
                pair = (node.first_child().label, node.last_child().label)
                self.cat_index.setdefault(pair, len(self.cat_index))
            for c in node.children:
                visit(c)

        for t in trees:
            visit(t)

    def _grow_params(self) -> None:
        """Resize parameter stacks after vocab/categories grew on a later
        fit() call (the reference mutates its maps in place; here the
        stacked arrays must grow or gathers silently clamp)."""
        p = self._params
        d = self.num_hidden

        def grow(name, n_new, init_scale, offset=None):
            arr = p[name]
            n_old = arr.shape[0]
            if n_new <= n_old:
                return
            self.key, sub = jax.random.split(self.key)
            extra = jax.random.normal(
                sub, (n_new - n_old,) + arr.shape[1:]) * init_scale
            if offset is not None:
                extra = extra + offset[None]
            p[name] = jnp.concatenate([arr, extra], axis=0)
            if self._adagrad_hist is not None:
                self._adagrad_hist[name] = jnp.concatenate(
                    [self._adagrad_hist[name], jnp.zeros_like(extra)], axis=0)

        n_emb_old = p["E"].shape[0]
        grow("E", len(self.word_index), self.scaling_for_init / d)
        if self._feature_vectors_init and p["E"].shape[0] > n_emb_old:
            # words first seen on a later fit() still get their pretrained
            # vectors when the lookup table has them, like _init_params
            emb = np.array(p["E"])  # np.asarray of a jax.Array is read-only
            for word, idx in self.word_index.items():
                if idx >= n_emb_old:
                    vec = self._feature_vectors_init.get(word)
                    if vec is not None:
                        emb[idx] = np.asarray(vec, np.float32)[:d]
            p["E"] = jnp.asarray(emb)
        n_cat = len(self.cat_index) if self.cat_index else 1
        n_ccat = len(self.ccat_index) if self.ccat_index else 1
        # categories first seen on a later fit() get the same [I/2, I/2, 0]
        # near-averaging offset as _init_params (randomTransformMatrix)
        grow("W", n_cat, self.scaling_for_init / (2 * d),
             offset=_transform_offset(d))
        grow("Wu", n_ccat, self.scaling_for_init / d)
        if "T" in p:
            grow("T", n_cat, self.scaling_for_init / (4 * d * d))
        if "Wb" in p:
            grow("Wb", n_cat, self.scaling_for_init / d)

    def _init_params(self) -> None:
        if self._params is not None:
            self._grow_params()
            return
        d, c = self.num_hidden, self.num_outs
        n_cat = len(self.cat_index) if self.cat_index else 1
        n_ccat = len(self.ccat_index) if self.ccat_index else 1
        v = len(self.word_index)
        keys = jax.random.split(self.key, 6)
        self.key = keys[0]
        scale = self.scaling_for_init
        # reference init: randn scaled by scalingForInit; identity added to
        # the transform's square blocks so the initial composition is
        # near-averaging (RNTN randomTransformMatrix)
        w = jax.random.normal(keys[1], (n_cat, d, 2 * d + 1)) * scale / (2 * d)
        params = {"W": w + _transform_offset(d)[None],
                  "Wu": jax.random.normal(keys[2], (n_ccat, c, d + 1))
                  * scale / d}
        if self.use_tensors:
            params["T"] = (jax.random.normal(keys[3], (n_cat, d, 2 * d, 2 * d))
                           * scale / (4 * d * d))
        if not self.combine_classification:
            params["Wb"] = (jax.random.normal(keys[4], (n_cat, c, d + 1))
                            * scale / d)
        if self._feature_vectors_init:
            emb = np.zeros((v, d), np.float32)
            found = 0
            for word, idx in self.word_index.items():
                vec = self._feature_vectors_init.get(word)
                if vec is not None:
                    emb[idx] = np.asarray(vec, np.float32)[:d]
                    found += 1
            missing = emb.sum(-1) == 0
            rand = np.asarray(jax.random.normal(keys[5], (v, d))) * scale / d
            emb[missing] = rand[missing]
            log.info("RNTN: %d/%d word vectors from lookup table", found, v)
            params["E"] = jnp.asarray(emb)
        else:
            params["E"] = jax.random.normal(keys[5], (v, d)) * scale / d
        self._params = params

    # ------------------------------------------------------------- forward
    def _forward_slots(self, params, enc_row):
        """Node vectors for one encoded tree: scan over post-order slots."""
        kind, word, left, right, cat = (enc_row["kind"], enc_row["word"],
                                        enc_row["left"], enc_row["right"],
                                        enc_row["cat"])
        d = self.num_hidden
        n_slots = kind.shape[0]
        act = self.activation_function

        def step(vecs, i):
            h_word = apply_activation(act, params["E"][word[i]])
            child = jnp.concatenate([vecs[left[i]], vecs[right[i]]])
            pre = params["W"][cat[i]] @ _append_one(child)
            if self.use_tensors:
                pre = pre + jnp.einsum("dij,i,j->d", params["T"][cat[i]],
                                       child, child)
            h_bin = apply_activation(act, pre)
            vec = jnp.where(kind[i] == 1, h_word,
                            jnp.where(kind[i] == 2, h_bin,
                                      jnp.zeros((d,))))
            return vecs.at[i].set(vec), None

        vecs0 = jnp.zeros((n_slots, d))
        vecs, _ = jax.lax.scan(step, vecs0, jnp.arange(n_slots))
        return vecs

    def _logits_slots(self, params, enc_row, vecs):
        """Per-slot class logits: unary classification for preterminals (and
        everything when combineClassification), else binary classification."""
        ccat, kind = enc_row["ccat"], enc_row["kind"]
        vecs1 = _append_one(vecs)
        unary = jnp.einsum("ncd,sd->snc", params["Wu"],
                           vecs1)[jnp.arange(vecs.shape[0]), ccat]
        if self.combine_classification or "Wb" not in params:
            return unary
        cat = enc_row["cat"]
        binary = jnp.einsum("ncd,sd->snc", params["Wb"],
                            vecs1)[jnp.arange(vecs.shape[0]), cat]
        return jnp.where((kind == 1)[:, None], unary, binary)

    def _tree_errors(self, params, enc_row, class_weight_vec):
        """Per-slot class-weighted cross-entropy (0 for pad/unlabeled)."""
        vecs = self._forward_slots(params, enc_row)
        logits = self._logits_slots(params, enc_row, vecs)
        gold, kind = enc_row["gold"], enc_row["kind"]
        labeled = (gold >= 0) & (kind > 0)
        safe_gold = jnp.maximum(gold, 0)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ce = -jnp.take_along_axis(logp, safe_gold[:, None], 1)[:, 0]
        weight = class_weight_vec[safe_gold]
        return jnp.where(labeled, ce * weight, 0.0), vecs, logits

    def _class_weight_vec(self) -> jnp.ndarray:
        w = np.ones((self.num_outs,), np.float32)
        for k, v in self.class_weights.items():
            w[k] = v
        return jnp.asarray(w)

    def loss_fn(self, params, enc: EncodedTrees):
        """Mean per-tree node error + the four L2 costs
        (reference scaleAndRegularize, RNTN.java:550-575)."""
        cw = self._class_weight_vec()
        enc_dict = enc._asdict()
        del enc_dict["root"]

        def one_tree(row):
            errors, _, _ = self._tree_errors(params, row, cw)
            return errors.sum()

        per_tree = jax.vmap(one_tree)(
            {k: jnp.asarray(v) for k, v in enc_dict.items()})
        loss = per_tree.mean()
        loss = loss + self.reg_transform_matrix / 2 * jnp.sum(
            params["W"] ** 2)
        if "T" in params:
            loss = loss + self.reg_transform_tensor / 2 * jnp.sum(
                params["T"] ** 2)
        loss = loss + self.reg_classification / 2 * jnp.sum(params["Wu"] ** 2)
        if "Wb" in params:
            loss = loss + self.reg_classification / 2 * jnp.sum(
                params["Wb"] ** 2)
        loss = loss + self.reg_word_vector / 2 * jnp.sum(params["E"] ** 2)
        return loss

    # ------------------------------------------------------------- training
    def _get_step(self):
        if self._step is None:
            @jax.jit
            def step(params, hist, enc_arrays):
                loss, grads = jax.value_and_grad(self.loss_fn)(
                    params, EncodedTrees(**enc_arrays))
                hist = jax.tree_util.tree_map(
                    lambda h, g: h + g * g, hist, grads)
                params = jax.tree_util.tree_map(
                    lambda p, g, h: p - self.lr * g /
                    (jnp.sqrt(h) + ADAGRAD_EPS), params, grads, hist)
                return params, hist, loss

            self._step = step
        return self._step

    def fit(self, trees: List[Tree], epochs: int = 1,
            max_nodes: Optional[int] = None) -> float:
        """Train on labeled trees; returns the final loss. AdaGrad history
        resets every `adagrad_reset_frequency` epochs (0 = never,
        reference adagradResetFrequency)."""
        self._build_vocab(trees)
        self._build_categories(trees)
        self._init_params()
        enc = self.encode(trees, max_nodes=max_nodes)
        enc_arrays = {k: jnp.asarray(v) for k, v in enc._asdict().items()}
        step = self._get_step()
        if self._adagrad_hist is None:
            self._adagrad_hist = jax.tree_util.tree_map(
                jnp.zeros_like, self._params)
        loss = None
        for epoch in range(epochs):
            if (self.adagrad_reset_frequency
                    and epoch and epoch % self.adagrad_reset_frequency == 0):
                self._adagrad_hist = jax.tree_util.tree_map(
                    jnp.zeros_like, self._params)
            self._params, self._adagrad_hist, loss = step(
                self._params, self._adagrad_hist, enc_arrays)
        self.value = float(loss)
        return self.value

    # ------------------------------------------------------------ inference
    def encode(self, trees: List[Tree],
               max_nodes: Optional[int] = None) -> EncodedTrees:
        # word_index keys are already normalized at vocab-build time; the
        # same normalization must apply to looked-up tree tokens
        return encode_trees(trees, self.word_index,
                            unk_index=self.word_index.get(UNK, 0),
                            cat_index=self.cat_index,
                            ccat_index=self.ccat_index, max_nodes=max_nodes,
                            word_transform=self._norm_word)

    def forward_propagate_tree(self, tree: Tree) -> None:
        """Annotate every internal node with vector/prediction/error
        (reference forwardPropagateTree :717 contract: after the call each
        non-leaf node carries its node vector and class predictions)."""
        if self._params is None:
            raise RuntimeError("fit() the RNTN before forward propagation")
        enc = self.encode([tree])
        row = {k: jnp.asarray(v[0]) for k, v in enc._asdict().items()
               if k != "root"}
        errors, vecs, logits = self._tree_errors(
            self._params, row, self._class_weight_vec())
        preds = jax.nn.softmax(logits, axis=-1)
        vecs, preds, errors = (np.asarray(vecs), np.asarray(preds),
                               np.asarray(errors))
        slot = [0]

        def visit(node: Tree):
            if node.is_leaf():
                return
            if not node.is_preterminal():
                for c in node.children:
                    visit(c)
            s = slot[0]
            slot[0] += 1
            node.vector = vecs[s]
            node.prediction = preds[s]
            node.error = float(errors[s])

        visit(tree)

    def predict(self, tree: Tree) -> int:
        """Predicted class of the root node."""
        self.forward_propagate_tree(tree)
        return int(np.argmax(tree.prediction))

    def output(self, trees: List[Tree]) -> np.ndarray:
        """Root-node class probabilities for a batch of trees — one
        encode + one vmapped forward (the batched path loss_fn uses),
        not a per-tree Python loop."""
        if self._params is None:
            raise RuntimeError("fit() the RNTN before inference")
        enc = self.encode(trees)
        cw = self._class_weight_vec()
        rows = {k: jnp.asarray(v) for k, v in enc._asdict().items()
                if k != "root"}

        def one_tree(row):
            _, vecs, logits = self._tree_errors(self._params, row, cw)
            return jax.nn.softmax(logits, axis=-1)

        preds = jax.vmap(one_tree)(rows)  # (n_trees, slots, C)
        return np.asarray(preds[np.arange(enc.n_trees), enc.root])

    # ----------------------------------------------------------- Model-ish
    def params(self):
        return self._params

    def set_params(self, params) -> None:
        self._params = params

    def score(self, trees: List[Tree]) -> float:
        enc = self.encode(trees)
        return float(self.loss_fn(self._params, EncodedTrees(
            *(jnp.asarray(a) for a in enc))))

    def num_parameters(self) -> int:
        return sum(int(np.prod(a.shape))
                   for a in jax.tree_util.tree_leaves(self._params))
