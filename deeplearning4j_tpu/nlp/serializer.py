"""Word-vector (de)serialization: Google word2vec binary + text formats.

Parity: reference nlp/models/embeddings/loader/WordVectorSerializer.java
(388 LoC): `writeWordVectors`/`loadTxtVectors` (text: "word v1 v2 ...\\n")
and the Google binary format ("V D\\n" header, then per word: "word " +
D float32 little-endian + '\\n').
"""

from __future__ import annotations

import struct
from typing import TYPE_CHECKING

import numpy as np

from deeplearning4j_tpu.nlp.vocab import VocabCache

if TYPE_CHECKING:  # pragma: no cover
    from deeplearning4j_tpu.nlp.word2vec import WordVectors


def save_word_vectors(wv: "WordVectors", path: str,
                      binary: bool = False) -> None:
    vocab, syn0 = wv.vocab, np.asarray(wv.syn0, np.float32)
    v, d = syn0.shape
    if binary:
        with open(path, "wb") as f:
            f.write(f"{v} {d}\n".encode())
            for i in range(v):
                f.write(vocab.word_at(i).encode() + b" ")
                f.write(syn0[i].astype("<f4").tobytes())
                f.write(b"\n")
    else:
        with open(path, "w", encoding="utf-8") as f:
            for i in range(v):
                vec = " ".join(f"{x:.6g}" for x in syn0[i])
                f.write(f"{vocab.word_at(i)} {vec}\n")


def load_word_vectors(path: str, binary: bool = False) -> "WordVectors":
    from deeplearning4j_tpu.nlp.word2vec import WordVectors

    cache = VocabCache()
    vectors = []
    if binary:
        with open(path, "rb") as f:
            header = f.readline().split()
            v, d = int(header[0]), int(header[1])
            for _ in range(v):
                word = bytearray()
                while True:
                    ch = f.read(1)
                    if ch in (b" ", b""):
                        break
                    word.extend(ch)
                vec = np.frombuffer(f.read(4 * d), dtype="<f4")
                trailer = f.read(1)  # newline
                if trailer not in (b"\n", b""):
                    raise ValueError("Malformed word2vec binary file")
                w = word.decode("utf-8", errors="replace")
                if cache.contains(w):  # duplicate row: keep the first
                    continue
                cache.add_token(w)
                cache.add_word_to_index(w)
                vectors.append(np.asarray(vec, np.float32))
    else:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                parts = line.rstrip("\n").split(" ")
                if len(parts) == 2 and all(p.isdigit() for p in parts):
                    continue  # optional "V D" header
                w, vals = parts[0], parts[1:]
                if cache.contains(w):  # duplicate row: keep the first
                    continue
                cache.add_token(w)
                cache.add_word_to_index(w)
                vectors.append(np.asarray([float(x) for x in vals],
                                          np.float32))
    if not vectors:
        raise ValueError(f"No vectors found in {path}")
    return WordVectors(cache, np.stack(vectors))
