"""Native part-of-speech tagging.

Parity target: reference `text/annotator/PoStagger.java:246` — a UIMA
AnalysisEngine wrapping OpenNLP's pre-trained maxent tagger. The wrapper
itself is third-party glue (scoped out, README), but the CAPABILITY it
gave the moving-window pipeline — per-token PoS tags as context
features — is a framework feature, provided here natively: a trainable
bigram HMM decoded with the shared Viterbi machinery
(`utils/viterbi.py::viterbi_path`, the general-table form of the
reference's own `core/util/Viterbi.java` chain).

Training is closed-form counting (no gradient loop): tag-bigram
transition counts and word|tag emission counts with add-k smoothing;
unknown words fall back to suffix-signature emissions (the classic
HMM-tagger recipe), so the tagger generalizes beyond its training
vocabulary.
"""

from __future__ import annotations

import math
from collections import Counter, defaultdict
from typing import Dict, List, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.utils.viterbi import viterbi_path

_SUFFIXES = ("ing", "ed", "ly", "s", "tion", "ity", "ous", "ful", "est",
             "er", "al", "ive")


def _signature(word: str) -> str:
    """Unknown-word bucket: digits / capitalization / suffix shape."""
    if any(c.isdigit() for c in word):
        return "<num>"
    for suf in _SUFFIXES:
        if len(word) > len(suf) + 1 and word.lower().endswith(suf):
            return f"<suf:{suf}>"
    if word[:1].isupper():
        return "<cap>"
    return "<unk>"


class HmmPosTagger:
    """Bigram HMM tagger: train on (word, tag) sentences, tag new
    token sequences via Viterbi decoding."""

    def __init__(self, smoothing: float = 0.1):
        self.smoothing = smoothing
        self.tags: List[str] = []
        self._tag_index: Dict[str, int] = {}
        self._log_trans: np.ndarray | None = None
        self._log_init: np.ndarray | None = None
        #: word -> (n_tags,) emission log-prob columns; includes the
        #: <unk>/signature buckets trained from singleton words
        self._log_emit: Dict[str, np.ndarray] = {}

    # ------------------------------------------------------------- train
    def train(self, tagged_sentences: Sequence[Sequence[Tuple[str, str]]]
              ) -> "HmmPosTagger":
        if not tagged_sentences:
            raise ValueError("need at least one tagged sentence")
        tag_set = sorted({t for sent in tagged_sentences for _, t in sent})
        if len(tag_set) < 2:
            raise ValueError("need at least 2 distinct tags")
        # retraining replaces the model wholesale — stale emission rows
        # from a previous corpus would carry the OLD tag alphabet
        self._log_emit = {}
        self.tags = tag_set
        self._tag_index = {t: i for i, t in enumerate(tag_set)}
        n = len(tag_set)
        k = self.smoothing

        trans = np.full((n, n), k)
        init = np.full((n,), k)
        emit: Dict[str, Counter] = defaultdict(Counter)
        word_freq: Counter = Counter()
        for sent in tagged_sentences:
            prev = None
            for word, tag in sent:
                ti = self._tag_index[tag]
                w = word.lower()
                emit[w][ti] += 1
                word_freq[w] += 1
                if prev is None:
                    init[ti] += 1
                else:
                    trans[prev, ti] += 1
                prev = ti
        # rare words (freq 1) ALSO train their signature bucket, so an
        # unseen word inherits the tag distribution of its shape class
        for sent in tagged_sentences:
            for word, tag in sent:
                if word_freq[word.lower()] <= 1:
                    emit[_signature(word)][self._tag_index[tag]] += 1

        self._log_trans = np.log(trans / trans.sum(axis=1, keepdims=True))
        self._log_init = np.log(init / init.sum())
        tag_totals = np.full((n,), k * (len(emit) + 1))
        for counts in emit.values():
            for ti, c in counts.items():
                tag_totals[ti] += c
        for w, counts in emit.items():
            col = np.full((n,), k)
            for ti, c in counts.items():
                col[ti] += c
            self._log_emit[w] = np.log(col / tag_totals)
        self._fallback = np.log(np.full((n,), k) / tag_totals)
        return self

    # --------------------------------------------------------------- tag
    def _emission_row(self, word: str) -> np.ndarray:
        w = word.lower()
        if w in self._log_emit:
            return self._log_emit[w]
        sig = _signature(word)
        return self._log_emit.get(sig, self._fallback)

    def tag(self, tokens: Sequence[str]) -> List[str]:
        """Most likely tag sequence for `tokens`."""
        if self._log_trans is None:
            raise RuntimeError("tagger is untrained; call train() first")
        if not tokens:
            return []
        emits = np.stack([self._emission_row(t) for t in tokens])
        _, path = viterbi_path(self._log_init, self._log_trans, emits)
        return [self.tags[i] for i in path]

    def tag_sentence(self, tokens: Sequence[str]
                     ) -> List[Tuple[str, str]]:
        return list(zip(tokens, self.tag(tokens)))


__all__ = ["HmmPosTagger"]
