"""Tokenizers + factories.

Parity: reference nlp/text/tokenization/ — `Tokenizer`/`TokenizerFactory`
with DefaultTokenizer (whitespace/punct), NGramTokenizer, and pluggable
token pre-processing (EndingPreProcessor etc.). UIMA-backed tokenizers are
out of scope (external UIMA dependency); the factory interface accepts any
callable pre-processor, which covers their role.
"""

from __future__ import annotations

import re
from typing import Callable, List, Optional


class Tokenizer:
    def tokens(self) -> List[str]:
        raise NotImplementedError


class DefaultTokenizer(Tokenizer):
    """Lowercased word tokens, punctuation-stripped (DefaultTokenizer)."""

    _WORD = re.compile(r"[\w']+")

    def __init__(self, text: str,
                 pre_processor: Optional[Callable[[str], str]] = None):
        self.text = text
        self.pre_processor = pre_processor

    def tokens(self) -> List[str]:
        toks = self._WORD.findall(self.text.lower())
        if self.pre_processor is not None:
            toks = [self.pre_processor(t) for t in toks]
        return [t for t in toks if t]


class TokenizerFactory:
    def create(self, text: str) -> Tokenizer:
        raise NotImplementedError

    def tokenize(self, text: str) -> List[str]:
        return self.create(text).tokens()


class DefaultTokenizerFactory(TokenizerFactory):
    def __init__(self, pre_processor: Optional[Callable[[str], str]] = None):
        self.pre_processor = pre_processor

    def create(self, text: str) -> DefaultTokenizer:
        return DefaultTokenizer(text, self.pre_processor)


class WhitespaceTokenizer(Tokenizer):
    """Plain whitespace tokenization — the reference's ACTUAL
    DefaultTokenizer (text/tokenization/tokenizer/DefaultTokenizer.java:
    a java.util.StringTokenizer: no lowercasing, no punctuation strip).
    ~5x faster than the regex tokenizer; the right choice for
    pre-cleaned/space-separated corpora (text8-style) where tokenization
    is the Word2Vec pipeline's bottleneck."""

    def __init__(self, text: str,
                 pre_processor: Optional[Callable[[str], str]] = None):
        self.text = text
        self.pre_processor = pre_processor

    def tokens(self) -> List[str]:
        toks = self.text.split()
        if self.pre_processor is not None:
            toks = [t for t in (self.pre_processor(t) for t in toks) if t]
        return toks


class WhitespaceTokenizerFactory(TokenizerFactory):
    def __init__(self, pre_processor: Optional[Callable[[str], str]] = None):
        self.pre_processor = pre_processor

    def create(self, text: str) -> WhitespaceTokenizer:
        return WhitespaceTokenizer(text, self.pre_processor)


class NGramTokenizerFactory(TokenizerFactory):
    """Emit n-grams (joined by '_') over the base tokens
    (reference NGramTokenizerFactory)."""

    def __init__(self, n_min: int = 1, n_max: int = 2,
                 base: Optional[TokenizerFactory] = None):
        self.n_min, self.n_max = n_min, n_max
        self.base = base or DefaultTokenizerFactory()

    def create(self, text: str) -> Tokenizer:
        words = self.base.tokenize(text)
        grams: List[str] = []
        for n in range(self.n_min, self.n_max + 1):
            for i in range(len(words) - n + 1):
                grams.append("_".join(words[i:i + n]))
        tok = Tokenizer()
        tok.tokens = lambda: grams  # type: ignore[assignment]
        return tok


def stem_ending_preprocessor(token: str) -> str:
    """Light suffix-stripping normalizer (reference EndingPreProcessor)."""
    for suffix in ("ies", "s", "ed", "ing", "ly"):
        if token.endswith(suffix) and len(token) > len(suffix) + 2:
            return token[: -len(suffix)]
    return token
