"""Sentence / document iterators.

Parity: reference nlp/text/sentenceiterator/ — `SentenceIterator`
(nextSentence/hasNext/reset + SentencePreProcessor),
CollectionSentenceIterator, FileSentenceIterator (every file under a dir),
LineSentenceIterator, and the label-aware variants used by
ParagraphVectors (LabelAwareSentenceIterator).
"""

from __future__ import annotations

import os
from typing import Callable, Iterable, List, Optional, Tuple


class SentenceIterator:
    def __init__(self, pre_processor: Optional[Callable[[str], str]] = None):
        self.pre_processor = pre_processor

    def _prep(self, s: str) -> str:
        return self.pre_processor(s) if self.pre_processor else s

    def next_sentence(self) -> str:
        raise NotImplementedError

    def has_next(self) -> bool:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next_sentence()


class CollectionSentenceIterator(SentenceIterator):
    def __init__(self, sentences: Iterable[str], **kw):
        super().__init__(**kw)
        self.sentences: List[str] = list(sentences)
        self._pos = 0

    def next_sentence(self) -> str:
        s = self.sentences[self._pos]
        self._pos += 1
        return self._prep(s)

    def has_next(self) -> bool:
        return self._pos < len(self.sentences)

    def reset(self) -> None:
        self._pos = 0


class LineSentenceIterator(SentenceIterator):
    """One sentence per line of a file (reference LineSentenceIterator)."""

    def __init__(self, path: str, **kw):
        super().__init__(**kw)
        self.path = path
        self._file = None

    def reset(self) -> None:
        if self._file:
            self._file.close()
        self._file = open(self.path, "r", encoding="utf-8", errors="replace")
        self._next = self._file.readline()

    def has_next(self) -> bool:
        if self._file is None:
            self.reset()
        return bool(self._next)

    def next_sentence(self) -> str:
        if self._file is None:
            self.reset()
        s, self._next = self._next, self._file.readline()
        return self._prep(s.rstrip("\n"))


class FileSentenceIterator(SentenceIterator):
    """Every line of every file under a directory
    (reference FileSentenceIterator)."""

    def __init__(self, root: str, **kw):
        super().__init__(**kw)
        self.root = root
        self._lines: Optional[List[str]] = None
        self._pos = 0

    def reset(self) -> None:
        lines: List[str] = []
        if os.path.isfile(self.root):
            paths = [self.root]
        else:
            paths = sorted(
                os.path.join(dp, f)
                for dp, _, fs in os.walk(self.root) for f in fs)
        for p in paths:
            with open(p, "r", encoding="utf-8", errors="replace") as f:
                lines.extend(line.rstrip("\n") for line in f)
        self._lines = lines
        self._pos = 0

    def has_next(self) -> bool:
        if self._lines is None:
            self.reset()
        return self._pos < len(self._lines)

    def next_sentence(self) -> str:
        if self._lines is None:
            self.reset()
        s = self._lines[self._pos]
        self._pos += 1
        return self._prep(s)


class LabelAwareSentenceIterator(SentenceIterator):
    """(label, sentence) pairs for ParagraphVectors
    (reference LabelAwareListSentenceIterator)."""

    def __init__(self, pairs: Iterable[Tuple[str, str]], **kw):
        super().__init__(**kw)
        self.pairs: List[Tuple[str, str]] = list(pairs)
        self._pos = 0

    def current_label(self) -> str:
        return self.pairs[max(0, self._pos - 1)][0]

    def next_sentence(self) -> str:
        label, s = self.pairs[self._pos]
        self._pos += 1
        return self._prep(s)

    def has_next(self) -> bool:
        return self._pos < len(self.pairs)

    def reset(self) -> None:
        self._pos = 0
