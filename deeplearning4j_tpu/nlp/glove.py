"""GloVe: co-occurrence counting + weighted least-squares embedding.

Parity: reference nlp/models/glove/ — `CoOccurrences` (windowed
co-occurrence counting with 1/distance weighting, CoOccurrences.java:355),
`GloveWeightLookupTable` (AdaGrad weighted-LSQ update, the f(X)=min(1,
(X/xMax)^alpha) weighting) and `Glove` (shuffled co-occurrence training,
Glove.java:57,:106-160).

TPU-native design: the reference updates one co-occurrence pair at a time
with per-row AdaGrad; here the (i, j, X_ij) triples become index tensors
and one jitted AdaGrad step computes the weighted-LSQ loss over the whole
shuffled batch — gathers in, scatter-add gradients out.
"""

from __future__ import annotations

import logging
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.sentence_iterator import (
    CollectionSentenceIterator,
    SentenceIterator,
)
from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory,
    TokenizerFactory,
)
from deeplearning4j_tpu.nlp.vocab import VocabCache, build_vocab
from deeplearning4j_tpu.nlp.word2vec import WordVectors

log = logging.getLogger(__name__)


class CoOccurrences:
    """Windowed co-occurrence counts weighted by 1/distance
    (reference CoOccurrences.java)."""

    def __init__(self, sentences: SentenceIterator,
                 tokenizer_factory: TokenizerFactory,
                 cache: VocabCache, window: int = 5,
                 symmetric: bool = True):
        self.sentences = sentences
        self.tokenizer_factory = tokenizer_factory
        self.cache = cache
        self.window = window
        self.symmetric = symmetric
        self._rows = np.empty(0, np.int32)
        self._cols = np.empty(0, np.int32)
        self._vals = np.empty(0, np.float32)

    def calc(self) -> "CoOccurrences":
        """Vectorized windowed counting: the corpus becomes ONE index
        array with -1 sentence separators; for each offset d the pair
        streams are sliced arrays (validity = no separator within the
        window, via a cumulative separator count), and aggregation is a
        sort-free np.unique over packed (row*V + col) keys. The
        reference's per-token loop (CoOccurrences.java) is O(corpus)
        Python dict updates — this handles a 10M-token corpus in
        seconds instead of minutes."""
        chunks = []
        sep = np.asarray([-1], np.int64)
        for sentence in self.sentences:
            toks = self.tokenizer_factory.tokenize(sentence)
            idxs = [self.cache.index_of(t) for t in toks]
            idxs = [i for i in idxs if i >= 0]
            if idxs:
                chunks.append(np.asarray(idxs, np.int64))
                chunks.append(sep)
        if not chunks:
            return self
        seq = np.concatenate(chunks)
        v = max(self.cache.num_words(), 1)
        n_sep = np.cumsum(seq < 0)
        keys_list, w_list = [], []
        for off in range(1, self.window + 1):
            if off >= seq.size:
                break
            # window unbroken: no separator strictly inside (i, i+off]
            # AND the left element itself is not a separator (the cumsum
            # difference does not count position i)
            valid = (n_sep[off:] - n_sep[:-off]) == 0
            valid &= seq[:-off] >= 0
            a = seq[:-off][valid]
            b = seq[off:][valid]
            if a.size == 0:
                continue
            w = np.full(a.size, 1.0 / off, np.float64)  # 1/distance
            keys_list.append(a * v + b)
            w_list.append(w)
            if self.symmetric:
                keys_list.append(b * v + a)
                w_list.append(w)
        if not keys_list:
            return self
        keys = np.concatenate(keys_list)
        weights = np.concatenate(w_list)
        uniq, inverse = np.unique(keys, return_inverse=True)
        sums = np.bincount(inverse, weights=weights)
        self._rows = (uniq // v).astype(np.int32)
        self._cols = (uniq % v).astype(np.int32)
        self._vals = sums.astype(np.float32)
        return self

    @property
    def counts(self) -> Dict[Tuple[int, int], float]:
        """READ-ONLY dict view of the counts, rebuilt on every access
        (small-corpus convenience; the training path uses triples()
        arrays directly). Mutating the returned dict does NOT write back
        into the accumulator — modify via count()/accumulate instead."""
        return defaultdict(float, {
            (int(r), int(c)): float(x)
            for r, c, x in zip(self._rows, self._cols, self._vals)})

    def triples(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        return self._rows, self._cols, self._vals


class Glove(WordVectors):
    """GloVe trainer (reference Glove.java builder semantics: layerSize,
    xMax, alpha, learningRate, iterations, window, minWordFrequency)."""

    def __init__(self, sentences=None, *, layer_size: int = 100,
                 window: int = 5, min_word_frequency: float = 1.0,
                 iterations: int = 5, learning_rate: float = 0.05,
                 x_max: float = 100.0, alpha: float = 0.75,
                 batch_size: int = 8192, seed: int = 123,
                 tokenizer_factory: Optional[TokenizerFactory] = None):
        self.layer_size = layer_size
        self.window = window
        self.min_word_frequency = min_word_frequency
        self.iterations = iterations
        self.lr = learning_rate
        self.x_max = x_max
        self.alpha = alpha
        self.batch_size = batch_size
        self.seed = seed
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        if isinstance(sentences, SentenceIterator):
            self.sentence_iter = sentences
        elif sentences is not None:
            self.sentence_iter = CollectionSentenceIterator(list(sentences))
        else:
            self.sentence_iter = None
        self.vocab = VocabCache()
        self.co: Optional[CoOccurrences] = None
        self._epoch_fn = None
        self._params = None
        self._accum = None
        self._triples = None
        self._device_triples = None
        self._epoch_key = None

    def _epoch_step(self):
        """Build (once) the compiled whole-epoch program: per-batch host
        dispatch (the dominant cost on a tunneled chip) is paid once per
        epoch, and the triple count is fixed so every epoch — across
        repeated train_epochs calls — reuses the same program."""
        if self._epoch_fn is not None:
            return self._epoch_fn
        x_max, alpha, lr = self.x_max, self.alpha, self.lr

        def loss_fn(params, r, c, x):
            wr, wc = params["w"][r], params["c"][c]
            pred = jnp.sum(wr * wc, axis=1) + params["bw"][r] + params["bc"][c]
            err = pred - jnp.log(x)
            fx = jnp.minimum(1.0, (x / x_max) ** alpha)
            return 0.5 * jnp.sum(fx * err * err) / r.shape[0]

        def step_core(carry, batch):
            params, accum = carry
            r, c, x = batch
            loss, grads = jax.value_and_grad(loss_fn)(params, r, c, x)
            accum = jax.tree_util.tree_map(
                lambda a, g: a + g * g, accum, grads)
            params = jax.tree_util.tree_map(
                lambda p, g, a: p - lr * g / jnp.sqrt(a), params, grads,
                accum)
            return (params, accum), loss

        B = self.batch_size

        @jax.jit
        def epoch(params, accum, key, rows, cols, vals):
            # DEVICE-side shuffle: the triples are uploaded once and
            # stay resident; permuting on device removes the ~MBs of
            # shuffled index arrays the host used to push through the
            # tunnel EVERY epoch (that H2D transfer was both the
            # throughput floor and the dominant noise source of the
            # glove bench — the tunnel's bandwidth weather varied it by
            # 4x between consecutive epochs). Shapes are static under
            # jit, so the pad/tile math is ordinary Python here.
            n = rows.shape[0]
            n_pad = (n + B - 1) // B * B
            perm = jax.random.permutation(key, n)
            # wrap-around pad (n may be far below one batch)
            order = perm[jnp.arange(n_pad) % n] if n_pad != n else perm
            shape = (n_pad // B, B)
            rb = rows[order].reshape(shape)
            cb = cols[order].reshape(shape)
            xb = vals[order].reshape(shape)
            (params, accum), losses = jax.lax.scan(
                step_core, (params, accum), (rb, cb, xb))
            return params, accum, losses[-1]

        self._epoch_fn = epoch
        return epoch

    def prepare(self) -> "Glove":
        """Corpus pass: vocab + co-occurrence counting (reference
        Glove.java :106 CoOccurrences.calc) and parameter init. Split
        from training so repeated train_epochs calls (resumed training,
        benchmarks) don't re-mine the corpus."""
        build_vocab(self.sentence_iter, self.tokenizer_factory,
                    self.min_word_frequency, self.vocab)
        self.co = CoOccurrences(self.sentence_iter, self.tokenizer_factory,
                                self.vocab, window=self.window).calc()
        rows, cols, vals = self.co.triples()
        if rows.size == 0:
            raise ValueError("No co-occurrences (corpus too small)")
        self._triples = (rows, cols, vals)
        v, d = self.vocab.num_words(), self.layer_size
        key = jax.random.PRNGKey(self.seed)
        kw, kc = jax.random.split(key)
        self._params = {
            "w": jax.random.uniform(kw, (v, d), jnp.float32, -0.5 / d, 0.5 / d),
            "c": jax.random.uniform(kc, (v, d), jnp.float32, -0.5 / d, 0.5 / d),
            "bw": jnp.zeros((v,), jnp.float32),
            "bc": jnp.zeros((v,), jnp.float32),
        }
        # per-parameter AdaGrad accumulators (GloveWeightLookupTable parity)
        self._accum = jax.tree_util.tree_map(
            lambda p: jnp.full(p.shape, 1e-8, jnp.float32), self._params)
        # distinct stream from the param-init keys (which consumed
        # split(PRNGKey(seed)) above) — fold_in decorrelates them
        self._epoch_key = jax.random.fold_in(
            jax.random.PRNGKey(self.seed), 0x5e)
        self._device_triples = None  # re-prepare invalidates the cache
        return self

    def train_epochs(self, n_epochs: int) -> float:
        """Run n shuffled epochs over the prepared co-occurrence triples
        (one compiled program per epoch) and refresh the WordVectors
        view. Returns the final batch loss."""
        if self._triples is None:
            raise ValueError("call prepare() before train_epochs()")
        if n_epochs < 1:
            raise ValueError(f"n_epochs must be >= 1, got {n_epochs}")
        rows, cols, vals = self._triples
        epoch = self._epoch_step()
        # triples uploaded ONCE and cached device-resident; each epoch
        # only ships a PRNG key (the shuffle runs on device)
        if self._device_triples is None:
            self._device_triples = (jnp.asarray(rows), jnp.asarray(cols),
                                    jnp.asarray(vals))
        d_rows, d_cols, d_vals = self._device_triples
        loss = None
        for _ in range(n_epochs):
            self._epoch_key, sub = jax.random.split(self._epoch_key)
            self._params, self._accum, loss = epoch(
                self._params, self._accum, sub, d_rows, d_cols, d_vals)
        syn0 = (np.asarray(self._params["w"])
                + np.asarray(self._params["c"]))
        WordVectors.__init__(self, self.vocab, syn0)
        return float(loss)

    def fit(self) -> "Glove":
        self.prepare()
        loss = self.train_epochs(self.iterations)
        log.info("glove trained: %d triples, final loss %.4f",
                 self._triples[0].size, loss)
        return self
