"""NLP stack: text pipeline, vocab, embeddings (Word2Vec/GloVe/doc2vec).

Parity: reference deeplearning4j-scaleout/deeplearning4j-nlp (SURVEY §2.6) —
sentence iterators, tokenizer factories, VocabCache, Huffman coding,
Word2Vec (skip-gram with hierarchical softmax + negative sampling), GloVe,
ParagraphVectors, bag-of-words/TF-IDF vectorizers, word-vector serializer.

TPU-native design: the reference's per-pair hogwild axpy hot loop
(InMemoryLookupTable.iterateSample :188) becomes BATCHED device training —
pairs are mined on the host, shipped as index tensors, and one jitted step
computes the loss over the whole batch; autodiff turns the embedding
gathers into scatter-add updates (deterministic segment-sums instead of
lock-free races).
"""

from deeplearning4j_tpu.nlp.tokenization import (  # noqa: F401
    DefaultTokenizer,
    DefaultTokenizerFactory,
    NGramTokenizerFactory,
    WhitespaceTokenizer,
    WhitespaceTokenizerFactory,
)
from deeplearning4j_tpu.nlp.sentence_iterator import (  # noqa: F401
    CollectionSentenceIterator,
    FileSentenceIterator,
    LabelAwareSentenceIterator,
    LineSentenceIterator,
)
from deeplearning4j_tpu.nlp.documents import (  # noqa: F401
    DocumentIterator,
    FileDocumentIterator,
    InvertedIndex,
    LabelAwareDocumentIterator,
)
from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabWord  # noqa: F401
from deeplearning4j_tpu.nlp.huffman import build_huffman  # noqa: F401
from deeplearning4j_tpu.nlp.word2vec import Word2Vec  # noqa: F401
from deeplearning4j_tpu.nlp.word2vec_iterator import (  # noqa: F401
    Word2VecDataFetcher,
    Word2VecDataSetIterator,
    viterbi_smooth,
)
from deeplearning4j_tpu.nlp.glove import CoOccurrences, Glove  # noqa: F401
from deeplearning4j_tpu.nlp.paragraph_vectors import ParagraphVectors  # noqa: F401
from deeplearning4j_tpu.nlp.serializer import (  # noqa: F401
    load_word_vectors,
    save_word_vectors,
)
from deeplearning4j_tpu.nlp.vectorizers import (  # noqa: F401
    BagOfWordsVectorizer,
    TfidfVectorizer,
)
from deeplearning4j_tpu.nlp.tree import (  # noqa: F401
    Tree,
    binarize,
    parse_tree,
)
from deeplearning4j_tpu.nlp.rntn import RNTN  # noqa: F401
