"""Word2Vec → classification-DataSet bridge with Viterbi smoothing.

Parity: reference nlp/models/word2vec/iterator/ —
`Word2VecDataSetIterator` (mine moving windows from a label-aware sentence
stream, featurize each window by concatenating the pretrained word vectors
of its tokens, one-hot the sentence label; Word2VecDataSetIterator.java:
next(num) window-cache loop :128-151, fromCached :153-197, inputColumns =
layerSize * window :208) and `Word2VecDataFetcher`. The reference pairs
this moving-window classifier with `Viterbi` smoothing of the predicted
label sequence (core/util/Viterbi.java:31-192).

TPU-native design: windows are featurized in blocks into one dense
(batch, window*dim) matrix — the batch crosses to the device once and the
classifier step stays a single fused XLA program. For corpora whose
window stream outgrows RAM, the window cache spills through
`DiskBasedQueue` (core/util/DiskBasedQueue.java parity) instead of the
reference's unbounded in-memory CopyOnWriteArrayList.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.api import DataSet, DataSetIterator
from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory,
    TokenizerFactory,
)
from deeplearning4j_tpu.nlp.windows import Window, window_as_vector, windows
from deeplearning4j_tpu.utils.disk_based_queue import DiskBasedQueue
from deeplearning4j_tpu.utils.viterbi import Viterbi

__all__ = ["Word2VecDataSetIterator", "Word2VecDataFetcher",
           "viterbi_smooth"]


class Word2VecDataSetIterator(DataSetIterator):
    """Moving-window classification datasets over pretrained word vectors
    (reference Word2VecDataSetIterator.java).

    `vec` is a fitted `WordVectors`/`Word2Vec` (needs `syn0`,
    `get_word_vector`, and a `window` size); `sentence_iter` is a
    LabelAwareSentenceIterator; `labels` fixes the outcome order."""

    def __init__(self, vec, sentence_iter, labels: Sequence[str],
                 batch: int = 10,
                 tokenizer_factory: Optional[TokenizerFactory] = None,
                 window_size: Optional[int] = None,
                 spill_to_disk: bool = False):
        self.vec = vec
        self.sentence_iter = sentence_iter
        self.labels = list(labels)
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        w = window_size if window_size is not None else getattr(
            vec, "window", 5)
        self.window_size = w if w % 2 == 1 else w + 1
        self.spill_to_disk = spill_to_disk
        self._cache = DiskBasedQueue() if spill_to_disk else None
        self._mem_cache: List[Window] = []
        # streaming source: totals unknowable up front (reference
        # totalExamples throws UnsupportedOperationException)
        super().__init__(batch_size=batch, num_examples=-1)

    # ------------------------------------------------------------ windows
    def _cache_size(self) -> int:
        return (self._cache.size() if self._cache is not None
                else len(self._mem_cache))

    def _cache_push(self, win: Window) -> None:
        if self._cache is not None:
            # windows serialize as JSON-able dicts (no pickle on disk)
            self._cache.add({"words": win.words, "focus": win.focus_index,
                             "label": win.label})
        else:
            self._mem_cache.append(win)

    def _cache_pop(self) -> Window:
        if self._cache is not None:
            rec = self._cache.remove()
            return Window(rec["words"], int(rec["focus"]),
                          label=rec["label"])
        return self._mem_cache.pop(0)

    def _mine_more(self, need: int) -> None:
        while self._cache_size() < need and self.sentence_iter.has_next():
            sentence = self.sentence_iter.next_sentence()
            if not sentence.strip():
                continue
            label = self.sentence_iter.current_label()
            tokens = self.tokenizer_factory.tokenize(sentence)
            for win in windows(tokens, self.window_size, label=label):
                self._cache_push(win)

    # ----------------------------------------------- DataSetIterator api
    def input_columns(self) -> int:
        """reference inputColumns :208: layerSize * window."""
        return int(self.vec.syn0.shape[1]) * self.window_size

    def total_outcomes(self) -> int:
        return len(self.labels)

    def total_examples(self) -> int:
        raise NotImplementedError(
            "streaming sentence source; total window count unknown "
            "(reference totalExamples throws UnsupportedOperationException)")

    num_examples = total_examples

    def has_next(self) -> bool:
        return self._cache_size() > 0 or self.sentence_iter.has_next()

    def reset(self) -> None:
        self.sentence_iter.reset()
        if self._cache is not None:
            self._cache.clear()
        self._mem_cache.clear()

    def next(self, num: Optional[int] = None) -> DataSet:
        n = num or self.batch_size
        self._mine_more(n)
        take = min(n, self._cache_size())
        if take == 0:
            raise StopIteration
        x = np.empty((take, self.input_columns()), np.float32)
        y = np.zeros((take, len(self.labels)), np.float32)
        for i in range(take):
            win = self._cache_pop()
            x[i] = window_as_vector(win, self.vec)
            if win.label is not None:
                try:
                    y[i, self.labels.index(win.label)] = 1.0
                except ValueError:
                    raise ValueError(
                        f"window label {win.label!r} not in labels "
                        f"{self.labels}") from None
        ds = DataSet(x, y)
        if self.pre_processor is not None:
            ds = self.pre_processor(ds)
        return ds


class Word2VecDataFetcher(Word2VecDataSetIterator):
    """File-corpus variant (reference Word2VecDataFetcher.java: iterate
    text files, window each line, featurize through the trained
    vectors). Labels come from each file's parent directory (the
    directory-per-class layout `LabelAwareDocumentIterator` reads);
    every non-empty line is one sentence."""

    def __init__(self, vec, corpus_root: str, labels=None, batch: int = 10,
                 **kw):
        from deeplearning4j_tpu.nlp.documents import (
            LabelAwareDocumentIterator)
        from deeplearning4j_tpu.nlp.sentence_iterator import (
            LabelAwareSentenceIterator)

        docs = LabelAwareDocumentIterator(corpus_root)
        pairs = []
        while docs.has_next():
            text = docs.next_document()
            label = docs.current_label()
            for line in text.splitlines():
                if line.strip():
                    pairs.append((label, line))
        if labels is None:
            labels = sorted({label for label, _ in pairs})
        super().__init__(vec, LabelAwareSentenceIterator(pairs),
                         labels=labels, batch=batch, **kw)


def viterbi_smooth(predictions: np.ndarray,
                   meta_stability: float = 0.9,
                   p_correct: float = 0.99) -> np.ndarray:
    """Smooth a sentence's per-window label predictions with Viterbi
    decoding (the reference's moving-window + Viterbi pairing,
    core/util/Viterbi.java:31-192): label flips between adjacent windows
    are penalized by the transition prior, so isolated one-window
    misclassifications snap to their neighborhood.

    `predictions`: (windows, classes) probabilities or one-hot — the
    per-window classifier output for ONE sentence, in order. Returns the
    smoothed label-index sequence."""
    predictions = np.asarray(predictions)
    if predictions.ndim != 2:
        raise ValueError("predictions must be (windows, classes)")
    v = Viterbi(np.arange(predictions.shape[1]),
                meta_stability=meta_stability, p_correct=p_correct)
    _, path = v.decode(predictions, binary_label_matrix=True)
    return path
