"""Document iteration + inverted index.

Parity: reference nlp text pipeline —
- `DocumentIterator` (text/documentiterator/DocumentIterator.java:28-48:
  nextDocument/hasNext/reset over input streams), `FileDocumentIterator`
  (FileDocumentIterator.java — recurse a root dir, one doc per file),
  `LabelAwareDocumentIterator` (currentLabel from the parent directory).
- `InvertedIndex` (text/invertedindex/InvertedIndex.java:34-160: word↔doc
  index with addWordsToDoc/document/documents/numDocuments/allDocs/
  batchIter/miniBatches + frequency subsampling) whose reference
  implementation is Lucene-backed (LuceneInvertedIndex.java, 927 LoC:
  Lucene Directory + IndexReader storing the word list per doc, and a
  mini-batch builder that subsamples frequent words with the word2vec
  `(sqrt(f/(sample*N)) + 1) * sample*N/f` keep-probability,
  LuceneInvertedIndex.java:517-535).

TPU-native design: no Lucene, no external index server. Documents are
token-index arrays packed into ONE contiguous int32 buffer with offsets
(the same flat layout the Word2Vec pair-miner and RNTN tree encoder use),
postings are plain int32 arrays per word — the whole index is
numpy-mmap-friendly and batches lower straight onto the device. Sampling
uses explicit numpy RNG (seeded, reproducible) instead of the reference's
racy shared-queue mini-batch thread.
"""

from __future__ import annotations

import os
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.nlp.vocab import VocabCache

__all__ = [
    "DocumentIterator",
    "FileDocumentIterator",
    "LabelAwareDocumentIterator",
    "InvertedIndex",
]


class DocumentIterator:
    """Iterate whole documents (reference DocumentIterator.java:28-48).

    Where the reference yields `InputStream`s, this yields `str` — the
    framework is host-side Python and every consumer immediately read and
    decoded the stream anyway."""

    def next_document(self) -> str:
        raise NotImplementedError

    def has_next(self) -> bool:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def __iter__(self) -> Iterator[str]:
        self.reset()
        while self.has_next():
            yield self.next_document()


class FileDocumentIterator(DocumentIterator):
    """One document per file under a root directory, recursively
    (reference FileDocumentIterator.java)."""

    def __init__(self, root: str, encoding: str = "utf-8"):
        if not os.path.isdir(root):
            raise ValueError(f"not a directory: {root}")
        self.root = root
        self.encoding = encoding
        self._paths = self._scan()
        self._pos = 0

    def _scan(self) -> List[str]:
        out = []
        for dirpath, _dirnames, filenames in os.walk(self.root):
            for name in sorted(filenames):
                out.append(os.path.join(dirpath, name))
        out.sort()
        return out

    def next_document(self) -> str:
        if not self.has_next():
            raise StopIteration
        path = self._paths[self._pos]
        self._pos += 1
        with open(path, encoding=self.encoding, errors="replace") as f:
            return f.read()

    def has_next(self) -> bool:
        return self._pos < len(self._paths)

    def reset(self) -> None:
        self._pos = 0


class LabelAwareDocumentIterator(FileDocumentIterator):
    """FileDocumentIterator that exposes the current document's label as
    its parent directory name (reference LabelAwareDocumentIterator —
    the imdb/20news-style directory-per-class corpus layout)."""

    def __init__(self, root: str, encoding: str = "utf-8"):
        super().__init__(root, encoding)
        self._current_label: Optional[str] = None

    def next_document(self) -> str:
        path = self._paths[self._pos]  # peek before advancing
        doc = super().next_document()
        self._current_label = os.path.basename(os.path.dirname(path))
        return doc

    def current_label(self) -> Optional[str]:
        return self._current_label


class InvertedIndex:
    """In-memory word↔document index with subsampled mini-batching
    (reference InvertedIndex.java contract / LuceneInvertedIndex.java
    implementation).

    Documents are stored as one flat int32 token buffer + offsets;
    postings (word → doc ids) are built lazily and cached as int32
    arrays. `sample` is the word2vec-style subsampling threshold used by
    `mini_batches` (reference LuceneInvertedIndex.java:521-527)."""

    def __init__(self, cache: Optional[VocabCache] = None,
                 sample: float = 0.0, seed: int = 0):
        self.cache = cache or VocabCache()
        self._sample = float(sample)
        self._rng = np.random.RandomState(seed)
        self._tokens: List[np.ndarray] = []  # per-doc token-index arrays
        self._labels: Dict[int, List[str]] = {}
        self._postings: Optional[Dict[int, np.ndarray]] = None

    # ------------------------------------------------------------- build
    def _invalidate(self) -> None:
        self._postings = None

    def add_words_to_doc(self, doc: int, words: Sequence[str],
                         label: Optional[str] = None) -> None:
        """reference addWordsToDoc :124 (+label overload :150). Words not
        in the vocab cache are added with frequency counts."""
        idx = np.empty(len(words), dtype=np.int32)
        for i, w in enumerate(words):
            self.cache.add_token(w)  # creates on first sight, counts always
            idx[i] = self.cache.add_word_to_index(w)
        while doc >= len(self._tokens):
            self._tokens.append(np.empty(0, dtype=np.int32))
        self._tokens[doc] = np.concatenate([self._tokens[doc], idx])
        if label is not None:
            self.add_label_for_doc(doc, label)
        self._invalidate()

    def add_label_for_doc(self, doc: int, label: str) -> None:
        self._labels.setdefault(doc, [])
        if label not in self._labels[doc]:
            self._labels[doc].append(label)

    # ------------------------------------------------------------- reads
    def num_documents(self) -> int:
        return len(self._tokens)

    def all_docs(self) -> np.ndarray:
        """reference allDocs — every document id."""
        return np.arange(len(self._tokens), dtype=np.int32)

    def document(self, index: int) -> List[str]:
        """Words of one document (reference document :74)."""
        return [self.cache.word_at(int(i))
                for i in self._tokens[index]]

    def document_indices(self, index: int) -> np.ndarray:
        """TPU-friendly variant: the raw int32 token-index array."""
        return self._tokens[index]

    def document_with_label(self, index: int) -> Tuple[List[str], Optional[str]]:
        labels = self._labels.get(index, [])
        return self.document(index), (labels[0] if labels else None)

    def document_with_labels(self, index: int) -> Tuple[List[str], List[str]]:
        return self.document(index), list(self._labels.get(index, []))

    def documents(self, word: str) -> np.ndarray:
        """Doc ids containing `word` (reference documents :98)."""
        if self._postings is None:
            postings: Dict[int, list] = {}
            for doc, toks in enumerate(self._tokens):
                for w in np.unique(toks):
                    postings.setdefault(int(w), []).append(doc)
            self._postings = {w: np.asarray(d, dtype=np.int32)
                              for w, d in postings.items()}
        widx = self.cache.index_of(word)
        return self._postings.get(widx, np.empty(0, dtype=np.int32))

    def sample(self) -> float:
        """Subsampling threshold (reference sample :62)."""
        return self._sample

    # ------------------------------------------------------------ batches
    def docs(self) -> Iterator[List[str]]:
        """Iterate documents as word lists (reference docs :45)."""
        for i in range(len(self._tokens)):
            yield self.document(i)

    def batch_iter(self, batch_size: int) -> Iterator[List[List[str]]]:
        """Iterate documents in batches (reference batchIter :40)."""
        batch: List[List[str]] = []
        for doc in self.docs():
            batch.append(doc)
            if len(batch) >= batch_size:
                yield batch
                batch = []
        if batch:
            yield batch

    def _keep_prob(self, counts: np.ndarray) -> np.ndarray:
        """Word2vec subsampling keep-probability per token (reference
        LuceneInvertedIndex.java:521-527: `(sqrt(f/(sample*N)) + 1) *
        sample*N/f`, clipped to [0, 1])."""
        n = max(1, self.num_documents())
        thresh = self._sample * n
        with np.errstate(divide="ignore", invalid="ignore"):
            ratio = (np.sqrt(counts / thresh) + 1.0) * thresh / counts
        return np.clip(np.nan_to_num(ratio, nan=1.0, posinf=1.0), 0.0, 1.0)

    def mini_batches(self, batch_size: int = 128) -> Iterator[List[str]]:
        """Subsampled word mini-batches for embedding training (reference
        miniBatches :68 + the builder at LuceneInvertedIndex.java:507-540).
        Frequent words are dropped with the word2vec subsampling formula
        when `sample > 0`; with sample == 0 every token passes."""
        counts = np.asarray(
            [self.cache.word_frequency(self.cache.word_at(i))
             for i in range(self.cache.num_words())], dtype=np.float64)
        batch: List[str] = []
        for toks in self._tokens:
            if len(toks) == 0:
                continue
            if self._sample > 0:
                keep = self._keep_prob(counts[toks])
                mask = self._rng.random_sample(len(toks)) < keep
                kept = toks[mask]
            else:
                kept = toks
            for widx in kept:
                batch.append(self.cache.word_at(int(widx)))
                if len(batch) >= batch_size:
                    yield batch
                    batch = []
        if batch:
            yield batch

    # ----------------------------------------------------------- lifecycle
    def unlock(self) -> None:
        """reference unlock :50 — Lucene write-lock release; no-op here."""

    def cleanup(self) -> None:
        """reference cleanup :55 — drop the index contents."""
        self._tokens.clear()
        self._labels.clear()
        self._invalidate()
