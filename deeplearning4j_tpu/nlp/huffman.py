"""Huffman coding over the vocabulary.

Parity: reference nlp/models/word2vec/Huffman.java — build the binary
Huffman tree over word frequencies; each VocabWord gets `codes` (the 0/1
path bits) and `points` (the inner-node indices along the path), consumed
by hierarchical softmax. Inner nodes are numbered so syn1 rows can be
indexed directly by `point` (word2vec convention: inner node i -> row i).
"""

from __future__ import annotations

import heapq
from itertools import count
from typing import List

from deeplearning4j_tpu.nlp.vocab import VocabCache

MAX_CODE_LENGTH = 40


def build_huffman(cache: VocabCache) -> None:
    """Assign codes/points to every indexed word, in place."""
    words = cache.vocab_words()
    n = len(words)
    if n == 0:
        return
    if n == 1:
        words[0].codes, words[0].points = [0], [0]
        return

    tie = count()
    # heap items: (count, tiebreak, node); leaf nodes are VocabWord indices,
    # inner nodes get ids n, n+1, ... (word2vec convention)
    heap = [(vw.count, next(tie), ("leaf", vw.index)) for vw in words]
    heapq.heapify(heap)
    next_inner = 0
    children = {}  # inner id -> (left node, right node)
    while len(heap) > 1:
        c1, _, n1 = heapq.heappop(heap)
        c2, _, n2 = heapq.heappop(heap)
        inner = ("inner", next_inner)
        next_inner += 1
        children[inner[1]] = (n1, n2)
        heapq.heappush(heap, (c1 + c2, next(tie), inner))
    root = heap[0][2]

    # Walk down, accumulating (codes, points). points are inner-node ids.
    stack = [(root, [], [])]
    while stack:
        node, codes, points = stack.pop()
        kind, idx = node
        if kind == "leaf":
            vw = words[idx]  # words list is ordered by index (vocab_words())
            vw.codes = codes[:MAX_CODE_LENGTH]
            vw.points = points[:MAX_CODE_LENGTH]
            continue
        left, right = children[idx]
        stack.append((left, codes + [0], points + [idx]))
        stack.append((right, codes + [1], points + [idx]))


def max_code_length(cache: VocabCache) -> int:
    return max((vw.code_length() for vw in cache.vocab_words()), default=0)
