"""Sentiment-lexicon scoring.

Parity: reference `text/corpora/sentiwordnet/SWN3.java` — a
SentiWordNet-backed polarity scorer used to label moving-window text:
per-word score = sense-rank-weighted (pos − neg) average
(weight 1/rank, normalized by the harmonic sum over all slots up to
the max rank, SWN3.java:106-118),
sentence score = sum of token scores with a sign flip when any negation
word is present (scoreTokens :174-190), and score -> class bands
(classForScore :149-165). The UIMA tokenizer plumbing is replaced by
plain token lists; the band comparisons are implemented as MONOTONE
intervals — the reference's chain (`score > 0 && score >= 0.25` for
"weak_positive", overlapping "positive" bounds) drops/garbles
conditions the same way its Viterbi dropped parentheses; the intended
banding is reproduced, not the bug.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, Iterable, List, Sequence

#: SWN3.java:50 — presence of any of these flips the sentence polarity
NEGATION_WORDS = frozenset([
    "could", "would", "should", "not", "isn't", "aren't", "wasn't",
    "weren't", "haven't", "doesn't", "didn't", "don't",
])


def class_for_score(score: float) -> str:
    """Monotone banding of the reference's classForScore intent."""
    if score >= 0.75:
        return "strong_positive"
    if score >= 0.25:
        return "positive"
    if score > 0:
        return "weak_positive"
    if score == 0:
        return "neutral"
    if score > -0.25:
        return "weak_negative"
    if score > -0.75:
        return "negative"
    return "strong_negative"


class SentimentLexicon:
    """word -> polarity score in [-1, 1]; scores/classifies token
    sequences with the SWN3 negation-flip rule."""

    def __init__(self, scores: Dict[str, float],
                 negation_words: Iterable[str] = NEGATION_WORDS):
        self.scores = {w.lower(): float(s) for w, s in scores.items()}
        self.negation_words = frozenset(negation_words)

    # ------------------------------------------------------------ lookup
    def extract(self, word: str) -> float:
        """Score for one token; 0 for out-of-lexicon words. Keys of the
        form `word#pos` (SentiWordNet) are aggregated across PoS at
        load time, so bare-token lookup works."""
        return self.scores.get(word.lower(), 0.0)

    # ----------------------------------------------------------- scoring
    def score_tokens(self, tokens: Sequence[str]) -> float:
        """Sum of token scores; sign flipped when any negation word
        appears (reference scoreTokens: 'flip for context')."""
        s = sum(self.extract(t) for t in tokens)
        if any(t.lower() in self.negation_words for t in tokens):
            s = -s
        return s

    def classify_tokens(self, tokens: Sequence[str]) -> str:
        return class_for_score(self.score_tokens(tokens))

    # ----------------------------------------------------------- loading
    @classmethod
    def from_sentiwordnet(cls, path: str) -> "SentimentLexicon":
        """Parse the SentiWordNet 3.0 TSV format the reference shipped:
        `pos \\t id \\t posScore \\t negScore \\t word#rank [word#rank...]`.
        Per (word, pos): score = sum_i (1/(rank_i)) * (pos-neg)_i
        normalized by the harmonic sum over ranks (SWN3.java:106-118);
        the bare word's score averages its per-PoS scores so token-level
        lookup needs no tagger."""
        per_sense: Dict[str, Dict[int, float]] = defaultdict(dict)
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.split("\t")
                if len(parts) < 5 or not parts[2] or not parts[3]:
                    continue
                pos = parts[0]
                score = float(parts[2]) - float(parts[3])
                for token in parts[4].split(" "):
                    if not token or "#" not in token:
                        continue
                    word, rank = token.rsplit("#", 1)
                    try:
                        r = int(rank)
                    except ValueError:
                        continue
                    if r < 1:  # rank-0 would divide by zero below;
                        continue  # skip like other malformed entries
                    per_sense[f"{word}#{pos}"][r] = score

        scores: Dict[str, float] = {}
        by_word: Dict[str, List[float]] = defaultdict(list)
        for key, senses in per_sense.items():
            num = sum(s / r for r, s in senses.items())
            # the reference normalizes by the harmonic sum over ALL
            # slots up to the max rank — absent senses score 0 but
            # still count in the denominator (SWN3.java:112-116)
            den = sum(1.0 / i for i in range(1, max(senses) + 1))
            val = num / den if den else 0.0
            scores[key] = val
            by_word[key.rsplit("#", 1)[0]].append(val)
        for word, vals in by_word.items():
            scores.setdefault(word, sum(vals) / len(vals))
        return cls(scores)


__all__ = ["SentimentLexicon", "class_for_score", "NEGATION_WORDS"]
