"""Vocabulary cache.

Parity: reference nlp/models/word2vec/wordstore/ — `VocabWord` (word +
count + Huffman codes/points), `VocabCache`/`InMemoryLookupCache` (word ->
index, counts, doc frequencies) and the vocab-building pass of
`TextVectorizer`/`VocabActor` (tokenize sentences, count, apply
min-word-frequency). The actor-based parallel counting collapses to a
single host pass — counting is IO-bound, not the TPU's job.
"""

from __future__ import annotations

from collections import Counter
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional


@dataclass
class VocabWord:
    word: str
    count: float = 1.0
    index: int = -1
    codes: List[int] = field(default_factory=list)
    points: List[int] = field(default_factory=list)

    def code_length(self) -> int:
        return len(self.codes)


class VocabCache:
    """Word store (reference InMemoryLookupCache)."""

    def __init__(self):
        self._words: Dict[str, VocabWord] = {}
        self._index: List[str] = []
        self.total_word_count = 0.0
        self.num_docs = 0
        self._doc_freq: Counter = Counter()

    # ------------------------------------------------------------ building
    def add_token(self, word: str, by: float = 1.0) -> VocabWord:
        vw = self._words.get(word)
        if vw is None:
            vw = VocabWord(word=word, count=0.0)
            self._words[word] = vw
        vw.count += by
        self.total_word_count += by
        return vw

    def add_word_to_index(self, word: str) -> int:
        vw = self._words[word]
        if vw.index < 0:
            vw.index = len(self._index)
            self._index.append(word)
        return vw.index

    def increment_doc_count(self, words: Iterable[str]) -> None:
        self.num_docs += 1
        self._doc_freq.update(set(words))

    def doc_frequency(self, word: str) -> int:
        return self._doc_freq[word]

    # ------------------------------------------------------------- queries
    def contains(self, word: str) -> bool:
        return word in self._words

    def word_for(self, word: str) -> Optional[VocabWord]:
        return self._words.get(word)

    def word_at(self, index: int) -> str:
        return self._index[index]

    def index_of(self, word: str) -> int:
        vw = self._words.get(word)
        return vw.index if vw else -1

    def word_frequency(self, word: str) -> float:
        vw = self._words.get(word)
        return vw.count if vw else 0.0

    def num_words(self) -> int:
        return len(self._index)

    def words(self) -> List[str]:
        return list(self._index)

    def vocab_words(self) -> List[VocabWord]:
        return [self._words[w] for w in self._index]

    # -------------------------------------------------------- serialization
    def to_dict(self) -> Dict:
        """JSON-able snapshot (index order, counts, Huffman codes/points) —
        the distributed wire format for shipping a built vocab to worker
        processes (reference Word2VecWork carries the vocab words)."""
        return {
            "words": [{"w": vw.word, "c": vw.count,
                       "codes": list(vw.codes), "points": list(vw.points)}
                      for vw in self.vocab_words()],
            "total_word_count": self.total_word_count,
            "num_docs": self.num_docs,
        }

    @classmethod
    def from_dict(cls, data: Dict) -> "VocabCache":
        cache = cls()
        for i, rec in enumerate(data["words"]):
            vw = VocabWord(word=rec["w"], count=rec["c"], index=i,
                           codes=[int(c) for c in rec["codes"]],
                           points=[int(p) for p in rec["points"]])
            cache._words[vw.word] = vw
            cache._index.append(vw.word)
        cache.total_word_count = data.get("total_word_count", 0.0)
        cache.num_docs = data.get("num_docs", 0)
        return cache

    def truncate(self, min_word_frequency: float) -> None:
        """Drop words below the frequency floor and re-index by descending
        count (word2vec convention: index 0 = most frequent)."""
        kept = {w: vw for w, vw in self._words.items()
                if vw.count >= min_word_frequency}
        self._words = kept
        ordered = sorted(kept.values(), key=lambda v: -v.count)
        self._index = []
        for vw in ordered:
            vw.index = len(self._index)
            self._index.append(vw.word)


def build_vocab(sentences, tokenizer_factory, min_word_frequency: float = 1.0,
                cache: Optional[VocabCache] = None) -> VocabCache:
    """Tokenize + count + truncate (reference Word2Vec.buildVocab :257)."""
    cache = cache or VocabCache()
    for sentence in sentences:
        toks = tokenizer_factory.tokenize(sentence)
        if not toks:
            continue
        cache.increment_doc_count(toks)
        for t in toks:
            cache.add_token(t)
    cache.truncate(min_word_frequency)
    return cache
