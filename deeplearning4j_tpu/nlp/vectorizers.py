"""Text vectorizers: bag-of-words and TF-IDF.

Parity: reference nlp/bagofwords/vectorizer/ — `BagOfWordsVectorizer` /
`TfidfVectorizer` over a VocabCache (BaseTextVectorizer.java:278: tokenize,
count, emit document vectors + label). Emits dense numpy document-term
matrices ready to feed MultiLayerNetwork.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory,
    TokenizerFactory,
)
from deeplearning4j_tpu.nlp.vocab import VocabCache, build_vocab
from deeplearning4j_tpu.utils import math_utils


class BagOfWordsVectorizer:
    def __init__(self, min_word_frequency: float = 1.0,
                 tokenizer_factory: Optional[TokenizerFactory] = None):
        self.min_word_frequency = min_word_frequency
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        self.vocab = VocabCache()

    def fit(self, documents: Iterable[str]) -> "BagOfWordsVectorizer":
        build_vocab(documents, self.tokenizer_factory,
                    self.min_word_frequency, self.vocab)
        return self

    def _weight(self, count: float, word: str) -> float:
        return count

    def transform(self, documents: Sequence[str]) -> np.ndarray:
        v = self.vocab.num_words()
        out = np.zeros((len(documents), v), np.float32)
        for row, doc in enumerate(documents):
            for t in self.tokenizer_factory.tokenize(doc):
                i = self.vocab.index_of(t)
                if i >= 0:
                    out[row, i] += 1.0
            for i in np.nonzero(out[row])[0]:
                out[row, i] = self._weight(out[row, i],
                                           self.vocab.word_at(int(i)))
        return out

    def fit_transform(self, documents: Sequence[str]) -> np.ndarray:
        return self.fit(documents).transform(documents)


class TfidfVectorizer(BagOfWordsVectorizer):
    """TF-IDF weighting via the MathUtils-parity helpers, matching the
    reference exactly: `MathUtils.tfidf(tf, idf)` with log10-scaled term
    frequency and `log10(numDocs / (1 + docFreq))` inverse document
    frequency (reference TfidfVectorizer.java:63-73 → MathUtils.java)."""

    def _weight(self, count: float, word: str) -> float:
        return math_utils.tfidf(
            math_utils.tf(int(count)),
            math_utils.idf(self.vocab.num_docs,
                           self.vocab.doc_frequency(word)))
