"""Word2Vec: skip-gram with hierarchical softmax and negative sampling.

Parity: reference nlp/models/word2vec/Word2Vec.java (fit :101, buildVocab
:257, trainSentence :298, skipGram :314, iterate :337, lr decay by words
seen :191-296) + InMemoryLookupTable.java (syn0/syn1/syn1Neg, unigram
table resetWeights :88, iterateSample :188-260) + WordVectorsImpl
(similarity / wordsNearest).

TPU-native design: the reference's hot loop does ONE (dot, sigmoid, axpy)
at a time per (center, context, code-bit), racing hogwild threads over
shared syn0/syn1. Here the host mines (center, context) pairs + their
Huffman codes/points into padded index tensors, and a single jitted step
computes the batch loss:

    HS:  BCE over dot(syn0[context], syn1[points]) against (1 - codes)
    NEG: BCE over dot(syn0[context], syn1neg[target|negatives])

jax.grad turns the gathers into scatter-adds — a deterministic segment-sum
formulation of the same update (colliding pairs ACCUMULATE instead of
racing), running on the MXU over thousands of pairs at once. Negative
samples are drawn on-device from the unigram^0.75 table via
jax.random.categorical over precomputed logits.
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.huffman import build_huffman, max_code_length
from deeplearning4j_tpu.nlp.sentence_iterator import (
    CollectionSentenceIterator,
    SentenceIterator,
)
from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory,
    TokenizerFactory,
)
from deeplearning4j_tpu.nlp.vocab import VocabCache, build_vocab

log = logging.getLogger(__name__)


def _prefetch(iterator, depth: int = 2):
    """Run a chunk producer in a background thread so host-side pair
    mining overlaps device training (the reference overlaps via its
    parallel sentence-training threads, Word2Vec.java:191). Exceptions
    propagate to the consumer."""
    q: "queue.Queue" = queue.Queue(maxsize=depth)
    _DONE, _ERR = object(), object()

    def produce():
        try:
            for item in iterator:
                q.put(item)
            q.put(_DONE)
        except BaseException as e:  # noqa: BLE001 — relay to consumer
            q.put((_ERR, e))

    t = threading.Thread(target=produce, name="w2v-miner", daemon=True)
    t.start()
    while True:
        item = q.get()
        if item is _DONE:
            return
        if isinstance(item, tuple) and len(item) == 2 and item[0] is _ERR:
            raise item[1]
        yield item


class WordVectors:
    """Similarity / nearest-words API over the learned table
    (reference WordVectorsImpl.java)."""

    def __init__(self, cache: VocabCache, syn0: np.ndarray):
        self.vocab = cache
        self.syn0 = np.asarray(syn0)
        norms = np.linalg.norm(self.syn0, axis=1, keepdims=True)
        self._unit = self.syn0 / np.maximum(norms, 1e-12)

    def _require_fitted(self) -> None:
        if getattr(self, "syn0", None) is None \
                or getattr(self, "_unit", None) is None:
            raise RuntimeError(
                f"{type(self).__name__} has no trained vectors yet — "
                "call fit() first")

    def get_word_vector(self, word: str) -> Optional[np.ndarray]:
        self._require_fitted()
        i = self.vocab.index_of(word)
        return self.syn0[i] if i >= 0 else None

    def has_word(self, word: str) -> bool:
        return self.vocab.index_of(word) >= 0

    def similarity(self, w1: str, w2: str) -> float:
        self._require_fitted()
        i, j = self.vocab.index_of(w1), self.vocab.index_of(w2)
        if i < 0 or j < 0:
            return float("nan")
        return float(self._unit[i] @ self._unit[j])

    def words_nearest(self, word: str, n: int = 10) -> List[Tuple[str, float]]:
        self._require_fitted()
        i = self.vocab.index_of(word)
        if i < 0:
            return []
        sims = self._unit @ self._unit[i]
        order = np.argsort(-sims)
        out = []
        for j in order:
            if j == i:
                continue
            out.append((self.vocab.word_at(int(j)), float(sims[j])))
            if len(out) >= n:
                break
        return out


class Word2Vec(WordVectors):
    """Skip-gram trainer (builder-style kwargs mirror the reference's
    Word2Vec.Builder: layerSize/windowSize/minWordFrequency/iterations/
    learningRate/minLearningRate/negativeSample/sample/seed)."""

    def __init__(self, sentences=None, *, layer_size: int = 100,
                 window: int = 5, min_word_frequency: float = 1.0,
                 iterations: int = 1, learning_rate: float = 0.025,
                 min_learning_rate: float = 1e-4, negative: int = 0,
                 sample: float = 0.0, batch_pairs: int = 4096,
                 chunk_batches: int = 32, seed: int = 123,
                 tokenizer_factory: Optional[TokenizerFactory] = None):
        self.layer_size = layer_size
        self.window = window
        self.min_word_frequency = min_word_frequency
        self.iterations = iterations
        self.alpha = learning_rate
        self.min_alpha = min_learning_rate
        self.negative = negative
        self.sample = sample
        self.batch_pairs = batch_pairs
        self.chunk_batches = chunk_batches  # scan length of the chunk step
        self.seed = seed
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        if isinstance(sentences, SentenceIterator):
            self.sentence_iter = sentences
        elif sentences is not None:
            self.sentence_iter = CollectionSentenceIterator(list(sentences))
        else:
            self.sentence_iter = None
        self.vocab = VocabCache()
        self.syn0 = None
        self.syn1 = None
        self.syn1neg = None
        self._code_len = 0
        self.pairs_trained = 0
        self._step_cache = None  # jitted step, keyed to the built vocab
        self._key = jax.random.PRNGKey(seed)

    # ----------------------------------------------------------- vocab/init
    def build_vocab(self) -> None:
        """reference buildVocab :257 + Huffman(vocab).build() :348."""
        build_vocab(self.sentence_iter, self.tokenizer_factory,
                    self.min_word_frequency, self.vocab)
        self._extend_vocab()  # hook: subclasses add pseudo-words (labels)
        build_huffman(self.vocab)
        self._code_len = max(1, max_code_length(self.vocab))
        self._step_cache = None  # vocab-dependent shapes changed

    def _extend_vocab(self) -> None:
        pass

    def reset_weights(self) -> None:
        """reference InMemoryLookupTable.resetWeights :88: syn0 uniform in
        +-0.5/dim, syn1 zeros."""
        n, d = self.vocab.num_words(), self.layer_size
        self._key, k = jax.random.split(self._key)
        self.syn0 = jax.random.uniform(k, (n, d), jnp.float32,
                                       -0.5 / d, 0.5 / d)
        if self.negative > 0:
            self.syn1neg = jnp.zeros((n, d), jnp.float32)
        else:  # hierarchical softmax path
            self.syn1 = jnp.zeros((n, d), jnp.float32)

    UNIGRAM_TABLE_SIZE = 1 << 20

    def _unigram_table(self) -> jnp.ndarray:
        """unigram^0.75 sampling table (the reference's unigram table,
        InMemoryLookupTable's `table` — 1e8 entries there, 2^20 here):
        table[i] = word index owning cdf bucket i, so drawing a negative
        is ONE random int + ONE gather. On TPU this beats both
        jax.random.categorical (which materializes (B, K, V) Gumbel
        noise — 20+ ms/step at V=10k, B=16k) and jnp.searchsorted
        (~12 ms/step); the table gather is ~0.1 ms. Quantization at
        2^-20 granularity matches the reference's quantized table."""
        counts = np.array([vw.count for vw in self.vocab.vocab_words()],
                          np.float64)
        probs = counts ** 0.75
        probs /= probs.sum()
        cdf = np.cumsum(probs)
        t = self.UNIGRAM_TABLE_SIZE
        # bucket midpoints -> owning word index
        table = np.searchsorted(cdf, (np.arange(t) + 0.5) / t)
        return jnp.asarray(np.minimum(table, len(cdf) - 1), jnp.int32)

    # ------------------------------------------------------------- training
    def _codes_points(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Pad per-word Huffman codes/points to (V, L) with a mask."""
        v, L = self.vocab.num_words(), self._code_len
        codes = np.zeros((v, L), np.float32)
        points = np.zeros((v, L), np.int32)
        mask = np.zeros((v, L), np.float32)
        for vw in self.vocab.vocab_words():
            ln = vw.code_length()
            codes[vw.index, :ln] = vw.codes
            points[vw.index, :ln] = vw.points
            mask[vw.index, :ln] = 1.0
        return codes, points, mask

    def _keep_probs(self) -> np.ndarray:
        """Per-vocab-index subsampling keep probability (reference
        trainSentence's frequent-word subsampling, vectorized as a table)."""
        total = max(1.0, self.vocab.total_word_count)
        counts = np.array([vw.count for vw in self.vocab.vocab_words()],
                          np.float64)
        f = np.maximum(counts, 1.0) / total
        keep = (np.sqrt(f / self.sample) + 1.0) * self.sample / f
        return np.minimum(keep, 1.0)

    def _tokens_to_indices(self, sentence: str) -> np.ndarray:
        toks = self.tokenizer_factory.tokenize(sentence)
        idx = np.fromiter((self.vocab.index_of(t) for t in toks),
                          np.int32, count=len(toks))
        return idx[idx >= 0]

    @staticmethod
    def _window_pairs(idx: np.ndarray, sid: np.ndarray, window: int,
                      rng: np.random.RandomState
                      ) -> Tuple[np.ndarray, np.ndarray]:
        """Vectorized skip-gram windowing over concatenated sentences.

        `idx` holds vocab indices, `sid` the sentence id of each position.
        For every offset 1..window, pairs (center@i, context@i±off) are
        kept when both positions share a sentence and off <= b[i], where b
        is the per-center random window shrink (reference skipGram :314's
        `b = random % window` semantics) — no Python per-token loop.
        """
        n = idx.size
        if n == 0:
            return (np.empty(0, np.int32),) * 2
        b = rng.randint(1, window + 1, size=n)
        cs, xs = [], []
        for off in range(1, window + 1):
            if off >= n:
                break
            same = sid[off:] == sid[:-off]
            m = same & (b[off:] >= off)      # context BEFORE center
            cs.append(idx[off:][m])
            xs.append(idx[:-off][m])
            m = same & (b[:-off] >= off)     # context AFTER center
            cs.append(idx[:-off][m])
            xs.append(idx[off:][m])
        return np.concatenate(cs), np.concatenate(xs)

    def _iter_pair_chunks(self, rng: np.random.RandomState,
                          chunk_tokens: int = 1 << 18
                          ):
        """Stream (centers, contexts, words_seen) chunks: sentences are
        tokenized and buffered up to ~chunk_tokens indices, then windowed
        in one vectorized shot. A text8-scale corpus (~17M tokens, ~1e8
        pairs at window 5) never materializes more than one chunk of pairs
        (~2.6M) in RAM. Overridable (ParagraphVectors appends label pairs).
        """
        keep = self._keep_probs() if self.sample > 0 else None
        buf_idx: List[np.ndarray] = []
        buf_sid: List[np.ndarray] = []
        count = 0
        sid = 0

        words_in_buf = 0  # in-vocab tokens BEFORE subsampling: the alpha
        # decay numerator must count the same mass as its denominator
        # (sum of kept-vocab counts), which subsampling doesn't reduce

        def flush():
            idx = np.concatenate(buf_idx)
            s = np.concatenate(buf_sid)
            c, x = self._window_pairs(idx, s, self.window, rng)
            return c, x, words_in_buf

        for sentence in self.sentence_iter:
            arr = self._tokens_to_indices(sentence)
            words_in_buf += arr.size
            if keep is not None and arr.size:
                arr = arr[rng.rand(arr.size) < keep[arr]]
            if arr.size:
                buf_idx.append(arr)
                buf_sid.append(np.full(arr.size, sid, np.int32))
                count += arr.size
                sid += 1
            if count >= chunk_tokens:
                yield flush()
                buf_idx, buf_sid, count, words_in_buf = [], [], 0, 0
        if count:
            yield flush()

    def _build_step(self):
        codes, points, mask = self._codes_points()
        codes_t, points_t, mask_t = (jnp.asarray(codes), jnp.asarray(points),
                                     jnp.asarray(mask))
        negative = self.negative
        uni_table = self._unigram_table() if negative > 0 else None

        def _bce(logits, labels):
            return (jnp.maximum(logits, 0) - logits * labels
                    + jnp.log1p(jnp.exp(-jnp.abs(logits))))

        def loss_fn(tables, centers, contexts, negs):
            """Batched equivalent of the reference's sequential per-pair
            axpy updates. Each ROW (of syn0 OR syn1/syn1neg) moves by the
            MEAN gradient over the pairs touching it in this batch, at the
            full per-pair alpha: a plain sum diverges whenever a hot row
            (small vocab; the HS root node; frequent negative targets)
            accumulates thousands of same-direction gradients that the
            reference's re-read-each-step loop would have saturated, while
            a plain mean scales the effective lr by 1/batch_pairs. The
            two sides need different normalizations, so the loss is split
            with stop_gradient: the first term only trains syn0, the
            second only trains syn1/syn1neg."""
            syn0 = tables["syn0"]
            l1 = syn0[contexts]  # (B, D) — reference trains syn0[context]
            l1_sg = jax.lax.stop_gradient(l1)
            counts = jnp.zeros(syn0.shape[0],
                               jnp.float32).at[contexts].add(1.0)
            w = 1.0 / counts[contexts]  # (B,) syn0-side weights
            loss = 0.0
            if "syn1" in tables:
                # hierarchical softmax over the center word's code path
                p = points_t[centers]          # (B, L)
                c = codes_t[centers]           # (B, L)
                m = mask_t[centers]            # (B, L)
                labels = 1.0 - c               # word2vec label convention
                rows = tables["syn1"][p]       # (B, L, D)
                pc = jnp.zeros(tables["syn1"].shape[0],
                               jnp.float32).at[p].add(m)
                u = m / jnp.maximum(pc[p], 1.0)  # (B, L) syn1-side weights
                syn0_side = _bce(
                    jnp.einsum("bd,bld->bl", l1,
                               jax.lax.stop_gradient(rows)), labels)
                syn1_side = _bce(
                    jnp.einsum("bd,bld->bl", l1_sg, rows), labels)
                loss = loss + jnp.sum(w[:, None] * syn0_side * m) \
                    + jnp.sum(u * syn1_side * m)
            if "syn1neg" in tables:
                tgt = jnp.concatenate([centers[:, None], negs], axis=1)
                labels = jnp.concatenate(
                    [jnp.ones_like(centers[:, None], jnp.float32),
                     jnp.zeros_like(negs, jnp.float32)], axis=1)
                # mask negatives that drew the positive target itself
                # (reference: `if (target == word) continue`)
                valid = jnp.concatenate(
                    [jnp.ones_like(centers[:, None], jnp.float32),
                     (negs != centers[:, None]).astype(jnp.float32)], axis=1)
                rows = tables["syn1neg"][tgt]  # (B, K, D)
                tc = jnp.zeros(tables["syn1neg"].shape[0],
                               jnp.float32).at[tgt].add(valid)
                u = valid / jnp.maximum(tc[tgt], 1.0)
                syn0_side = _bce(
                    jnp.einsum("bd,bkd->bk", l1,
                               jax.lax.stop_gradient(rows)), labels)
                syn1_side = _bce(
                    jnp.einsum("bd,bkd->bk", l1_sg, rows), labels)
                loss = loss + jnp.sum(w[:, None] * syn0_side * valid) \
                    + jnp.sum(u * syn1_side * valid)
            return loss

        def step_core(tables, centers, contexts, alpha, key):
            if negative > 0:
                draws = jax.random.randint(
                    key, (centers.shape[0], negative), 0,
                    uni_table.shape[0])
                negs = uni_table[draws]
            else:
                negs = jnp.zeros((centers.shape[0], 0), jnp.int32)
            loss, grads = jax.value_and_grad(loss_fn)(
                tables, centers, contexts, negs)
            tables = jax.tree_util.tree_map(
                lambda t, g: t - alpha * g, tables, grads)
            return tables, loss

        step = jax.jit(step_core)

        # Whole-chunk training as one program: batches are a scan axis, so
        # the per-batch host work (two H2D transfers + RNG split + dispatch,
        # ~25 ms/batch over a tunneled chip) is paid once per CHUNK. This
        # kernel is gather-bound, not MXU-bound, so scanning costs nothing
        # (unlike the dense-MLP case — see MultiLayerNetwork.fit_scan).
        @jax.jit
        def step_chunk(tables, cb, xb, alpha, key):
            keys = jax.random.split(key, cb.shape[0])

            def body(tables, inp):
                c, x, k = inp
                return step_core(tables, c, x, alpha, k)

            tables, losses = jax.lax.scan(body, tables, (cb, xb, keys))
            return tables, losses[-1]

        return step, step_chunk

    # ---------------------------------------------------- pre-mined pairs
    def mine_pairs(self, rng=None):
        """Mine every (center, context) skip-gram pair for ONE corpus
        pass, as two int32 arrays. Public surface over the chunk miner
        for callers that reuse pairs across repeated training (resumed
        runs, benchmarks) instead of re-mining per fit()."""
        if self.vocab.num_words() == 0:
            self.build_vocab()
        rng = rng or np.random.RandomState(self.seed + 1)
        chunks = list(self._iter_pair_chunks(rng))
        if not chunks:
            return np.empty(0, np.int32), np.empty(0, np.int32)
        centers = np.concatenate([c for c, _, _ in chunks])
        contexts = np.concatenate([x for _, x, _ in chunks])
        return centers, contexts

    def train_pairs(self, centers, contexts, alpha: float = None) -> int:
        """Train on pre-mined pairs through the production chunked-scan
        step at a FIXED learning rate (callers own any decay schedule).
        Whole chunks (chunk_batches x batch_pairs) ride the scan; the
        tail trains in single batches (each an eager dispatch), dropping
        only the sub-batch remainder — unless the whole input is smaller
        than one batch, which is tiled up. Returns the number of pairs
        trained."""
        if self.syn0 is None:
            self.reset_weights()
        if self._step_cache is None:
            self._step_cache = self._build_step()
        step, step_chunk = self._step_cache
        alpha = self.alpha if alpha is None else float(alpha)
        tables = {"syn0": self.syn0}
        if self.syn1 is not None:
            tables["syn1"] = self.syn1
        if self.syn1neg is not None:
            tables["syn1neg"] = self.syn1neg
        # jnp.asarray is a no-op for device-resident int32 inputs, so
        # callers looping train_pairs can upload once and pay zero
        # host->device transfer per call (the tunnel's per-transfer
        # round trip would otherwise dominate)
        centers = jnp.asarray(centers, jnp.int32)
        contexts = jnp.asarray(contexts, jnp.int32)
        B, CB = self.batch_pairs, self.chunk_batches
        n = centers.size // (B * CB) * (B * CB)
        trained = 0
        if n:
            cb = centers[:n].reshape(-1, CB, B)
            xb = contexts[:n].reshape(-1, CB, B)
            for i in range(cb.shape[0]):
                self._key, k = jax.random.split(self._key)
                tables, _ = step_chunk(tables, cb[i], xb[i],
                                       jnp.float32(alpha), k)
            trained = n
        tail_c, tail_x = centers[n:], contexts[n:]
        for lo in range(0, tail_c.size // B * B, B):
            self._key, k = jax.random.split(self._key)
            tables, _ = step(tables, tail_c[lo:lo + B],
                             tail_x[lo:lo + B], jnp.float32(alpha), k)
            trained += B
        rem = tail_c.size % B
        if rem and trained == 0:
            # smaller than one batch: tile up so tiny inputs still train
            pad = jnp.arange(B - rem) % rem
            self._key, k = jax.random.split(self._key)
            tables, _ = step(
                tables, jnp.concatenate([tail_c[-rem:], tail_c[-rem:][pad]]),
                jnp.concatenate([tail_x[-rem:], tail_x[-rem:][pad]]),
                jnp.float32(alpha), k)
            trained = rem
        self.syn0 = tables["syn0"]
        self.syn1 = tables.get("syn1")
        self.syn1neg = tables.get("syn1neg")
        self.pairs_trained += trained
        # NOTE: the similarity/nearest-words view is NOT refreshed here
        # (that would D2H the whole table every call — train_pairs is
        # built for tight loops); call refresh_vectors() when done.
        return trained

    def refresh_vectors(self) -> None:
        """Pull syn0 to host and refresh the WordVectors view (after a
        train_pairs loop; fit() does this automatically)."""
        WordVectors.__init__(self, self.vocab, np.asarray(self.syn0))

    def fit(self) -> "Word2Vec":
        """reference fit :101: build vocab, Huffman, reset weights, train
        with lr decaying by words seen (Word2Vec.java :191-296's
        `alpha * (1 - wordsSeen/totalWords)`), streaming pair chunks so a
        text8-scale corpus trains in bounded memory."""
        if self.sentence_iter is None:
            raise ValueError("Word2Vec needs sentences")
        if self.vocab.num_words() == 0:
            self.build_vocab()
        if self.syn0 is None:
            self.reset_weights()
        rng = np.random.RandomState(self.seed)
        # the miner runs in a prefetch thread concurrently with the
        # training loop's permutation draws — it needs its OWN RandomState
        # (numpy RandomState is not thread-safe)
        mine_rng = np.random.RandomState(self.seed + 1)
        if self._step_cache is None:
            self._step_cache = self._build_step()
        step, step_chunk = self._step_cache

        tables = {"syn0": self.syn0}
        if self.syn1 is not None:
            tables["syn1"] = self.syn1
        if self.syn1neg is not None:
            tables["syn1neg"] = self.syn1neg

        # denominator = kept-vocab token mass (total_word_count still
        # includes mass truncate() dropped, which words_seen never counts —
        # using it would stall the decay well above min_alpha)
        kept_mass = sum(vw.count for vw in self.vocab.vocab_words())
        total_words = max(1.0, float(kept_mass) * self.iterations)
        words_seen = 0
        self.pairs_trained = 0
        loss = None
        B = self.batch_pairs
        carry_c = np.empty(0, np.int32)
        carry_x = np.empty(0, np.int32)

        def train_batch(bc, bx, ts):
            nonlocal tables
            self._key, k = jax.random.split(self._key)
            alpha = max(self.min_alpha,
                        self.alpha * (1.0 - words_seen / total_words))
            ts, ls = step(ts, jnp.asarray(bc), jnp.asarray(bx),
                          jnp.float32(alpha), k)
            return ts, ls

        # fixed scan length => exactly two compiled programs all run long:
        # the CB-batch chunk scan and the single-batch tail step
        CB = self.chunk_batches

        def train_chunk(bc, bx, ts):
            nonlocal loss
            self._key, k = jax.random.split(self._key)
            alpha = max(self.min_alpha,
                        self.alpha * (1.0 - words_seen / total_words))
            cb = jnp.asarray(bc.reshape(CB, B))
            xb = jnp.asarray(bx.reshape(CB, B))
            ts, loss = step_chunk(ts, cb, xb, jnp.float32(alpha), k)
            return ts

        for _ in range(self.iterations):
            for centers, contexts, n_words in _prefetch(
                    self._iter_pair_chunks(mine_rng)):
                self.pairs_trained += centers.size
                perm = rng.permutation(centers.size)
                centers = np.concatenate([carry_c, centers[perm]])
                contexts = np.concatenate([carry_x, contexts[perm]])
                lo = 0
                while centers.size - lo >= CB * B:
                    # one program per CB batches: batches are a scan axis,
                    # so per-batch host overhead (transfers + dispatch) is
                    # paid once per CB steps. Alpha is constant across the
                    # scan (decay advances per mined chunk, as before).
                    tables = train_chunk(centers[lo:lo + CB * B],
                                         contexts[lo:lo + CB * B], tables)
                    lo += CB * B
                # remainder rides into the next chunk, keeping every
                # compiled shape static
                carry_c, carry_x = centers[lo:], contexts[lo:]
                # decay lags the chunk (the reference decays by words
                # ALREADY seen) so the first batch trains at full alpha and
                # the last iteration is not spent at min_alpha
                words_seen += n_words
            # iteration tail: full batches through the single-batch step,
            # then tile the final partial batch up to the batch shape
            n_full = carry_c.size // B * B
            for lo in range(0, n_full, B):
                tables, loss = train_batch(carry_c[lo:lo + B],
                                           carry_x[lo:lo + B], tables)
            carry_c, carry_x = carry_c[n_full:], carry_x[n_full:]
            if carry_c.size:
                pad = np.arange(B - carry_c.size) % carry_c.size
                tables, loss = train_batch(
                    np.concatenate([carry_c, carry_c[pad]]),
                    np.concatenate([carry_x, carry_x[pad]]), tables)
                carry_c = np.empty(0, np.int32)
                carry_x = np.empty(0, np.int32)
        if self.pairs_trained == 0:
            raise ValueError("No training pairs (vocab/corpus too small)")
        self.syn0 = tables["syn0"]
        self.syn1 = tables.get("syn1")
        self.syn1neg = tables.get("syn1neg")
        log.info("word2vec trained: %d pairs, final loss %.4f",
                 self.pairs_trained, float(loss))
        # refresh the WordVectors view
        WordVectors.__init__(self, self.vocab, np.asarray(self.syn0))
        return self
