"""Word2Vec: skip-gram with hierarchical softmax and negative sampling.

Parity: reference nlp/models/word2vec/Word2Vec.java (fit :101, buildVocab
:257, trainSentence :298, skipGram :314, iterate :337, lr decay by words
seen :191-296) + InMemoryLookupTable.java (syn0/syn1/syn1Neg, unigram
table resetWeights :88, iterateSample :188-260) + WordVectorsImpl
(similarity / wordsNearest).

TPU-native design: the reference's hot loop does ONE (dot, sigmoid, axpy)
at a time per (center, context, code-bit), racing hogwild threads over
shared syn0/syn1. Here the host mines (center, context) pairs + their
Huffman codes/points into padded index tensors, and a single jitted step
computes the batch loss:

    HS:  BCE over dot(syn0[context], syn1[points]) against (1 - codes)
    NEG: BCE over dot(syn0[context], syn1neg[target|negatives])

jax.grad turns the gathers into scatter-adds — a deterministic segment-sum
formulation of the same update (colliding pairs ACCUMULATE instead of
racing), running on the MXU over thousands of pairs at once. Negative
samples are drawn on-device from the unigram^0.75 table via
jax.random.categorical over precomputed logits.
"""

from __future__ import annotations

import logging
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.huffman import build_huffman, max_code_length
from deeplearning4j_tpu.nlp.sentence_iterator import (
    CollectionSentenceIterator,
    SentenceIterator,
)
from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory,
    TokenizerFactory,
)
from deeplearning4j_tpu.nlp.vocab import VocabCache, build_vocab

log = logging.getLogger(__name__)


class WordVectors:
    """Similarity / nearest-words API over the learned table
    (reference WordVectorsImpl.java)."""

    def __init__(self, cache: VocabCache, syn0: np.ndarray):
        self.vocab = cache
        self.syn0 = np.asarray(syn0)
        norms = np.linalg.norm(self.syn0, axis=1, keepdims=True)
        self._unit = self.syn0 / np.maximum(norms, 1e-12)

    def _require_fitted(self) -> None:
        if getattr(self, "syn0", None) is None \
                or getattr(self, "_unit", None) is None:
            raise RuntimeError(
                f"{type(self).__name__} has no trained vectors yet — "
                "call fit() first")

    def get_word_vector(self, word: str) -> Optional[np.ndarray]:
        self._require_fitted()
        i = self.vocab.index_of(word)
        return self.syn0[i] if i >= 0 else None

    def has_word(self, word: str) -> bool:
        return self.vocab.index_of(word) >= 0

    def similarity(self, w1: str, w2: str) -> float:
        self._require_fitted()
        i, j = self.vocab.index_of(w1), self.vocab.index_of(w2)
        if i < 0 or j < 0:
            return float("nan")
        return float(self._unit[i] @ self._unit[j])

    def words_nearest(self, word: str, n: int = 10) -> List[Tuple[str, float]]:
        self._require_fitted()
        i = self.vocab.index_of(word)
        if i < 0:
            return []
        sims = self._unit @ self._unit[i]
        order = np.argsort(-sims)
        out = []
        for j in order:
            if j == i:
                continue
            out.append((self.vocab.word_at(int(j)), float(sims[j])))
            if len(out) >= n:
                break
        return out


class Word2Vec(WordVectors):
    """Skip-gram trainer (builder-style kwargs mirror the reference's
    Word2Vec.Builder: layerSize/windowSize/minWordFrequency/iterations/
    learningRate/minLearningRate/negativeSample/sample/seed)."""

    def __init__(self, sentences=None, *, layer_size: int = 100,
                 window: int = 5, min_word_frequency: float = 1.0,
                 iterations: int = 1, learning_rate: float = 0.025,
                 min_learning_rate: float = 1e-4, negative: int = 0,
                 sample: float = 0.0, batch_pairs: int = 4096,
                 seed: int = 123,
                 tokenizer_factory: Optional[TokenizerFactory] = None):
        self.layer_size = layer_size
        self.window = window
        self.min_word_frequency = min_word_frequency
        self.iterations = iterations
        self.alpha = learning_rate
        self.min_alpha = min_learning_rate
        self.negative = negative
        self.sample = sample
        self.batch_pairs = batch_pairs
        self.seed = seed
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        if isinstance(sentences, SentenceIterator):
            self.sentence_iter = sentences
        elif sentences is not None:
            self.sentence_iter = CollectionSentenceIterator(list(sentences))
        else:
            self.sentence_iter = None
        self.vocab = VocabCache()
        self.syn0 = None
        self.syn1 = None
        self.syn1neg = None
        self._code_len = 0
        self._key = jax.random.PRNGKey(seed)

    # ----------------------------------------------------------- vocab/init
    def build_vocab(self) -> None:
        """reference buildVocab :257 + Huffman(vocab).build() :348."""
        build_vocab(self.sentence_iter, self.tokenizer_factory,
                    self.min_word_frequency, self.vocab)
        self._extend_vocab()  # hook: subclasses add pseudo-words (labels)
        build_huffman(self.vocab)
        self._code_len = max(1, max_code_length(self.vocab))

    def _extend_vocab(self) -> None:
        pass

    def reset_weights(self) -> None:
        """reference InMemoryLookupTable.resetWeights :88: syn0 uniform in
        +-0.5/dim, syn1 zeros."""
        n, d = self.vocab.num_words(), self.layer_size
        self._key, k = jax.random.split(self._key)
        self.syn0 = jax.random.uniform(k, (n, d), jnp.float32,
                                       -0.5 / d, 0.5 / d)
        if self.negative > 0:
            self.syn1neg = jnp.zeros((n, d), jnp.float32)
        else:  # hierarchical softmax path
            self.syn1 = jnp.zeros((n, d), jnp.float32)

    def _unigram_logits(self) -> jnp.ndarray:
        """unigram^0.75 sampling distribution (the reference's table)."""
        counts = np.array([vw.count for vw in self.vocab.vocab_words()],
                          np.float64)
        probs = counts ** 0.75
        probs /= probs.sum()
        return jnp.asarray(np.log(np.maximum(probs, 1e-12)), jnp.float32)

    # ------------------------------------------------------------- training
    def _codes_points(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Pad per-word Huffman codes/points to (V, L) with a mask."""
        v, L = self.vocab.num_words(), self._code_len
        codes = np.zeros((v, L), np.float32)
        points = np.zeros((v, L), np.int32)
        mask = np.zeros((v, L), np.float32)
        for vw in self.vocab.vocab_words():
            ln = vw.code_length()
            codes[vw.index, :ln] = vw.codes
            points[vw.index, :ln] = vw.points
            mask[vw.index, :ln] = 1.0
        return codes, points, mask

    def _mine_pairs(self, rng: np.random.RandomState
                    ) -> Tuple[np.ndarray, np.ndarray]:
        """Host-side pair mining: skip-gram windows with the word2vec random
        window shrink (reference skipGram :314 trains syn0[context] against
        the CENTER word's codes) + optional frequent-word subsampling."""
        centers, contexts = [], []
        total = max(1.0, self.vocab.total_word_count)
        for sentence in self.sentence_iter:
            toks = self.tokenizer_factory.tokenize(sentence)
            idxs = [self.vocab.index_of(t) for t in toks]
            idxs = [i for i in idxs if i >= 0]
            if self.sample > 0:
                kept = []
                for i in idxs:
                    f = self.vocab.word_frequency(self.vocab.word_at(i)) / total
                    keep_p = (np.sqrt(f / self.sample) + 1) * self.sample / f
                    if rng.rand() < keep_p:
                        kept.append(i)
                idxs = kept
            for pos, center in enumerate(idxs):
                b = rng.randint(1, self.window + 1)  # shrunk window
                for off in range(-b, b + 1):
                    if off == 0:
                        continue
                    j = pos + off
                    if 0 <= j < len(idxs):
                        centers.append(center)
                        contexts.append(idxs[j])
        return (np.asarray(centers, np.int32),
                np.asarray(contexts, np.int32))

    def _build_step(self):
        codes, points, mask = self._codes_points()
        codes_t, points_t, mask_t = (jnp.asarray(codes), jnp.asarray(points),
                                     jnp.asarray(mask))
        negative = self.negative
        uni_logits = self._unigram_logits() if negative > 0 else None

        def loss_fn(tables, centers, contexts, negs):
            syn0 = tables["syn0"]
            l1 = syn0[contexts]  # (B, D) — reference trains syn0[context]
            loss = 0.0
            if "syn1" in tables:
                # hierarchical softmax over the center word's code path
                p = points_t[centers]          # (B, L)
                c = codes_t[centers]           # (B, L)
                m = mask_t[centers]            # (B, L)
                logits = jnp.einsum("bd,bld->bl", l1, tables["syn1"][p])
                labels = 1.0 - c               # word2vec label convention
                bce = jnp.maximum(logits, 0) - logits * labels + \
                    jnp.log1p(jnp.exp(-jnp.abs(logits)))
                # sum over the code path, mean over pairs: matches the
                # reference's per-pair accumulation of one update per bit
                loss = loss + jnp.mean(jnp.sum(bce * m, axis=1))
            if "syn1neg" in tables:
                tgt = jnp.concatenate([centers[:, None], negs], axis=1)
                labels = jnp.concatenate(
                    [jnp.ones_like(centers[:, None], jnp.float32),
                     jnp.zeros_like(negs, jnp.float32)], axis=1)
                # mask negatives that drew the positive target itself
                # (reference: `if (target == word) continue`)
                valid = jnp.concatenate(
                    [jnp.ones_like(centers[:, None], jnp.float32),
                     (negs != centers[:, None]).astype(jnp.float32)], axis=1)
                logits = jnp.einsum("bd,bkd->bk", l1, tables["syn1neg"][tgt])
                bce = jnp.maximum(logits, 0) - logits * labels + \
                    jnp.log1p(jnp.exp(-jnp.abs(logits)))
                loss = loss + jnp.mean(jnp.sum(bce * valid, axis=1))
            return loss

        @jax.jit
        def step(tables, centers, contexts, alpha, key):
            if negative > 0:
                negs = jax.random.categorical(
                    key, uni_logits, shape=(centers.shape[0], negative))
            else:
                negs = jnp.zeros((centers.shape[0], 0), jnp.int32)
            loss, grads = jax.value_and_grad(loss_fn)(
                tables, centers, contexts, negs)
            tables = jax.tree_util.tree_map(
                lambda t, g: t - alpha * g, tables, grads)
            return tables, loss

        return step

    def fit(self) -> "Word2Vec":
        """reference fit :101: build vocab, Huffman, reset weights, train
        with lr decaying by pairs seen."""
        if self.sentence_iter is None:
            raise ValueError("Word2Vec needs sentences")
        if self.vocab.num_words() == 0:
            self.build_vocab()
        if self.syn0 is None:
            self.reset_weights()
        rng = np.random.RandomState(self.seed)
        centers, contexts = self._mine_pairs(rng)
        if centers.size == 0:
            raise ValueError("No training pairs (vocab/corpus too small)")
        step = self._build_step()

        tables = {"syn0": self.syn0}
        if self.syn1 is not None:
            tables["syn1"] = self.syn1
        if self.syn1neg is not None:
            tables["syn1neg"] = self.syn1neg
        n = centers.shape[0]
        total_steps = max(1, self.iterations * ((n - 1) // self.batch_pairs
                                                + 1))
        step_i = 0
        loss = None
        for _ in range(self.iterations):
            order = rng.permutation(n)
            for lo in range(0, n, self.batch_pairs):
                sel = order[lo:lo + self.batch_pairs]
                # static batch shape: tile the tail so jit compiles once
                if sel.size < self.batch_pairs:
                    sel = np.concatenate(
                        [sel, sel[np.arange(self.batch_pairs - sel.size)
                                  % sel.size]])
                alpha = max(self.min_alpha,
                            self.alpha * (1.0 - step_i / total_steps))
                self._key, k = jax.random.split(self._key)
                tables, loss = step(tables, jnp.asarray(centers[sel]),
                                    jnp.asarray(contexts[sel]),
                                    jnp.float32(alpha), k)
                step_i += 1
        self.syn0 = tables["syn0"]
        self.syn1 = tables.get("syn1")
        self.syn1neg = tables.get("syn1neg")
        log.info("word2vec trained: %d pairs, final loss %.4f", n,
                 float(loss))
        # refresh the WordVectors view
        WordVectors.__init__(self, self.vocab, np.asarray(self.syn0))
        return self
