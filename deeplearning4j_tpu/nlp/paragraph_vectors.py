"""ParagraphVectors (doc2vec, PV-DBOW flavor).

Parity: reference nlp/models/paragraphvectors/ParagraphVectors.java:53-61 —
extends Word2Vec by adding label "words" trained on every window of their
document, so each label gets an embedding in the same space as the words.

TPU-native design: labels are appended to the vocab as pseudo-words; pair
mining emits (label, context-word) pairs for every word of the labeled
sentence alongside the normal skip-gram pairs; training reuses the batched
Word2Vec step unchanged.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.nlp.sentence_iterator import LabelAwareSentenceIterator
from deeplearning4j_tpu.nlp.word2vec import Word2Vec


class ParagraphVectors(Word2Vec):
    def __init__(self, labeled_sentences=None, **kw):
        """`labeled_sentences`: iterable of (label, sentence) pairs or a
        LabelAwareSentenceIterator."""
        if isinstance(labeled_sentences, LabelAwareSentenceIterator):
            pairs = list(labeled_sentences.pairs)
        else:
            pairs = list(labeled_sentences or [])
        self.labeled = pairs
        super().__init__([s for _, s in pairs], **kw)
        self.labels = sorted({lb for lb, _ in pairs})

    def _extend_vocab(self) -> None:
        # labels enter the vocab as pseudo-words with doc-level counts
        # AFTER truncation (so min_word_frequency can't drop them) and
        # BEFORE the single Huffman build in Word2Vec.build_vocab
        for label in self.labels:
            n_docs = sum(1 for lb, _ in self.labeled if lb == label)
            vw = self.vocab.add_token(self._label_token(label), by=n_docs)
            self.vocab.add_word_to_index(vw.word)

    @staticmethod
    def _label_token(label: str) -> str:
        return f"__label__{label}"

    def _iter_pair_chunks(self, rng: np.random.RandomState,
                          chunk_tokens: int = 1 << 18):
        # PV-DBOW: each doc's label predicts every word of the doc
        # (reference trains the label word in every window, :61).
        # Label chunks are INTERLEAVED with the base skip-gram stream —
        # yielding them all at the end would train every label pair at
        # the fully-decayed learning rate (words_seen ≈ total by then),
        # which measurably wrecked label quality at corpus scale (topic
        # retrieval 0.40 tail-trained vs ~1.0 interleaved on a 2M-token
        # 20-topic corpus). Label pairs carry no new corpus words
        # (n_words = 0: the base chunks own the alpha decay).
        base = super()._iter_pair_chunks(rng, chunk_tokens)
        labels = self._iter_label_chunks(chunk_tokens)
        while True:
            stop = True
            for stream in (base, labels):
                chunk = next(stream, None)
                if chunk is not None:
                    stop = False
                    yield chunk
            if stop:
                return

    def _iter_label_chunks(self, chunk_tokens: int):
        # chunked like the base stream so a corpus-scale labeled set
        # never materializes all label pairs at once
        lab_centers: List[np.ndarray] = []
        lab_contexts: List[np.ndarray] = []
        buffered = 0
        for label, sentence in self.labeled:
            li = self.vocab.index_of(self._label_token(label))
            if li < 0:
                continue
            words = self._tokens_to_indices(sentence)
            if words.size:
                lab_centers.append(words)   # predict word via its codes
                lab_contexts.append(        # from the label's vector
                    np.full(words.size, li, np.int32))
                buffered += words.size
            if buffered >= chunk_tokens:
                yield (np.concatenate(lab_centers),
                       np.concatenate(lab_contexts), 0)
                lab_centers, lab_contexts, buffered = [], [], 0
        if lab_centers:
            yield (np.concatenate(lab_centers),
                   np.concatenate(lab_contexts), 0)

    # ---------------------------------------------------------------- query
    def label_vector(self, label: str) -> Optional[np.ndarray]:
        return self.get_word_vector(self._label_token(label))

    def similarity_to_label(self, word: str, label: str) -> float:
        return self.similarity(word, self._label_token(label))

    def nearest_labels(self, word: str, n: int = 5):
        i = self.vocab.index_of(word)
        if i < 0:
            return []
        out = []
        for label in self.labels:
            out.append((label, self.similarity(word,
                                               self._label_token(label))))
        out.sort(key=lambda t: -t[1])
        return out[:n]
