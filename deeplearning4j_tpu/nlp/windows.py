"""Moving-window featurization for word-level classification.

Parity: reference nlp/text/movingwindow/ — `Window` (tokens + focus word +
label), `Windows.windows(text, windowSize)` (pad with <s>/</s>, slide over
tokens), and `WindowConverter.asExampleMatrix` (concatenate the word
vectors of the window into one input row). Feeds the Word2Vec-based
classification pipeline (Word2VecDataSetIterator)."""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

BEGIN, END = "<s>", "</s>"


class Window:
    def __init__(self, words: Sequence[str], focus_index: int,
                 label: Optional[str] = None):
        self.words = list(words)
        self.focus_index = focus_index
        self.label = label

    def focus_word(self) -> str:
        return self.words[self.focus_index]

    def __repr__(self):
        return f"Window({self.words}, focus={self.focus_word()!r})"


def windows(tokens: Sequence[str], window_size: int = 5,
            label: Optional[str] = None) -> List[Window]:
    """Slide a centered window over tokens, padding the edges
    (reference Windows.windows)."""
    if window_size % 2 == 0:
        raise ValueError("window_size must be odd")
    half = window_size // 2
    padded = [BEGIN] * half + list(tokens) + [END] * half
    out = []
    for i in range(len(tokens)):
        out.append(Window(padded[i:i + window_size], half, label=label))
    return out


def window_as_vector(window: Window, word_vectors) -> np.ndarray:
    """Concatenate the window's word vectors into one example row
    (reference WindowConverter.asExampleMatrix). Unknown/pad words get
    zero vectors."""
    d = word_vectors.syn0.shape[1]
    parts = []
    for w in window.words:
        vec = word_vectors.get_word_vector(w)
        parts.append(np.zeros(d, np.float32) if vec is None
                     else np.asarray(vec, np.float32))
    return np.concatenate(parts)
