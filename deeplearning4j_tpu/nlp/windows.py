"""Moving-window featurization for word-level classification.

Parity: reference nlp/text/movingwindow/ — `Window` (tokens + focus word +
label), `Windows.windows(text, windowSize)` (pad with <s>/</s>, slide over
tokens), `WindowConverter.asExampleMatrix` (concatenate the word
vectors of the window into one input row), and
`ContextLabelRetriever.stringWithLabels` (strip inline <LABEL>…</LABEL>
span markup into (tokens, span->label)). Feeds the Word2Vec-based
classification pipeline (Word2VecDataSetIterator).

Round 5 adds the annotator capabilities the reference got from UIMA
wrappers, natively: `annotate_windows` labels each window with the
focus token's PoS tag (nlp/pos.py HmmPosTagger) and/or the window's
sentiment class (nlp/sentiment.py SentimentLexicon) — the roles
PoStagger.java and SWN3.java played for ContextLabel features."""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

BEGIN, END = "<s>", "</s>"

#: superset of the reference's `<([A-Za-z]+|\d+)>` pattern
#: (ContextLabelRetriever.java:35-36): also admits B-LOC / X_2 style
#: labels so common span markup can't silently leak into the tokens
_BEGIN_LABEL = re.compile(r"<([A-Za-z0-9_.-]+)>$")
_END_LABEL = re.compile(r"</([A-Za-z0-9_.-]+)>$")


class Window:
    def __init__(self, words: Sequence[str], focus_index: int,
                 label: Optional[str] = None,
                 tags: Optional[Sequence[str]] = None):
        self.words = list(words)
        self.focus_index = focus_index
        self.label = label
        #: optional per-word annotations (PoS tags), aligned with words
        self.tags = list(tags) if tags is not None else None

    def focus_word(self) -> str:
        return self.words[self.focus_index]

    def focus_tag(self) -> Optional[str]:
        return self.tags[self.focus_index] if self.tags else None

    def __repr__(self):
        return f"Window({self.words}, focus={self.focus_word()!r})"


def windows(tokens: Sequence[str], window_size: int = 5,
            label: Optional[str] = None) -> List[Window]:
    """Slide a centered window over tokens, padding the edges
    (reference Windows.windows)."""
    if window_size % 2 == 0:
        raise ValueError("window_size must be odd")
    half = window_size // 2
    padded = [BEGIN] * half + list(tokens) + [END] * half
    out = []
    for i in range(len(tokens)):
        out.append(Window(padded[i:i + window_size], half, label=label))
    return out


def window_as_vector(window: Window, word_vectors) -> np.ndarray:
    """Concatenate the window's word vectors into one example row
    (reference WindowConverter.asExampleMatrix). Unknown/pad words get
    zero vectors."""
    d = word_vectors.syn0.shape[1]
    parts = []
    for w in window.words:
        vec = word_vectors.get_word_vector(w)
        parts.append(np.zeros(d, np.float32) if vec is None
                     else np.asarray(vec, np.float32))
    return np.concatenate(parts)


def string_with_labels(sentence: str, tokenizer=None
                       ) -> Tuple[List[str], Dict[Tuple[int, int], str]]:
    """Strip inline <LABEL>...</LABEL> markup from a sentence
    (reference ContextLabelRetriever.stringWithLabels:50-118): returns
    (tokens without markup, {(start, end): label}) where the span is a
    half-open token range into the returned list. Raises on unbalanced
    markup like the reference."""
    tokens = (tokenizer(sentence) if tokenizer is not None
              else sentence.split())
    out: List[str] = []
    spans: Dict[Tuple[int, int], str] = {}
    label: Optional[str] = None
    start = 0
    for tok in tokens:
        m = _BEGIN_LABEL.match(tok)
        if m:
            if label is not None:
                raise ValueError(
                    f"nested begin label <{m.group(1)}> inside <{label}>")
            label = m.group(1)
            start = len(out)
            continue
        m = _END_LABEL.match(tok)
        if m:
            if label is None:
                raise ValueError(
                    f"end label </{m.group(1)}> with no begin label")
            if m.group(1) != label:
                raise ValueError(
                    f"end label </{m.group(1)}> does not match <{label}>")
            spans[(start, len(out))] = label
            label = None
            continue
        out.append(tok)
    if label is not None:
        raise ValueError(f"begin label <{label}> was never closed")
    return out, spans


def annotate_windows(tokens: Sequence[str], window_size: int = 5,
                     tagger=None, lexicon=None,
                     span_labels: Optional[Dict[Tuple[int, int], str]]
                     = None) -> List[Window]:
    """Moving windows with native annotations: per-word PoS tags from
    `tagger` (HmmPosTagger.tag interface), window label precedence
    span_labels > lexicon sentiment class > None. This is the
    end-to-end path the reference assembled from ContextLabel +
    PoStagger + SWN3."""
    wins = windows(tokens, window_size)
    half = window_size // 2
    tags = list(tagger.tag(tokens)) if tagger is not None else None
    for i, w in enumerate(wins):
        if tags is not None:
            # align tags with the padded window; pads have no tag
            w.tags = [
                tags[j] if 0 <= (j := i - half + k) < len(tokens) else None
                for k in range(window_size)]
        label = None
        if span_labels:
            for (s, e), lab in span_labels.items():
                if s <= i < e:
                    label = lab
                    break
        if label is None and lexicon is not None:
            label = lexicon.classify_tokens(
                [t for t in w.words if t not in (BEGIN, END)])
        w.label = label
    return wins
