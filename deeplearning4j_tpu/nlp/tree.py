"""Labeled parse trees for recursive models (RNTN).

Parity: reference Tree structure
(deeplearning4j-core/.../models/featuredetectors/autoencoder/recursive/
Tree.java:30-484 — label/value/children/goldLabel/vector/prediction/error,
isLeaf/isPreTerminal, depth, getLeaves, errorSum, clone) plus a
Penn-treebank-style s-expression parser so labeled trees can be built
without the reference's UIMA/treebank stack.

TPU-first design: the Python Tree is a host-side construction/inspection
structure only; `encode_trees` lowers a batch of trees to padded
topological index arrays (children always before parents) that a single
`lax.scan` consumes on device — the jittable replacement for the
reference's per-node Java recursion.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional

import numpy as np


class Tree:
    """A node: leaves carry a token in `value`; internal nodes carry an
    optional integer `gold_label` (sentiment class; -1 = unlabeled) and a
    string `label` (syntactic category; '' under the simplified model)."""

    def __init__(self, value: Optional[str] = None,
                 children: Optional[List["Tree"]] = None,
                 gold_label: int = -1, label: str = ""):
        self.value = value
        self.children: List[Tree] = children or []
        self.gold_label = gold_label
        self.label = label
        # set by RNTN.forward_propagate_tree (reference setVector/
        # setPrediction/setError)
        self.vector = None
        self.prediction = None
        self.error = 0.0

    # ------------------------------------------------------------ structure
    def is_leaf(self) -> bool:
        return not self.children

    def is_preterminal(self) -> bool:
        """One child which is a leaf (reference isPreTerminal :160)."""
        return len(self.children) == 1 and self.children[0].is_leaf()

    def first_child(self) -> "Tree":
        return self.children[0]

    def last_child(self) -> "Tree":
        return self.children[-1]

    def depth(self) -> int:
        if self.is_leaf():
            return 0
        return 1 + max(c.depth() for c in self.children)

    def leaves(self) -> List["Tree"]:
        if self.is_leaf():
            return [self]
        out: List[Tree] = []
        for c in self.children:
            out.extend(c.leaves())
        return out

    def tokens(self) -> List[str]:
        """The yield: left-to-right leaf values (reference yield() :92)."""
        return [leaf.value for leaf in self.leaves()]

    def error_sum(self) -> float:
        """Total error over the subtree (reference errorSum :271)."""
        return self.error + sum(c.error_sum() for c in self.children)

    def clone(self) -> "Tree":
        t = Tree(self.value, [c.clone() for c in self.children],
                 self.gold_label, self.label)
        return t

    def __repr__(self):
        if self.is_leaf():
            return f"Tree({self.value!r})"
        head = self.label or self.gold_label
        return f"Tree({head}, {len(self.children)} children)"

    def to_sexpr(self) -> str:
        if self.is_leaf():
            return str(self.value)
        head = self.label if self.label else str(self.gold_label)
        return f"({head} " + " ".join(c.to_sexpr()
                                      for c in self.children) + ")"


def parse_tree(text: str) -> Tree:
    """Parse an s-expression like ``(2 (1 bad) (3 movie))`` — integer heads
    become gold labels, non-integer heads become category labels."""
    tokens = text.replace("(", " ( ").replace(")", " ) ").split()
    pos = 0

    def parse() -> Tree:
        nonlocal pos
        if tokens[pos] != "(":
            word = tokens[pos]
            pos += 1
            return Tree(value=word)
        pos += 1  # consume '('
        head = tokens[pos]
        pos += 1
        node = Tree()
        try:
            node.gold_label = int(head)
        except ValueError:
            node.label = head
        while tokens[pos] != ")":
            node.children.append(parse())
        pos += 1  # consume ')'
        return node

    tree = parse()
    if pos != len(tokens):
        raise ValueError(f"Trailing tokens in tree text: {tokens[pos:]!r}")
    return tree


def binarize(tree: Tree) -> Tree:
    """Left-binarize n-ary nodes and collapse unary chains above
    preterminals so every internal node is preterminal or binary — the
    shape RNTN requires (reference BinarizeTreeTransformer +
    CollapseUnaries, nlp/text/corpora/treeparser/). Returns a new tree;
    the input is never mutated or aliased."""
    if tree.is_leaf() or tree.is_preterminal():
        return tree.clone()
    children = [binarize(c) for c in tree.children]
    while len(children) > 2:
        merged = Tree(gold_label=-1, label=tree.label,
                      children=children[:2])
        children = [merged] + children[2:]
    if len(children) == 1:
        child = children[0]
        # collapse unary: keep the outermost gold label if child unlabeled
        if child.gold_label < 0:
            child.gold_label = tree.gold_label
        return child
    return Tree(gold_label=tree.gold_label, label=tree.label,
                children=children)


class EncodedTrees(NamedTuple):
    """Batch of padded topological tree encodings (device-ready).

    All arrays have shape (n_trees, max_nodes); slot order is post-order so
    a scan from slot 0 upward always sees children computed first.
    kind: 0=pad, 1=preterminal/word, 2=binary.
    """

    kind: np.ndarray
    word: np.ndarray   # word id (kind 1)
    left: np.ndarray   # child slot index (kind 2)
    right: np.ndarray
    cat: np.ndarray    # transform-parameter index (category pair)
    ccat: np.ndarray   # classification-parameter index
    gold: np.ndarray   # gold label, -1 = unlabeled
    root: np.ndarray   # (n_trees,) slot index of each root

    @property
    def n_trees(self) -> int:
        return self.kind.shape[0]

    @property
    def max_nodes(self) -> int:
        return self.kind.shape[1]


def _count_internal(tree: Tree) -> int:
    if tree.is_leaf():
        return 0
    if tree.is_preterminal():
        return 1
    return 1 + sum(_count_internal(c) for c in tree.children)


def encode_trees(trees: List[Tree], word_index: Dict[str, int],
                 unk_index: int = 0,
                 cat_index=None, ccat_index=None,
                 max_nodes: Optional[int] = None,
                 word_transform=None) -> EncodedTrees:
    """Lower Python trees to padded post-order index arrays.

    `cat_index`/`ccat_index` map (left_label, right_label) pairs / labels to
    parameter indices; None = simplified model (single shared index 0,
    reference simplifiedModel/combineClassification defaults).
    `word_transform` (e.g. str.lower) is applied to each token before the
    word_index lookup.
    """
    sizes = [_count_internal(t) for t in trees]
    width = max_nodes or max(sizes)
    if max(sizes) > width:
        raise ValueError(f"Tree with {max(sizes)} nodes exceeds "
                         f"max_nodes={width}")
    n = len(trees)
    enc = EncodedTrees(
        kind=np.zeros((n, width), np.int32),
        word=np.zeros((n, width), np.int32),
        left=np.zeros((n, width), np.int32),
        right=np.zeros((n, width), np.int32),
        cat=np.zeros((n, width), np.int32),
        ccat=np.zeros((n, width), np.int32),
        gold=np.full((n, width), -1, np.int32),
        root=np.zeros((n,), np.int32),
    )

    for ti, tree in enumerate(trees):
        slot = [0]

        def visit(node: Tree) -> int:
            if node.is_leaf():
                raise ValueError(
                    "encode_trees visits internal nodes only; got a bare "
                    "leaf — wrap tokens in preterminals (binarize() helps)")
            if not (node.is_preterminal() or len(node.children) == 2):
                raise ValueError(
                    f"RNTN trees must be binary (or preterminal); node has "
                    f"{len(node.children)} children — call binarize() first")
            if node.is_preterminal():
                s = slot[0]
                slot[0] += 1
                enc.kind[ti, s] = 1
                word = node.first_child().value
                if word_transform is not None:
                    word = word_transform(word)
                enc.word[ti, s] = word_index.get(word, unk_index)
                enc.ccat[ti, s] = (ccat_index[node.label]
                                   if ccat_index else 0)
                enc.gold[ti, s] = node.gold_label
                return s
            li = visit(node.first_child())
            ri = visit(node.last_child())
            s = slot[0]
            slot[0] += 1
            enc.kind[ti, s] = 2
            enc.left[ti, s] = li
            enc.right[ti, s] = ri
            pair = (node.first_child().label, node.last_child().label)
            enc.cat[ti, s] = cat_index[pair] if cat_index else 0
            enc.ccat[ti, s] = (ccat_index[node.label]
                               if ccat_index else 0)
            enc.gold[ti, s] = node.gold_label
            return s

        enc.root[ti] = visit(tree)
    return enc
