"""Clustering + spatial indexes.

Parity: reference core/clustering/ — KMeans (kmeans/KMeansClustering.java
over the BaseClusteringAlgorithm strategy machinery), KDTree
(kdtree/KDTree.java), VPTree (vptree/VpTreeNode.java), QuadTree
(quadtree/QuadTree.java — the Barnes-Hut t-SNE accelerator).

TPU-native design: KMeans runs its Lloyd iterations as one jitted
assign/update step (distance matrix on the MXU); the spatial indexes are
host-side numpy structures — pointer-chasing trees don't belong on the
accelerator, and their consumers (neighbor queries, Barnes-Hut) are
host-side too.
"""

from deeplearning4j_tpu.clustering.kmeans import KMeansClustering  # noqa: F401
from deeplearning4j_tpu.clustering.kdtree import KDTree  # noqa: F401
from deeplearning4j_tpu.clustering.vptree import VPTree  # noqa: F401
from deeplearning4j_tpu.clustering.quadtree import QuadTree  # noqa: F401
