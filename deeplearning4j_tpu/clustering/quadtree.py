"""Quad-tree over 2D points — the Barnes-Hut t-SNE accelerator.

Parity: reference core/clustering/quadtree/QuadTree.java (491 LoC):
insert with cell subdivision, center-of-mass accumulation, and the
Barnes-Hut `computeNonEdgeForces` traversal (theta criterion).
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class _Cell:
    __slots__ = ("x", "y", "hw", "hh")

    def __init__(self, x, y, hw, hh):
        self.x, self.y, self.hw, self.hh = x, y, hw, hh

    def contains(self, px, py) -> bool:
        return (abs(self.x - px) <= self.hw + 1e-12
                and abs(self.y - py) <= self.hh + 1e-12)


class QuadTree:
    QT_NODE_CAPACITY = 1

    def __init__(self, cell: Optional[_Cell] = None, points=None):
        if points is not None:
            points = np.asarray(points, np.float64)
            cx, cy = points[:, 0].mean(), points[:, 1].mean()
            hw = max(points[:, 0].max() - cx, cx - points[:, 0].min()) + 1e-5
            hh = max(points[:, 1].max() - cy, cy - points[:, 1].min()) + 1e-5
            cell = _Cell(cx, cy, hw, hh)
        self.cell = cell
        self.center_of_mass = np.zeros(2)
        self.cum_size = 0
        self.point: Optional[np.ndarray] = None
        self.children = None  # [nw, ne, sw, se]
        if points is not None:
            for p in points:
                self.insert(p)

    # ------------------------------------------------------------- insert
    def insert(self, p) -> bool:
        p = np.asarray(p, np.float64)
        if not self.cell.contains(p[0], p[1]):
            return False
        self.cum_size += 1
        self.center_of_mass += (p - self.center_of_mass) / self.cum_size
        if self.point is None and self.children is None:
            self.point = p
            return True
        if self.children is None:
            if self.point is not None and np.allclose(self.point, p):
                return True  # coincident point: merge into this leaf's mass
            self._subdivide()
        return any(child.insert(p) for child in self.children)

    def _subdivide(self):
        c = self.cell
        hw, hh = c.hw / 2, c.hh / 2
        self.children = [
            QuadTree(_Cell(c.x - hw, c.y - hh, hw, hh)),
            QuadTree(_Cell(c.x + hw, c.y - hh, hw, hh)),
            QuadTree(_Cell(c.x - hw, c.y + hh, hw, hh)),
            QuadTree(_Cell(c.x + hw, c.y + hh, hw, hh)),
        ]
        old, self.point = self.point, None
        for child in self.children:
            if child.insert(old):
                break

    # -------------------------------------------------- Barnes-Hut forces
    def compute_non_edge_forces(self, point, theta: float = 0.5,
                                neg_f=None) -> float:
        """Accumulate repulsive forces on `point`; returns the Z partial sum
        (reference computeNonEdgeForces)."""
        if neg_f is None:
            neg_f = np.zeros(2)
        if self.cum_size == 0:
            return 0.0
        point = np.asarray(point, np.float64)
        diff = point - self.center_of_mass
        d2 = float(diff @ diff)
        is_leaf_same = (self.point is not None
                        and np.allclose(self.point, point))
        max_width = max(self.cell.hw, self.cell.hh) * 2
        if is_leaf_same and self.children is None:
            return 0.0
        if self.children is None or max_width / np.sqrt(d2 + 1e-12) < theta:
            # treat the cell as one body
            q = 1.0 / (1.0 + d2)
            mult = self.cum_size * q
            z = mult
            neg_f += mult * q * diff
            return z
        return sum(child.compute_non_edge_forces(point, theta, neg_f)
                   for child in self.children)
