"""Vantage-point tree for metric nearest-neighbor search.

Parity: reference core/clustering/vptree/VpTreeNode.java (306 LoC):
build by random vantage point + median-distance split; k-NN search with
triangle-inequality pruning.
"""

from __future__ import annotations

import heapq
from typing import Callable, List, Optional, Tuple

import numpy as np


class _VPNode:
    __slots__ = ("index", "threshold", "inside", "outside")

    def __init__(self, index: int):
        self.index = index
        self.threshold = 0.0
        self.inside: Optional["_VPNode"] = None
        self.outside: Optional["_VPNode"] = None


class VPTree:
    def __init__(self, points, distance: Optional[Callable] = None,
                 seed: int = 0):
        self.points = np.asarray(points, np.float64)
        self.distance = distance or (
            lambda a, b: float(np.linalg.norm(a - b)))
        rng = np.random.RandomState(seed)
        self.root = self._build(list(range(self.points.shape[0])), rng)

    def _build(self, idxs: List[int], rng) -> Optional[_VPNode]:
        if not idxs:
            return None
        vp = idxs[rng.randint(len(idxs))]
        rest = [i for i in idxs if i != vp]
        node = _VPNode(vp)
        if not rest:
            return node
        dists = np.array([self.distance(self.points[vp], self.points[i])
                          for i in rest])
        node.threshold = float(np.median(dists))
        inside = [i for i, d in zip(rest, dists) if d < node.threshold]
        outside = [i for i, d in zip(rest, dists) if d >= node.threshold]
        node.inside = self._build(inside, rng)
        node.outside = self._build(outside, rng)
        return node

    def knn(self, query, k: int) -> List[Tuple[float, int]]:
        """k nearest: [(distance, point index)] ascending."""
        query = np.asarray(query, np.float64)
        heap: List[Tuple[float, int]] = []  # max-heap by -dist

        def rec(node: Optional[_VPNode]):
            if node is None:
                return
            d = self.distance(query, self.points[node.index])
            if len(heap) < k:
                heapq.heappush(heap, (-d, node.index))
            elif d < -heap[0][0]:
                heapq.heapreplace(heap, (-d, node.index))
            tau = -heap[0][0] if len(heap) == k else np.inf
            if d < node.threshold:
                rec(node.inside)
                if d + tau >= node.threshold:
                    rec(node.outside)
            else:
                rec(node.outside)
                if d - tau <= node.threshold:
                    rec(node.inside)

        rec(self.root)
        return sorted([(-nd, i) for nd, i in heap], key=lambda t: t[0])
