"""KMeans clustering.

Parity: reference core/clustering/kmeans/KMeansClustering.java (+ the
strategy/condition machinery of clustering/algorithm/BaseClusteringAlgorithm:
iterate until max iterations or distribution-variation convergence).

TPU-native design: k-means++ seeding on the host, then each Lloyd
iteration is ONE jitted step — the (n, k) distance matrix is a matmul on
the MXU, assignment is an argmin, and the centroid update is a
segment-sum. No per-point Java loops.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


class KMeansClustering:
    def __init__(self, k: int, max_iterations: int = 100, tol: float = 1e-4,
                 seed: int = 0):
        self.k = k
        self.max_iterations = max_iterations
        self.tol = tol
        self.seed = seed
        self.centroids: Optional[np.ndarray] = None

    # ------------------------------------------------------------- seeding
    def _init_centroids(self, x: np.ndarray, rng: np.random.RandomState
                        ) -> np.ndarray:
        """k-means++ seeding."""
        n = x.shape[0]
        centroids = [x[rng.randint(n)]]
        for _ in range(1, self.k):
            d2 = np.min(
                ((x[:, None, :] - np.stack(centroids)[None]) ** 2).sum(-1),
                axis=1)
            total = d2.sum()
            if total <= 0:  # fewer distinct points than k: uniform fallback
                centroids.append(x[rng.randint(n)])
            else:
                centroids.append(x[rng.choice(n, p=d2 / total)])
        return np.stack(centroids)

    # ------------------------------------------------------------ training
    @staticmethod
    @jax.jit
    def _step(x, centroids):
        # (n,k) squared distances via the expansion trick (MXU matmul)
        x2 = jnp.sum(x * x, axis=1, keepdims=True)
        c2 = jnp.sum(centroids * centroids, axis=1)[None, :]
        d2 = x2 + c2 - 2.0 * (x @ centroids.T)
        assign = jnp.argmin(d2, axis=1)
        one_hot = jax.nn.one_hot(assign, centroids.shape[0], dtype=x.dtype)
        counts = jnp.maximum(one_hot.sum(axis=0), 1.0)
        new_centroids = (one_hot.T @ x) / counts[:, None]
        # keep empty clusters where they were
        empty = (one_hot.sum(axis=0) == 0)[:, None]
        new_centroids = jnp.where(empty, centroids, new_centroids)
        shift = jnp.max(jnp.linalg.norm(new_centroids - centroids, axis=1))
        return new_centroids, assign, shift

    def fit(self, x) -> "KMeansClustering":
        x = np.asarray(x, np.float32)
        if x.shape[0] < self.k:
            raise ValueError(f"k={self.k} > n={x.shape[0]} points")
        rng = np.random.RandomState(self.seed)
        centroids = jnp.asarray(self._init_centroids(x, rng))
        xj = jnp.asarray(x)
        for _ in range(self.max_iterations):
            centroids, assign, shift = self._step(xj, centroids)
            if float(shift) < self.tol:
                break
        self.centroids = np.asarray(centroids)
        return self

    def predict(self, x) -> np.ndarray:
        if self.centroids is None:
            raise RuntimeError("call fit() first")
        x = jnp.asarray(np.asarray(x, np.float32))
        _, assign, _ = self._step(x, jnp.asarray(self.centroids))
        return np.asarray(assign)
